"""paddle.metric parity (ref: python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

from ..tensor.tensor import Tensor


class Metric:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self._name

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__(name or "acc")
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred_np = np.asarray(pred._value) if isinstance(pred, Tensor) else np.asarray(pred)
        label_np = np.asarray(label._value) if isinstance(label, Tensor) else np.asarray(label)
        maxk = max(self.topk)
        topk_idx = np.argsort(-pred_np, axis=-1)[..., :maxk]
        if label_np.ndim == pred_np.ndim:
            label_np = label_np.squeeze(-1)
        correct = topk_idx == label_np[..., None]
        return Tensor(np.asarray(correct, np.float32))

    def update(self, correct, *args):
        c = np.asarray(correct._value) if isinstance(correct, Tensor) else np.asarray(correct)
        n = c.shape[0] if c.ndim else 1
        for i, k in enumerate(self.topk):
            self.total[i] += float(c[..., :k].sum())
            self.count[i] += n
        acc = self.total[0] / max(self.count[0], 1)
        return acc

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res


class Precision(Metric):
    def __init__(self, name=None):
        super().__init__(name or "precision")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (np.asarray(preds) > 0.5).astype(np.int32).reshape(-1)
        l = np.asarray(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        return self.tp / max(self.tp + self.fp, 1)


class Recall(Metric):
    def __init__(self, name=None):
        super().__init__(name or "recall")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (np.asarray(preds) > 0.5).astype(np.int32).reshape(-1)
        l = np.asarray(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        return self.tp / max(self.tp + self.fn, 1)


class Auc(Metric):
    """Streaming AUC with histogram buckets (ref metrics.py Auc / fleet metrics.cc)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__(name or "auc")
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        if preds.ndim == 2 and preds.shape[1] == 2:
            pos_prob = preds[:, 1]
        else:
            pos_prob = preds.reshape(-1)
        bins = np.minimum((pos_prob * self.num_thresholds).astype(np.int64), self.num_thresholds)
        for b, l in zip(bins, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            auc += self._stat_pos[i] * (tot_neg + self._stat_neg[i] / 2.0)
            tot_pos += self._stat_pos[i]
            tot_neg += self._stat_neg[i]
        return auc / (tot_pos * tot_neg) if tot_pos and tot_neg else 0.0


def accuracy(input, label, k=1, correct=None, total=None):
    pred = np.asarray(input._value) if isinstance(input, Tensor) else np.asarray(input)
    lbl = np.asarray(label._value) if isinstance(label, Tensor) else np.asarray(label)
    topk = np.argsort(-pred, axis=-1)[..., :k]
    if lbl.ndim == pred.ndim:
        lbl = lbl.squeeze(-1)
    acc = (topk == lbl[..., None]).any(-1).mean()
    return Tensor(np.asarray(acc, np.float32))
