"""Activation layers (ref: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .layers import Layer
from .. import functional as F
from ..initializer import Constant


def _mk(name, fname=None, **default_kwargs):
    fname = fname or name.lower()

    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            kwargs.pop("name", None)
            self._args = args
            self._kwargs = {**default_kwargs, **kwargs}

        def forward(self, x):
            return getattr(F, fname)(x, *self._args, **self._kwargs)

    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


ReLU = _mk("ReLU", "relu")
ReLU6 = _mk("ReLU6", "relu6")
GELU = _mk("GELU", "gelu")
Sigmoid = _mk("Sigmoid", "sigmoid")
Tanh = _mk("Tanh", "tanh")
Softmax = _mk("Softmax", "softmax")
LogSoftmax = _mk("LogSoftmax", "log_softmax")
LogSigmoid = _mk("LogSigmoid", "log_sigmoid")
Softplus = _mk("Softplus", "softplus")
Softsign = _mk("Softsign", "softsign")
Softshrink = _mk("Softshrink", "softshrink")
Hardshrink = _mk("Hardshrink", "hardshrink")
Hardsigmoid = _mk("Hardsigmoid", "hardsigmoid")
Hardswish = _mk("Hardswish", "hardswish")
Hardtanh = _mk("Hardtanh", "hardtanh")
LeakyReLU = _mk("LeakyReLU", "leaky_relu")
ELU = _mk("ELU", "elu")
SELU = _mk("SELU", "selu")
CELU = _mk("CELU", "celu")
Silu = _mk("Silu", "silu")
Swish = _mk("Swish", "swish")
Mish = _mk("Mish", "mish")
Tanhshrink = _mk("Tanhshrink", "tanhshrink")
ThresholdedReLU = _mk("ThresholdedReLU", "thresholded_relu")
Maxout = _mk("Maxout", "maxout")
GLU = _mk("GLU", "glu")
RReLU = _mk("RReLU", "rrelu")


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter([num_parameters], attr=weight_attr,
                                            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)
