"""Norm layers (ref: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import jax.numpy as jnp

from .layers import Layer
from .. import functional as F
from ..initializer import Constant
from ...tensor.tensor import Tensor


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is not False:
            self.weight = self.create_parameter([num_features], attr=weight_attr,
                                                default_initializer=Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True)
        else:
            self.bias = None
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features], jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features], jnp.float32)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum, epsilon=self._epsilon,
            data_format=self._data_format, use_global_stats=self._use_global_stats,
        )

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}, epsilon={self._epsilon}"


class BatchNorm(_BatchNormBase):
    """fluid-style BatchNorm (ref fluid/dygraph/nn.py BatchNorm) — act param supported."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, dtype="float32", data_layout="NCHW",
                 in_place=False, moving_mean_name=None, moving_variance_name=None,
                 do_model_average_for_mean_and_var=True, use_global_stats=False,
                 trainable_statistics=False):
        super().__init__(num_channels, momentum, epsilon, param_attr, bias_attr, data_layout,
                         use_global_stats if use_global_stats else None)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         "NCHW" if data_format == "NCDHW" else data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN.  Under pjit/shard_map the batch axis stats are computed
    globally by XLA when the input is sharded over 'dp' (psum of moments); in eager
    single-process mode it equals BatchNorm.  Ref: nn/layer/norm.py SyncBatchNorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        for l in layer.sublayers(include_self=True):
            for name, sub in list(l._sub_layers.items()):
                if isinstance(sub, _BatchNormBase) and not isinstance(sub, SyncBatchNorm):
                    sbn = SyncBatchNorm(sub._num_features, sub._momentum, sub._epsilon,
                                        data_format=sub._data_format)
                    if sub.weight is not None:
                        sbn.weight.set_value(sub.weight._value)
                    if sub.bias is not None:
                        sbn.bias.set_value(sub.bias._value)
                    sbn._mean.set_value(sub._mean._value)
                    sbn._variance.set_value(sub._variance._value)
                    l._sub_layers[name] = sbn
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        ns = normalized_shape if isinstance(normalized_shape, (list, tuple)) else [normalized_shape]
        self._normalized_shape = list(ns)
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(ns, attr=weight_attr, default_initializer=Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(ns, attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    """LLaMA-family RMSNorm (net-new vs reference snapshot)."""

    def __init__(self, hidden_size, epsilon=1e-6):
        super().__init__()
        self.weight = self.create_parameter([hidden_size], default_initializer=Constant(1.0))
        self._epsilon = epsilon

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is not False:
            self.weight = self.create_parameter([num_channels], attr=weight_attr,
                                                default_initializer=Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter([num_channels], attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight, self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is not False:
            self.scale = self.create_parameter([num_features], attr=weight_attr,
                                               default_initializer=Constant(1.0))
        else:
            self.scale = None
        if bias_attr is not False:
            self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias, eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12, dtype="float32"):
        super().__init__()
        raise NotImplementedError("SpectralNorm layer pending; use functional power iteration")
