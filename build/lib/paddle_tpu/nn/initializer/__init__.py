"""Initializers (ref: python/paddle/nn/initializer/, fluid/initializer.py).

Each initializer mutates a Parameter in place via set_value — randomness from the
global Generator (threefry keys), so `paddle.seed` reproduces inits exactly.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...framework import random as _random
from ...tensor.tensor import Tensor


class Initializer:
    def __call__(self, param, block=None):
        raise NotImplementedError


def _fan_in_out(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    rf = int(np.prod(shape[2:]))
    return shape[1] * rf, shape[0] * rf


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, param, block=None):
        param.set_value(jnp.full(param._value.shape, self.value, param._value.dtype))
        return param


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, param, block=None):
        v = jax.random.normal(_random.get_rng_key(), param._value.shape, jnp.float32)
        param.set_value((v * self.std + self.mean).astype(param._value.dtype))
        return param


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, param, block=None):
        v = jax.random.truncated_normal(_random.get_rng_key(), -2.0, 2.0, param._value.shape, jnp.float32)
        param.set_value((v * self.std + self.mean).astype(param._value.dtype))
        return param


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, param, block=None):
        v = jax.random.uniform(_random.get_rng_key(), param._value.shape, jnp.float32,
                               minval=self.low, maxval=self.high)
        param.set_value(v.astype(param._value.dtype))
        return param


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, param, block=None):
        fi, fo = _fan_in_out(param._value.shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        v = jax.random.normal(_random.get_rng_key(), param._value.shape, jnp.float32) * std
        param.set_value(v.astype(param._value.dtype))
        return param


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, param, block=None):
        fi, fo = _fan_in_out(param._value.shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        v = jax.random.uniform(_random.get_rng_key(), param._value.shape, jnp.float32,
                               minval=-limit, maxval=limit)
        param.set_value(v.astype(param._value.dtype))
        return param


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, param, block=None):
        fi, _ = _fan_in_out(param._value.shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0) if self.nonlinearity == "relu" else math.sqrt(2.0 / (1 + self.negative_slope**2))
        std = gain / math.sqrt(fi)
        v = jax.random.normal(_random.get_rng_key(), param._value.shape, jnp.float32) * std
        param.set_value(v.astype(param._value.dtype))
        return param


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, param, block=None):
        fi, _ = _fan_in_out(param._value.shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0) if self.nonlinearity == "relu" else math.sqrt(2.0 / (1 + self.negative_slope**2))
        limit = gain * math.sqrt(3.0 / fi)
        v = jax.random.uniform(_random.get_rng_key(), param._value.shape, jnp.float32,
                               minval=-limit, maxval=limit)
        param.set_value(v.astype(param._value.dtype))
        return param


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, param, block=None):
        shape = param._value.shape
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        flat = jax.random.normal(_random.get_rng_key(), (max(rows, cols), min(rows, cols)), jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        q = q.T if rows < cols else q
        param.set_value((self.gain * q[:rows, :cols]).reshape(shape).astype(param._value.dtype))
        return param


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, param, block=None):
        v = self.value._value if isinstance(self.value, Tensor) else jnp.asarray(np.asarray(self.value))
        param.set_value(v.astype(param._value.dtype))
        return param


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, param, block=None):
        shape = param._value.shape
        v = np.zeros(shape, np.float32)
        oc, ic = shape[0], shape[1]
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for i in range(min(oc // self.groups, ic)):
                idx = (g * (oc // self.groups) + i, i) + tuple(centers)
                v[idx] = 1.0
        param.set_value(jnp.asarray(v).astype(param._value.dtype))
        return param


# fluid-style aliases (ref fluid/initializer.py)
ConstantInitializer = Constant
NormalInitializer = Normal
UniformInitializer = Uniform
XavierInitializer = XavierNormal
MSRAInitializer = KaimingNormal
TruncatedNormalInitializer = TruncatedNormal

calculate_gain = lambda nonlinearity, param=None: {
    "sigmoid": 1.0,
    "tanh": 5.0 / 3,
    "relu": math.sqrt(2.0),
    "leaky_relu": math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
    "selu": 3.0 / 4,
    "linear": 1.0,
    "conv2d": 1.0,
}.get(nonlinearity, 1.0)


def set_global_initializer(weight_init=None, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init, _global_bias_init = weight_init, bias_init


_global_weight_init = None
_global_bias_init = None
