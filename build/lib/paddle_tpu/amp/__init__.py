"""AMP (ref: python/paddle/amp/auto_cast.py:21, grad_scaler.py:26).

TPU-native: bf16 is the native mixed-precision dtype — no loss scaling needed.  The
O1 autocast white/black lists (ref imperative/amp_auto_cast.h:45 AmpOperators) are
honored by casting inputs of matmul/conv-class ops inside `auto_cast` regions;
`GradScaler` keeps full API parity and becomes a no-op scale=1 path for bf16.
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp

from ..core import dtypes as _dt
from ..tensor.tensor import Tensor

from .grad_scaler import GradScaler, AmpScaler  # noqa: F401

# ops cast to low precision inside autocast (ref fluid/dygraph/amp/auto_cast.py lists)
WHITE_LIST = {"matmul", "mm", "bmm", "conv2d", "conv1d", "conv3d", "linear", "einsum",
              "sdpa", "flash_attention", "addmm"}
BLACK_LIST = {"exp", "square", "log", "mean", "sum", "cos_sim", "softmax",
              "softmax_with_cross_entropy", "sigmoid_cross_entropy_with_logits",
              "cross_entropy", "layer_norm", "batch_norm"}

_amp_state = {"enabled": False, "dtype": None, "level": "O1"}


def amp_state():
    return _amp_state


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None, level="O1",
              dtype="bfloat16"):
    """paddle.amp.auto_cast parity.  On TPU dtype defaults to bfloat16."""
    prev = dict(_amp_state)
    _amp_state.update(
        enabled=bool(enable),
        dtype=_dt.convert_dtype(dtype),
        level=level,
    )
    if custom_white_list:
        WHITE_LIST.update(custom_white_list)
    if custom_black_list:
        BLACK_LIST.update(custom_black_list)
    try:
        yield
    finally:
        _amp_state.update(prev)


autocast = auto_cast


def decorate(models, optimizers=None, level="O1", dtype="bfloat16", master_weight=None,
             save_dtype=None):
    """paddle.amp.decorate parity: O2 casts parameters to the low dtype."""
    d = _dt.convert_dtype(dtype)
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            m._cast_all(d)
    if optimizers is None:
        return models
    return models, optimizers


def maybe_cast_inputs(op_name, raw_args):
    """Hook used by apply_op when autocast is active."""
    if not _amp_state["enabled"]:
        return raw_args
    d = _amp_state["dtype"]
    if op_name in WHITE_LIST:
        return [a.astype(d) if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating) else a
                for a in raw_args]
    if op_name in BLACK_LIST:
        return [a.astype(jnp.float32) if hasattr(a, "dtype") and a.dtype in (jnp.bfloat16, jnp.float16) else a
                for a in raw_args]
    return raw_args


# register the autocast hook on the op-dispatch point
from ..tensor import tensor as _tensor_mod

_tensor_mod._amp_cast_hook = maybe_cast_inputs
_tensor_mod._amp_state_ref = _amp_state


def is_bfloat16_supported(place=None):
    return True


def is_float16_supported(place=None):
    return True
