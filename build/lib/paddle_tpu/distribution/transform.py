"""Random-variable transforms (ref: python/paddle/distribution/transform.py:50 —
the 13-transform library behind TransformedDistribution).

Each transform supplies forward/inverse and forward_log_det_jacobian; all math
is jnp so transforms compose into jitted densities.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor.tensor import Tensor, apply_op

__all__ = [
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
]


def _raw(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


class Transform:
    """Ref transform.py:50."""

    _codomain_event_rank = 0

    def forward(self, x):
        return apply_op(self._forward, (x,), name=f"{type(self).__name__}.fwd")

    def inverse(self, y):
        return apply_op(self._inverse, (y,), name=f"{type(self).__name__}.inv")

    def forward_log_det_jacobian(self, x):
        return apply_op(self._forward_log_det_jacobian, (x,),
                        name=f"{type(self).__name__}.fldj")

    def inverse_log_det_jacobian(self, y):
        return apply_op(
            lambda yv: -self._forward_log_det_jacobian(self._inverse(yv)), (y,),
            name=f"{type(self).__name__}.ildj")

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    # subclass hooks on raw arrays
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError


class AbsTransform(Transform):
    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y  # principal branch, ref AbsTransform.inverse returns positive

    def _forward_log_det_jacobian(self, x):
        return jnp.zeros_like(x)


class AffineTransform(Transform):
    """y = loc + scale * x (ref transform.py:390)."""

    def __init__(self, loc, scale):
        self.loc = _raw(loc)
        self.scale = _raw(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _raw(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(jnp.clip(y, -1 + 1e-7, 1 - 1e-7))

    def _forward_log_det_jacobian(self, x):
        # log(1 - tanh(x)^2) = 2 (log2 - x - softplus(-2x))
        return 2.0 * (jnp.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class ChainTransform(Transform):
    """Composition t_n ∘ ... ∘ t_1 (ref transform.py:467)."""

    def __init__(self, transforms):
        self.transforms = list(transforms)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _forward_log_det_jacobian(self, x):
        total = 0.0
        for t in self.transforms:
            total = total + t._forward_log_det_jacobian(x)
            x = t._forward(x)
        return total

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return shape


class IndependentTransform(Transform):
    """Reinterprets batch dims as event dims (ref transform.py:639)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _forward_log_det_jacobian(self, x):
        ldj = self.base._forward_log_det_jacobian(x)
        return jnp.sum(ldj, axis=tuple(range(-self.rank, 0)))


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        if int(np.prod(self.in_event_shape)) != int(np.prod(self.out_event_shape)):
            raise ValueError("reshape must preserve the event size")

    def _forward(self, x):
        batch = x.shape[: x.ndim - len(self.in_event_shape)]
        return x.reshape(batch + self.out_event_shape)

    def _inverse(self, y):
        batch = y.shape[: y.ndim - len(self.out_event_shape)]
        return y.reshape(batch + self.in_event_shape)

    def _forward_log_det_jacobian(self, x):
        batch = x.shape[: x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch, x.dtype)

    def forward_shape(self, shape):
        n = len(self.in_event_shape)
        return tuple(shape[:-n]) + self.out_event_shape


class SoftmaxTransform(Transform):
    """x -> softmax over the last axis (not bijective; ldj undefined, ref
    transform.py:943 only provides forward/inverse)."""

    def _forward(self, x):
        return jax.nn.softmax(x, -1)

    def _inverse(self, y):
        return jnp.log(y)


class StackTransform(Transform):
    """Applies a different transform to each slice along `axis`
    (ref transform.py:999)."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = axis

    def _map(self, fn_name, v):
        parts = jnp.split(v, len(self.transforms), self.axis)
        outs = [getattr(t, fn_name)(jnp.squeeze(p, self.axis))
                for t, p in zip(self.transforms, parts)]
        return jnp.stack(outs, self.axis)

    def _forward(self, x):
        return self._map("_forward", x)

    def _inverse(self, y):
        return self._map("_inverse", y)

    def _forward_log_det_jacobian(self, x):
        return self._map("_forward_log_det_jacobian", x)


class StickBreakingTransform(Transform):
    """R^{K-1} -> K-simplex via stick breaking (ref transform.py:1104)."""

    _codomain_event_rank = 1

    def _forward(self, x):
        offset = x.shape[-1] - jnp.arange(x.shape[-1], dtype=x.dtype)
        z = jax.nn.sigmoid(x - jnp.log(offset))
        zp = jnp.concatenate([z, jnp.ones(x.shape[:-1] + (1,), x.dtype)], -1)
        one_minus = jnp.concatenate(
            [jnp.ones(x.shape[:-1] + (1,), x.dtype), 1 - z], -1)
        return zp * jnp.cumprod(one_minus, -1)

    def _inverse(self, y):
        y_crop = y[..., :-1]
        rem = 1 - jnp.cumsum(y_crop, -1)
        rem = jnp.concatenate(
            [jnp.ones(y.shape[:-1] + (1,), y.dtype), rem[..., :-1]], -1)
        offset = y_crop.shape[-1] - jnp.arange(y_crop.shape[-1], dtype=y.dtype)
        frac = jnp.clip(y_crop / rem, 1e-10, 1 - 1e-10)
        return jnp.log(frac / (1 - frac)) + jnp.log(offset)

    def _forward_log_det_jacobian(self, x):
        offset = x.shape[-1] - jnp.arange(x.shape[-1], dtype=x.dtype)
        z = jax.nn.sigmoid(x - jnp.log(offset))
        # sum over K-1 sticks of log(z_k (1-z_k) * remaining_k)
        one_minus = jnp.concatenate(
            [jnp.ones(x.shape[:-1] + (1,), x.dtype), 1 - z[..., :-1]], -1)
        rem = jnp.cumprod(one_minus, -1)
        return jnp.sum(jnp.log(z) + jnp.log1p(-z) + jnp.log(rem), -1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)
