"""paddle.distribution parity (ref: python/paddle/distribution/ — the full
15-file zoo: base + exponential family, Beta/Dirichlet/Multinomial/Laplace/
Gumbel/LogNormal, Independent/TransformedDistribution wrappers, the transform
library, and the register_kl multi-dispatch table)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor.tensor import Tensor, apply_op, _unwrap
from ..framework import random as _random
from .kl import register_kl, kl_divergence  # noqa: F401
from .transform import (  # noqa: F401
    Transform, AbsTransform, AffineTransform, ChainTransform, ExpTransform,
    IndependentTransform, PowerTransform, ReshapeTransform, SigmoidTransform,
    SoftmaxTransform, StackTransform, StickBreakingTransform, TanhTransform,
)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return list(self._batch_shape)

    @property
    def event_shape(self):
        return list(self._event_shape)

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from ..tensor.math import exp

        return exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = loc if isinstance(loc, Tensor) else Tensor(jnp.asarray(loc, jnp.float32))
        self.scale = scale if isinstance(scale, Tensor) else Tensor(jnp.asarray(scale, jnp.float32))
        super().__init__(tuple(np.broadcast_shapes(self.loc.shape, self.scale.shape)))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + tuple(self._batch_shape)
        eps = jax.random.normal(_random.get_rng_key(), shape, jnp.float32)
        return Tensor(eps * self.scale._value + self.loc._value)

    def log_prob(self, value):
        def _f(v, loc, scale):
            var = scale * scale
            return -((v - loc) ** 2) / (2 * var) - jnp.log(scale) - 0.5 * math.log(2 * math.pi)

        return apply_op(_f, (value, self.loc, self.scale), name="normal_log_prob")

    def entropy(self):
        def _f(scale):
            return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(scale) + jnp.zeros(self._batch_shape)

        return apply_op(_f, (self.scale,), name="normal_entropy")

    def kl_divergence(self, other):
        def _f(l1, s1, l2, s2):
            vr = (s1 / s2) ** 2
            t1 = ((l1 - l2) / s2) ** 2
            return 0.5 * (vr + t1 - 1 - jnp.log(vr))

        return apply_op(_f, (self.loc, self.scale, other.loc, other.scale), name="normal_kl")


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = low if isinstance(low, Tensor) else Tensor(jnp.asarray(low, jnp.float32))
        self.high = high if isinstance(high, Tensor) else Tensor(jnp.asarray(high, jnp.float32))
        super().__init__(tuple(np.broadcast_shapes(self.low.shape, self.high.shape)))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + tuple(self._batch_shape)
        u = jax.random.uniform(_random.get_rng_key(), shape, jnp.float32)
        return Tensor(u * (self.high._value - self.low._value) + self.low._value)

    def log_prob(self, value):
        def _f(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            return jnp.where(inside, -jnp.log(hi - lo), -jnp.inf)

        return apply_op(_f, (value, self.low, self.high), name="uniform_log_prob")

    def entropy(self):
        return apply_op(lambda lo, hi: jnp.log(hi - lo), (self.low, self.high), name="uniform_entropy")


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = logits if isinstance(logits, Tensor) else Tensor(jnp.asarray(logits, jnp.float32))
        super().__init__(tuple(self.logits.shape[:-1]))

    def sample(self, shape=()):
        n = int(np.prod(shape)) if shape else 1
        out = jax.random.categorical(_random.get_rng_key(), self.logits._value,
                                     shape=tuple(shape) + tuple(self._batch_shape))
        return Tensor(out.astype(jnp.int64))

    def log_prob(self, value):
        def _f(logits, v):
            logp = jax.nn.log_softmax(logits, -1)
            return jnp.take_along_axis(logp, v.astype(jnp.int32)[..., None], -1)[..., 0]

        return apply_op(_f, (self.logits, value), name="categorical_log_prob")

    def probs(self, value=None):
        from ..nn.functional import softmax

        p = softmax(self.logits, axis=-1)
        if value is None:
            return p
        from ..tensor.manipulation import take_along_axis

        return take_along_axis(p, value.unsqueeze(-1), -1).squeeze(-1)

    def entropy(self):
        def _f(logits):
            logp = jax.nn.log_softmax(logits, -1)
            return -jnp.sum(jnp.exp(logp) * logp, -1)

        return apply_op(_f, (self.logits,), name="categorical_entropy")


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = probs if isinstance(probs, Tensor) else Tensor(jnp.asarray(probs, jnp.float32))
        super().__init__(tuple(self.probs_.shape))

    def sample(self, shape=()):
        out = jax.random.bernoulli(_random.get_rng_key(), self.probs_._value,
                                   tuple(shape) + tuple(self._batch_shape))
        return Tensor(out.astype(jnp.float32))

    def log_prob(self, value):
        def _f(p, v):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)

        return apply_op(_f, (self.probs_, value), name="bernoulli_log_prob")


def _as_t(v):
    return v if isinstance(v, Tensor) else Tensor(jnp.asarray(v, jnp.float32))


class ExponentialFamily(Distribution):
    """Ref exponential_family.py — base for Beta/Dirichlet/Gamma-style
    families; entropy via the Bregman identity is replaced by per-family
    closed forms (jax.grad makes the generic route possible but the closed
    forms are exact and cheaper)."""


class Beta(ExponentialFamily):
    """Ref beta.py."""

    def __init__(self, alpha, beta):
        self.alpha = _as_t(alpha)
        self.beta = _as_t(beta)
        super().__init__(tuple(np.broadcast_shapes(self.alpha.shape, self.beta.shape)))

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(self._batch_shape)
        k1, k2 = jax.random.split(_random.get_rng_key())
        ga = jax.random.gamma(k1, self.alpha._value, shape)
        gb = jax.random.gamma(k2, self.beta._value, shape)
        return Tensor(ga / (ga + gb))

    def log_prob(self, value):
        def _f(v, a, b):
            lbeta = (jax.scipy.special.gammaln(a) + jax.scipy.special.gammaln(b)
                     - jax.scipy.special.gammaln(a + b))
            return (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - lbeta

        return apply_op(_f, (value, self.alpha, self.beta), name="beta_log_prob")

    def mean(self):
        return apply_op(lambda a, b: a / (a + b), (self.alpha, self.beta), name="beta_mean")

    def variance(self):
        def _f(a, b):
            s = a + b
            return a * b / (s * s * (s + 1))

        return apply_op(_f, (self.alpha, self.beta), name="beta_var")

    def entropy(self):
        def _f(a, b):
            dg = jax.scipy.special.digamma
            lbeta = (jax.scipy.special.gammaln(a) + jax.scipy.special.gammaln(b)
                     - jax.scipy.special.gammaln(a + b))
            return (lbeta - (a - 1) * dg(a) - (b - 1) * dg(b)
                    + (a + b - 2) * dg(a + b))

        return apply_op(_f, (self.alpha, self.beta), name="beta_entropy")


class Dirichlet(ExponentialFamily):
    """Ref dirichlet.py."""

    def __init__(self, concentration):
        self.concentration = _as_t(concentration)
        super().__init__(tuple(self.concentration.shape[:-1]),
                         tuple(self.concentration.shape[-1:]))

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(self._batch_shape) + tuple(self._event_shape)
        g = jax.random.gamma(_random.get_rng_key(), self.concentration._value, shape)
        return Tensor(g / jnp.sum(g, -1, keepdims=True))

    def log_prob(self, value):
        def _f(v, c):
            lognorm = (jnp.sum(jax.scipy.special.gammaln(c), -1)
                       - jax.scipy.special.gammaln(jnp.sum(c, -1)))
            return jnp.sum((c - 1) * jnp.log(v), -1) - lognorm

        return apply_op(_f, (value, self.concentration), name="dirichlet_log_prob")

    def mean(self):
        return apply_op(lambda c: c / jnp.sum(c, -1, keepdims=True),
                        (self.concentration,), name="dirichlet_mean")

    def entropy(self):
        def _f(c):
            dg = jax.scipy.special.digamma
            c0 = jnp.sum(c, -1)
            k = c.shape[-1]
            lognorm = (jnp.sum(jax.scipy.special.gammaln(c), -1)
                       - jax.scipy.special.gammaln(c0))
            return (lognorm + (c0 - k) * dg(c0)
                    - jnp.sum((c - 1) * dg(c), -1))

        return apply_op(_f, (self.concentration,), name="dirichlet_entropy")


class Multinomial(Distribution):
    """Ref multinomial.py: counts over `total_count` trials."""

    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs = _as_t(probs)
        super().__init__(tuple(self.probs.shape[:-1]), tuple(self.probs.shape[-1:]))

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(self._batch_shape)
        logits = jnp.log(self.probs._value)
        draws = jax.random.categorical(
            _random.get_rng_key(), logits, axis=-1,
            shape=(self.total_count,) + shape)
        k = self.probs._value.shape[-1]
        counts = jax.nn.one_hot(draws, k, dtype=jnp.float32).sum(0)
        return Tensor(counts)

    def log_prob(self, value):
        def _f(v, p):
            gl = jax.scipy.special.gammaln
            logcoef = gl(jnp.asarray(self.total_count + 1.0)) - jnp.sum(gl(v + 1.0), -1)
            return logcoef + jnp.sum(v * jnp.log(p), -1)

        return apply_op(_f, (value, self.probs), name="multinomial_log_prob")

    def mean(self):
        return apply_op(lambda p: self.total_count * p, (self.probs,),
                        name="multinomial_mean")


class Laplace(Distribution):
    """Ref laplace.py."""

    def __init__(self, loc, scale):
        self.loc = _as_t(loc)
        self.scale = _as_t(scale)
        super().__init__(tuple(np.broadcast_shapes(self.loc.shape, self.scale.shape)))

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(self._batch_shape)
        u = jax.random.uniform(_random.get_rng_key(), shape, jnp.float32,
                               minval=-0.5 + 1e-7, maxval=0.5)
        return Tensor(self.loc._value
                      - self.scale._value * jnp.sign(u) * jnp.log1p(-2 * jnp.abs(u)))

    def log_prob(self, value):
        def _f(v, loc, sc):
            return -jnp.abs(v - loc) / sc - jnp.log(2 * sc)

        return apply_op(_f, (value, self.loc, self.scale), name="laplace_log_prob")

    def entropy(self):
        return apply_op(lambda sc: 1 + jnp.log(2 * sc), (self.scale,),
                        name="laplace_entropy")


class Gumbel(Distribution):
    """Ref gumbel.py (reference implements it as TransformedDistribution;
    closed forms are exact here)."""

    def __init__(self, loc, scale):
        self.loc = _as_t(loc)
        self.scale = _as_t(scale)
        super().__init__(tuple(np.broadcast_shapes(self.loc.shape, self.scale.shape)))

    def sample(self, shape=()):
        shape = tuple(shape) + tuple(self._batch_shape)
        g = jax.random.gumbel(_random.get_rng_key(), shape, jnp.float32)
        return Tensor(self.loc._value + self.scale._value * g)

    def log_prob(self, value):
        def _f(v, loc, sc):
            z = (v - loc) / sc
            return -(z + jnp.exp(-z)) - jnp.log(sc)

        return apply_op(_f, (value, self.loc, self.scale), name="gumbel_log_prob")

    def mean(self):
        return apply_op(lambda loc, sc: loc + np.euler_gamma * sc,
                        (self.loc, self.scale), name="gumbel_mean")

    def entropy(self):
        return apply_op(lambda sc: jnp.log(sc) + 1 + np.euler_gamma,
                        (self.scale,), name="gumbel_entropy")


class LogNormal(Distribution):
    """Ref lognormal.py: exp(Normal(loc, scale))."""

    def __init__(self, loc, scale):
        self.loc = _as_t(loc)
        self.scale = _as_t(scale)
        self._base = Normal(self.loc, self.scale)
        super().__init__(tuple(np.broadcast_shapes(self.loc.shape, self.scale.shape)))

    def sample(self, shape=()):
        return Tensor(jnp.exp(self._base.sample(shape)._value))

    def log_prob(self, value):
        def _f(v, loc, sc):
            logv = jnp.log(v)
            var = sc * sc
            return (-((logv - loc) ** 2) / (2 * var) - jnp.log(sc)
                    - 0.5 * math.log(2 * math.pi) - logv)

        return apply_op(_f, (value, self.loc, self.scale), name="lognormal_log_prob")

    def entropy(self):
        return apply_op(
            lambda loc, sc: 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(sc) + loc,
            (self.loc, self.scale), name="lognormal_entropy")


class Independent(Distribution):
    """Ref independent.py: reinterpret rightmost batch dims as event dims."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        bshape = tuple(base.batch_shape)
        super().__init__(bshape[: len(bshape) - self.rank],
                         bshape[len(bshape) - self.rank:] + tuple(base.event_shape))

    def sample(self, shape=()):
        return self.base.sample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)

        def _f(v):
            return jnp.sum(v, axis=tuple(range(-self.rank, 0)))

        return apply_op(_f, (lp,), name="independent_log_prob")

    def entropy(self):
        ent = self.base.entropy()
        return apply_op(lambda v: jnp.sum(v, axis=tuple(range(-self.rank, 0))),
                        (ent,), name="independent_entropy")


class TransformedDistribution(Distribution):
    """Ref transformed_distribution.py: push base samples through transforms,
    correcting densities by the log-det-Jacobian."""

    def __init__(self, base, transforms):
        self.base = base
        self.transforms = list(transforms)
        super().__init__(tuple(base.batch_shape), tuple(base.event_shape))

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        lp = 0.0
        y = value
        for t in reversed(self.transforms):
            x = t.inverse(y)
            ldj = t.forward_log_det_jacobian(x)
            lp = lp - ldj if not isinstance(lp, float) else -ldj
            y = x
        base_lp = self.base.log_prob(y)
        return base_lp + lp if not isinstance(lp, float) else base_lp


# ----------------------------------------------------------------- KL rules
@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    return p.kl_divergence(q)


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    def _f(lp, lq):
        a = jax.nn.log_softmax(lp, -1)
        b = jax.nn.log_softmax(lq, -1)
        return jnp.sum(jnp.exp(a) * (a - b), -1)

    return apply_op(_f, (p.logits, q.logits), name="categorical_kl")


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    def _f(pl, ph, ql, qh):
        inside = (ql <= pl) & (ph <= qh)
        return jnp.where(inside, jnp.log((qh - ql) / (ph - pl)), jnp.inf)

    return apply_op(_f, (p.low, p.high, q.low, q.high), name="uniform_kl")


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli_bernoulli(p, q):
    def _f(a, b):
        a = jnp.clip(a, 1e-7, 1 - 1e-7)
        b = jnp.clip(b, 1e-7, 1 - 1e-7)
        return a * (jnp.log(a) - jnp.log(b)) + (1 - a) * (jnp.log1p(-a) - jnp.log1p(-b))

    return apply_op(_f, (p.probs_, q.probs_), name="bernoulli_kl")


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    def _f(a1, b1, a2, b2):
        gl, dg = jax.scipy.special.gammaln, jax.scipy.special.digamma
        lbeta1 = gl(a1) + gl(b1) - gl(a1 + b1)
        lbeta2 = gl(a2) + gl(b2) - gl(a2 + b2)
        return (lbeta2 - lbeta1 + (a1 - a2) * dg(a1) + (b1 - b2) * dg(b1)
                + (a2 - a1 + b2 - b1) * dg(a1 + b1))

    return apply_op(_f, (p.alpha, p.beta, q.alpha, q.beta), name="beta_kl")


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    def _f(c1, c2):
        gl, dg = jax.scipy.special.gammaln, jax.scipy.special.digamma
        s1 = jnp.sum(c1, -1)
        return (gl(s1) - jnp.sum(gl(c1), -1)
                - gl(jnp.sum(c2, -1)) + jnp.sum(gl(c2), -1)
                + jnp.sum((c1 - c2) * (dg(c1) - dg(s1)[..., None]), -1))

    return apply_op(_f, (p.concentration, q.concentration), name="dirichlet_kl")


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    def _f(l1, s1, l2, s2):
        d = jnp.abs(l1 - l2)
        return (jnp.log(s2 / s1) + d / s2
                + s1 / s2 * jnp.exp(-d / s1) - 1)

    return apply_op(_f, (p.loc, p.scale, q.loc, q.scale), name="laplace_kl")


@register_kl(LogNormal, LogNormal)
def _kl_lognormal_lognormal(p, q):
    return p._base.kl_divergence(q._base)
