"""KL divergence multi-dispatch registry (ref: python/paddle/distribution/kl.py:64
register_kl / kl_divergence)."""
from __future__ import annotations

_REGISTER_TABLE: dict = {}


def register_kl(cls_p, cls_q):
    """Decorator registering a pairwise KL implementation (ref kl.py:64)."""

    def decorator(f):
        _REGISTER_TABLE[cls_p, cls_q] = f
        return f

    return decorator


def _dispatch(type_p, type_q):
    matches = [(sp, sq) for sp, sq in _REGISTER_TABLE
               if issubclass(type_p, sp) and issubclass(type_q, sq)]
    if not matches:
        return None
    # most-derived match wins (the reference sorts by MRO distance similarly)
    def key(pair):
        sp, sq = pair
        return (type_p.__mro__.index(sp), type_q.__mro__.index(sq))

    return _REGISTER_TABLE[min(matches, key=key)]


def kl_divergence(p, q):
    """Ref kl.py kl_divergence: dispatch on (type(p), type(q))."""
    rule = _dispatch(type(p), type(q))
    if rule is None:
        raise NotImplementedError(
            f"no KL rule registered for ({type(p).__name__}, {type(q).__name__}); "
            f"add one with @register_kl({type(p).__name__}, {type(q).__name__})")
    return rule(p, q)
