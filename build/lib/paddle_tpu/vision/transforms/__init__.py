"""vision transforms (ref: python/paddle/vision/transforms/) — numpy/CHW based."""
from __future__ import annotations

import numbers

import numpy as np

from ...tensor.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, img):
        return self._apply_image(img)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        if arr.max() > 1.5:
            arr = arr / 255.0
        if arr.ndim == 2:
            arr = arr[None] if self.data_format == "CHW" else arr[..., None]
        elif arr.ndim == 3 and self.data_format == "CHW" and arr.shape[-1] in (1, 3, 4):
            arr = arr.transpose(2, 0, 1)
        return arr


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        super().__init__(keys)
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            m = self.mean.reshape(-1, 1, 1) if self.mean.ndim else self.mean
            s = self.std.reshape(-1, 1, 1) if self.std.ndim else self.std
        else:
            m, s = self.mean, self.std
        return (arr - m) / s


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        import jax

        arr = np.asarray(img, np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if chw:
            out_shape = (arr.shape[0], *self.size)
        elif arr.ndim == 3:
            out_shape = (*self.size, arr.shape[-1])
        else:
            out_shape = self.size
        return np.asarray(jax.image.resize(arr, out_shape, method="bilinear"))


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        h_axis, w_axis = (1, 2) if (arr.ndim == 3 and arr.shape[0] in (1, 3, 4)) else (0, 1)
        h, w = arr.shape[h_axis], arr.shape[w_axis]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        sl = [slice(None)] * arr.ndim
        sl[h_axis] = slice(i, i + th)
        sl[w_axis] = slice(j, j + tw)
        return arr[tuple(sl)]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = np.asarray(img)
        h_axis, w_axis = (1, 2) if (arr.ndim == 3 and arr.shape[0] in (1, 3, 4)) else (0, 1)
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) else [self.padding] * 2
            pad = [(0, 0)] * arr.ndim
            pad[h_axis] = (p[0], p[0])
            pad[w_axis] = (p[1], p[1])
            arr = np.pad(arr, pad)
        h, w = arr.shape[h_axis], arr.shape[w_axis]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        sl = [slice(None)] * arr.ndim
        sl[h_axis] = slice(i, i + th)
        sl[w_axis] = slice(j, j + tw)
        return arr[tuple(sl)]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        arr = np.asarray(img)
        if np.random.rand() < self.prob:
            w_axis = 2 if (arr.ndim == 3 and arr.shape[0] in (1, 3, 4)) else 1
            arr = np.flip(arr, axis=w_axis).copy()
        return arr


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3), interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        h_axis, w_axis = (1, 2) if chw else (0, 1)
        h, w = arr.shape[h_axis], arr.shape[w_axis]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = np.random.randint(0, h - th + 1)
                j = np.random.randint(0, w - tw + 1)
                sl = [slice(None)] * arr.ndim
                sl[h_axis] = slice(i, i + th)
                sl[w_axis] = slice(j, j + tw)
                arr = arr[tuple(sl)]
                break
        return Resize(self.size)._apply_image(arr)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[..., None]
        return arr.transpose(self.order)


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    arr = np.asarray(img)
    w_axis = 2 if (arr.ndim == 3 and arr.shape[0] in (1, 3, 4)) else 1
    return np.flip(arr, axis=w_axis).copy()
