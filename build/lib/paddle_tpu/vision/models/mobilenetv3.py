"""MobileNetV3 (ref: python/paddle/vision/models/mobilenetv3.py:166; also the
OCR det/rec backbone in BASELINE config #3)."""
from __future__ import annotations

from ... import nn
from .mobilenetv2 import _make_divisible


class SqueezeExcitation(nn.Layer):
    """SE block with hardsigmoid gate (ref mobilenetv3.py:39)."""

    def __init__(self, channels, squeeze_channels):
        super().__init__()
        self.avgpool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(channels, squeeze_channels, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(squeeze_channels, channels, 1)
        self.hardsigmoid = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hardsigmoid(self.fc2(self.relu(self.fc1(self.avgpool(x)))))
        return x * s


def _conv_bn_act(in_c, out_c, kernel, stride=1, groups=1, act="hardswish"):
    pad = (kernel - 1) // 2
    layers = [nn.Conv2D(in_c, out_c, kernel, stride=stride, padding=pad,
                        groups=groups, bias_attr=False),
              nn.BatchNorm2D(out_c)]
    if act == "relu":
        layers.append(nn.ReLU())
    elif act == "hardswish":
        layers.append(nn.Hardswish())
    return nn.Sequential(*layers)


class InvertedResidual(nn.Layer):
    """ref mobilenetv3.py:110: expand -> dw -> (SE) -> project."""

    def __init__(self, in_c, expand_c, out_c, kernel, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expand_c != in_c:
            layers.append(_conv_bn_act(in_c, expand_c, 1, act=act))
        layers.append(_conv_bn_act(expand_c, expand_c, kernel, stride=stride,
                                   groups=expand_c, act=act))
        if use_se:
            layers.append(SqueezeExcitation(expand_c,
                                            _make_divisible(expand_c // 4)))
        layers.append(_conv_bn_act(expand_c, out_c, 1, act=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


# (kernel, expand, out, use_se, act, stride) — ref mobilenetv3.py:251,302 configs
_SMALL = [
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1), (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1), (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2), (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]
_LARGE = [
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2), (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1), (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1), (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2), (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]


class MobileNetV3(nn.Layer):
    def __init__(self, config, last_channel, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_c = _make_divisible(16 * scale)
        blocks = [_conv_bn_act(3, in_c, 3, stride=2, act="hardswish")]
        for kernel, expand, out, use_se, act, stride in config:
            exp_c = _make_divisible(expand * scale)
            out_c = _make_divisible(out * scale)
            blocks.append(InvertedResidual(in_c, exp_c, out_c, kernel, stride,
                                           use_se, act))
            in_c = out_c
        last_conv = _make_divisible(6 * in_c)
        blocks.append(_conv_bn_act(in_c, last_conv, 1, act="hardswish"))
        self.features = nn.Sequential(*blocks)
        self._feat_channels = last_conv
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_conv, last_channel), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_channel, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL, _make_divisible(1024 * scale), scale,
                         num_classes, with_pool)


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE, _make_divisible(1280 * scale), scale,
                         num_classes, with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return MobileNetV3Large(scale=scale, **kwargs)
