"""SqueezeNet 1.0/1.1 (ref: python/paddle/vision/models/squeezenet.py:76)."""
from __future__ import annotations

import paddle_tpu as paddle
from ... import nn


class Fire(nn.Layer):
    """squeeze 1x1 -> parallel expand 1x1 + expand 3x3, concat
    (ref squeezenet.py:57 MakeFire)."""

    def __init__(self, in_c, squeeze_c, e1_c, e3_c):
        super().__init__()
        self.squeeze = nn.Sequential(nn.Conv2D(in_c, squeeze_c, 1), nn.ReLU())
        self.expand1 = nn.Sequential(nn.Conv2D(squeeze_c, e1_c, 1), nn.ReLU())
        self.expand3 = nn.Sequential(nn.Conv2D(squeeze_c, e3_c, 3, padding=1),
                                     nn.ReLU())

    def forward(self, x):
        s = self.squeeze(x)
        return paddle.concat([self.expand1(s), self.expand3(s)], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        if version not in ("1.0", "1.1"):
            raise ValueError(f"unsupported SqueezeNet version {version!r}")
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                Fire(96, 16, 64, 64), Fire(128, 16, 64, 64), Fire(128, 32, 128, 128),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                Fire(256, 32, 128, 128), Fire(256, 48, 192, 192),
                Fire(384, 48, 192, 192), Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                Fire(64, 16, 64, 64), Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                Fire(128, 32, 128, 128), Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, stride=2, ceil_mode=True),
                Fire(256, 48, 192, 192), Fire(384, 48, 192, 192),
                Fire(384, 64, 256, 256), Fire(512, 64, 256, 256))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.5),
                nn.Conv2D(512, num_classes, 1), nn.ReLU(),
                nn.AdaptiveAvgPool2D(1))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x).flatten(1)
        elif self.with_pool:
            x = paddle.nn.functional.adaptive_avg_pool2d(x, 1)
        return x


def _squeezenet(version, pretrained, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return SqueezeNet(version=version, **kwargs)


def squeezenet1_0(pretrained=False, **kwargs):
    return _squeezenet("1.0", pretrained, **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return _squeezenet("1.1", pretrained, **kwargs)
