"""vision model zoo (ref: python/paddle/vision/models/__init__.py — all 13
families the reference ships, plus ViT).  Modules import lazily to keep the
top-level `import paddle_tpu` light."""
from .lenet import LeNet  # noqa: F401

_LAZY = {
    "resnet": ("ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
               "resnet152", "resnext50_32x4d", "resnext50_64x4d",
               "resnext101_32x4d", "resnext101_64x4d", "resnext152_32x4d",
               "resnext152_64x4d", "wide_resnet50_2", "wide_resnet101_2"),
    "vgg": ("VGG", "vgg11", "vgg13", "vgg16", "vgg19"),
    "alexnet": ("AlexNet", "alexnet"),
    "mobilenetv1": ("MobileNetV1", "mobilenet_v1"),
    "mobilenetv2": ("MobileNetV2", "mobilenet_v2"),
    "mobilenetv3": ("MobileNetV3", "MobileNetV3Small", "MobileNetV3Large",
                    "mobilenet_v3_small", "mobilenet_v3_large"),
    "densenet": ("DenseNet", "densenet121", "densenet161", "densenet169",
                 "densenet201", "densenet264"),
    "googlenet": ("GoogLeNet", "googlenet"),
    "inceptionv3": ("InceptionV3", "inception_v3"),
    "shufflenetv2": ("ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
                     "shufflenet_v2_x0_5", "shufflenet_v2_x1_0",
                     "shufflenet_v2_x1_5", "shufflenet_v2_x2_0",
                     "shufflenet_v2_swish"),
    "squeezenet": ("SqueezeNet", "squeezenet1_0", "squeezenet1_1"),
    "vit": ("VisionTransformer", "vit_b_16", "vit_b_32", "vit_l_16"),
}
_NAME_TO_MODULE = {name: mod for mod, names in _LAZY.items() for name in names}

__all__ = ["LeNet", *_NAME_TO_MODULE]


def __getattr__(name):
    mod = _NAME_TO_MODULE.get(name)
    if mod is None:
        raise AttributeError(name)
    import importlib

    module = importlib.import_module(f".{mod}", __name__)
    # cache ALL of the module's exported names: importing `.alexnet` binds the
    # submodule as a package attribute, which would otherwise shadow the
    # same-named `alexnet` factory whichever exported name is accessed first
    for n in _LAZY[mod]:
        globals()[n] = getattr(module, n)
    return globals()[name]
