"""Vision Transformer (BASELINE config #2 ViT-base; ref: PaddleClas ViT and the
reference's nn.TransformerEncoder building blocks)."""
from __future__ import annotations

from ... import nn
from ...tensor.tensor import Parameter
from ...tensor import manipulation as M
import jax.numpy as jnp


class PatchEmbed(nn.Layer):
    def __init__(self, img_size=224, patch_size=16, in_chans=3, embed_dim=768):
        super().__init__()
        self.num_patches = (img_size // patch_size) ** 2
        self.proj = nn.Conv2D(in_chans, embed_dim, patch_size, stride=patch_size)

    def forward(self, x):
        x = self.proj(x)  # [B, C, H/p, W/p]
        B, C = x.shape[0], x.shape[1]
        x = M.reshape(x, [B, C, -1])
        return M.transpose(x, [0, 2, 1])  # [B, N, C]


class MLP(nn.Layer):
    def __init__(self, dim, hidden, drop=0.0):
        super().__init__()
        self.fc1 = nn.Linear(dim, hidden)
        self.act = nn.GELU()
        self.fc2 = nn.Linear(hidden, dim)
        self.drop = nn.Dropout(drop)

    def forward(self, x):
        return self.drop(self.fc2(self.drop(self.act(self.fc1(x)))))


class Block(nn.Layer):
    def __init__(self, dim, num_heads, mlp_ratio=4.0, drop=0.0, attn_drop=0.0):
        super().__init__()
        self.norm1 = nn.LayerNorm(dim, epsilon=1e-6)
        self.attn = nn.MultiHeadAttention(dim, num_heads, dropout=attn_drop)
        self.norm2 = nn.LayerNorm(dim, epsilon=1e-6)
        self.mlp = MLP(dim, int(dim * mlp_ratio), drop)

    def forward(self, x):
        y = self.norm1(x)
        x = x + self.attn(y, y, y)
        x = x + self.mlp(self.norm2(x))
        return x


class VisionTransformer(nn.Layer):
    def __init__(self, img_size=224, patch_size=16, in_chans=3, num_classes=1000,
                 embed_dim=768, depth=12, num_heads=12, mlp_ratio=4.0, drop_rate=0.0,
                 attn_drop_rate=0.0, **kwargs):
        super().__init__()
        self.num_classes = num_classes
        self.patch_embed = PatchEmbed(img_size, patch_size, in_chans, embed_dim)
        n = self.patch_embed.num_patches
        self.cls_token = Parameter(jnp.zeros([1, 1, embed_dim], jnp.float32))
        # drawn from the framework RNG so paddle.seed() reproduces construction
        import jax as _jax
        from ...framework import random as _random

        self.pos_embed = Parameter(
            _jax.random.normal(_random.get_rng_key(), (1, n + 1, embed_dim), jnp.float32) * 0.02
        )
        self.pos_drop = nn.Dropout(drop_rate)
        self.blocks = nn.LayerList([
            Block(embed_dim, num_heads, mlp_ratio, drop_rate, attn_drop_rate)
            for _ in range(depth)
        ])
        self.norm = nn.LayerNorm(embed_dim, epsilon=1e-6)
        self.head = nn.Linear(embed_dim, num_classes) if num_classes > 0 else nn.Identity()

    def forward(self, x):
        x = self.patch_embed(x)
        B = x.shape[0]
        cls = M.expand(self.cls_token, [B, 1, x.shape[2]])
        x = M.concat([cls, x], axis=1)
        x = self.pos_drop(x + self.pos_embed)
        for blk in self.blocks:
            x = blk(x)
        x = self.norm(x)
        return self.head(x[:, 0])


def vit_b_16(**kwargs):
    return VisionTransformer(patch_size=16, embed_dim=768, depth=12, num_heads=12, **kwargs)


def vit_b_32(**kwargs):
    return VisionTransformer(patch_size=32, embed_dim=768, depth=12, num_heads=12, **kwargs)


def vit_l_16(**kwargs):
    return VisionTransformer(patch_size=16, embed_dim=1024, depth=24, num_heads=16, **kwargs)
