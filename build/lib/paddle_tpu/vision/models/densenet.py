"""DenseNet family (ref: python/paddle/vision/models/densenet.py:186)."""
from __future__ import annotations

from ... import nn
import paddle_tpu as _paddle

_ARCH = {
    121: (32, [6, 12, 24, 16], 64),
    161: (48, [6, 12, 36, 24], 96),
    169: (32, [6, 12, 32, 32], 64),
    201: (32, [6, 12, 48, 32], 64),
    264: (32, [6, 12, 64, 48], 64),
}


class DenseLayer(nn.Layer):
    """Pre-activation BN-ReLU-Conv1x1 -> BN-ReLU-Conv3x3, concat input
    (ref densenet.py:78 with bn_size=4)."""

    def __init__(self, in_c, growth_rate, bn_size=4, dropout=0.0):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(in_c)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(in_c, bn_size * growth_rate, 1, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3, padding=1,
                               bias_attr=False)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return _paddle.concat([x, out], axis=1)


class DenseBlock(nn.Layer):
    def __init__(self, in_c, growth_rate, num_layers, bn_size=4, dropout=0.0):
        super().__init__()
        self.layers = nn.LayerList([
            DenseLayer(in_c + i * growth_rate, growth_rate, bn_size, dropout)
            for i in range(num_layers)])
        self.out_channels = in_c + num_layers * growth_rate

    def forward(self, x):
        for layer in self.layers:
            x = layer(x)
        return x


class TransitionLayer(nn.Layer):
    def __init__(self, in_c, out_c):
        super().__init__()
        self.bn = nn.BatchNorm2D(in_c)
        self.relu = nn.ReLU()
        self.conv = nn.Conv2D(in_c, out_c, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        if layers not in _ARCH:
            raise ValueError(f"DenseNet layers must be one of {sorted(_ARCH)}")
        growth_rate, block_config, num_init = _ARCH[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, num_init, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(num_init), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1))
        blocks = []
        c = num_init
        for i, n in enumerate(block_config):
            block = DenseBlock(c, growth_rate, n, bn_size, dropout)
            blocks.append(block)
            c = block.out_channels
            if i != len(block_config) - 1:
                blocks.append(TransitionLayer(c, c // 2))
                c = c // 2
        self.blocks = nn.Sequential(*blocks)
        self.bn_last = nn.BatchNorm2D(c)
        self.relu_last = nn.ReLU()
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(c, num_classes)

    def forward(self, x):
        x = self.relu_last(self.bn_last(self.blocks(self.stem(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


def _densenet(layers, pretrained, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)
