"""MobileNetV1 (ref: python/paddle/vision/models/mobilenetv1.py:56)."""
from __future__ import annotations

from ... import nn


def _conv_bn(in_c, out_c, kernel, stride=1, padding=0, groups=1):
    return nn.Sequential(
        nn.Conv2D(in_c, out_c, kernel, stride=stride, padding=padding,
                  groups=groups, bias_attr=False),
        nn.BatchNorm2D(out_c),
        nn.ReLU(),
    )


class DepthwiseSeparable(nn.Layer):
    """Depthwise 3x3 + pointwise 1x1 (ref mobilenetv1.py:30)."""

    def __init__(self, in_c, out_c1, out_c2, stride, scale):
        super().__init__()
        c1, c2 = int(out_c1 * scale), int(out_c2 * scale)
        self.depthwise = _conv_bn(int(in_c * scale), c1, 3, stride=stride,
                                  padding=1, groups=int(in_c * scale))
        self.pointwise = _conv_bn(c1, c2, 1)

    def forward(self, x):
        return self.pointwise(self.depthwise(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = _conv_bn(3, int(32 * scale), 3, stride=2, padding=1)
        # (in, c1, c2, stride) per block — the standard 13-block stack
        cfg = [(32, 32, 64, 1), (64, 64, 128, 2), (128, 128, 128, 1),
               (128, 128, 256, 2), (256, 256, 256, 1), (256, 256, 512, 2),
               *[(512, 512, 512, 1)] * 5,
               (512, 512, 1024, 2), (1024, 1024, 1024, 1)]
        self.blocks = nn.Sequential(*[DepthwiseSeparable(i, a, b, s, scale)
                                      for i, a, b, s in cfg])
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(int(1024 * scale), num_classes)

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return MobileNetV1(scale=scale, **kwargs)
