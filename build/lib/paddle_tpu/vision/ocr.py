"""OCR pipeline building blocks (BASELINE config #3: PP-OCRv4-style det+rec).

The reference framework repo carries only the primitives (warpctc kernel,
conv/lstm ops); the det/rec model shapes follow the public PP-OCR design:
DB (Differentiable Binarization) text detection over a MobileNetV3 FPN, and a
CRNN-style CTC recognizer.  TPU-specific: variable-size images go through a
width-bucketing policy (SURVEY §7.3.4) so XLA compiles one program per bucket,
not per image size.
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle
from .. import nn
from ..nn import functional as F
from .models.mobilenetv3 import MobileNetV3Small, MobileNetV3Large


# --------------------------------------------------------------- det backbone
class MobileNetV3Backbone(nn.Layer):
    """MobileNetV3 trunk returning the 4 deepest scale features
    (strides 4/8/16/32 for a /32-stride net)."""

    def __init__(self, scale=0.5, arch="small"):
        super().__init__()
        cls = MobileNetV3Small if arch == "small" else MobileNetV3Large
        self.blocks = cls(scale=scale, num_classes=0, with_pool=False).features

    def forward(self, x):
        feats = []
        out = x
        for block in self.blocks:
            new = block(out)
            if new.shape[2] != out.shape[2]:
                feats.append(out)     # finest map at the previous stride
            out = new
        feats.append(out)
        return feats[-4:]             # strides 4, 8, 16, 32

    def out_channels(self, in_hw=64):
        import jax.numpy as jnp
        from ..tensor.tensor import Tensor
        import jax

        # eval mode: train-mode BN would write traced running stats into the
        # buffers during the shape-only trace (a tracer leak)
        was_training = self.training
        self.eval()
        try:
            dummy = jax.eval_shape(
                lambda v: [f._value for f in self.forward(Tensor(v))],
                jax.ShapeDtypeStruct((1, 3, in_hw, in_hw), jnp.float32))
        finally:
            if was_training:
                self.train()
        return [s.shape[1] for s in dummy]


class DBFPN(nn.Layer):
    """DB neck: lateral 1x1 + top-down adds, smooth to out_ch//4, concat at
    stride 4 (the public DBNet neck shape)."""

    def __init__(self, in_channels, out_channels=96):
        super().__init__()
        self.laterals = nn.LayerList([
            nn.Conv2D(c, out_channels, 1, bias_attr=False) for c in in_channels])
        self.smooths = nn.LayerList([
            nn.Conv2D(out_channels, out_channels // 4, 3, padding=1,
                      bias_attr=False) for _ in in_channels])
        self.out_channels = out_channels

    def forward(self, feats):
        laterals = [lat(f) for lat, f in zip(self.laterals, feats)]
        for i in range(len(laterals) - 1, 0, -1):
            up = F.interpolate(laterals[i], scale_factor=2, mode="nearest")
            laterals[i - 1] = laterals[i - 1] + up
        outs = []
        for i, (smooth, lat) in enumerate(zip(self.smooths, laterals)):
            o = smooth(lat)
            if i > 0:
                o = F.interpolate(o, scale_factor=2 ** i, mode="nearest")
            outs.append(o)
        return paddle.concat(outs, axis=1)


class DBHead(nn.Layer):
    """DB head: probability map P, threshold map T, and the differentiable
    binarization  B = sigmoid(k * (P - T))  with k=50."""

    def __init__(self, in_channels, k=50):
        super().__init__()
        self.k = k

        def branch():
            c = in_channels
            return nn.Sequential(
                nn.Conv2D(c, c // 4, 3, padding=1, bias_attr=False),
                nn.BatchNorm2D(c // 4), nn.ReLU(),
                nn.Conv2DTranspose(c // 4, c // 4, 2, stride=2),
                nn.BatchNorm2D(c // 4), nn.ReLU(),
                nn.Conv2DTranspose(c // 4, 1, 2, stride=2),
                nn.Sigmoid())

        self.prob = branch()
        self.thresh = branch()

    def forward(self, x):
        p = self.prob(x)
        t = self.thresh(x)
        b = F.sigmoid(self.k * (p - t))
        return {"maps": paddle.concat([p, t, b], axis=1),
                "prob": p, "thresh": t, "binary": b}


class DBNet(nn.Layer):
    """Backbone + FPN + DB head; maps come out at input/1 resolution
    (stride-4 fuse upsampled x4 by the head's transpose convs)."""

    def __init__(self, backbone_scale=0.5, arch="small", neck_channels=96):
        super().__init__()
        self.backbone = MobileNetV3Backbone(scale=backbone_scale, arch=arch)
        self.neck = DBFPN(self.backbone.out_channels(), neck_channels)
        self.head = DBHead(neck_channels)

    def forward(self, x):
        return self.head(self.neck(self.backbone(x)))


def _dice_loss(pred, gt, mask, eps=1e-6):
    inter = paddle.sum(pred * gt * mask)
    union = paddle.sum(pred * pred * mask) + paddle.sum(gt * gt * mask) + eps
    return 1.0 - 2.0 * inter / union


def db_loss(pred, shrink_map, shrink_mask, thresh_map=None, thresh_mask=None,
            alpha=5.0, beta=10.0, ohem_ratio=3.0):
    """DB training loss: balanced BCE on P, masked L1 on T, dice on B.

    Balancing is by pos/neg weighting (a traced-shape-friendly stand-in for the
    reference-era OHEM top-k, which needs dynamic k)."""
    p = pred["prob"][:, 0]
    b = pred["binary"][:, 0]
    pos = shrink_map * shrink_mask
    neg = (1.0 - shrink_map) * shrink_mask
    n_pos = paddle.sum(pos) + 1.0
    n_neg = paddle.sum(neg) + 1.0
    w = pos * (1.0 / n_pos) + neg * (1.0 / paddle.maximum(
        n_neg / ohem_ratio, n_pos))
    eps = 1e-6
    bce = -(shrink_map * paddle.log(p + eps)
            + (1.0 - shrink_map) * paddle.log(1.0 - p + eps))
    loss_p = paddle.sum(bce * w) / paddle.sum(w)
    loss_b = _dice_loss(b, shrink_map, shrink_mask)
    loss = alpha * loss_p + loss_b
    if thresh_map is not None:
        tm = thresh_mask if thresh_mask is not None else paddle.ones_like(thresh_map)
        l1 = paddle.sum(paddle.abs(pred["thresh"][:, 0] - thresh_map) * tm) / (
            paddle.sum(tm) + eps)
        loss = loss + beta * l1
    return loss


# ------------------------------------------------------------------ rec model
class CRNN(nn.Layer):
    """CTC recognizer: conv trunk squeezing H to 1, BiLSTM neck, linear head.

    Input (N, 3, 32, W) -> logits (N, W/4, num_classes); feed transposed
    [T, N, C] into F.ctc_loss (ref phi WarpctcKernel layout)."""

    def __init__(self, num_classes, hidden_size=48, channels=(32, 64, 128, 128)):
        super().__init__()
        c0, c1, c2, c3 = channels

        def cbr(i, o):
            return nn.Sequential(nn.Conv2D(i, o, 3, padding=1, bias_attr=False),
                                 nn.BatchNorm2D(o), nn.ReLU())

        self.conv = nn.Sequential(
            cbr(3, c0), nn.MaxPool2D(2, stride=2),            # H/2,  W/2
            cbr(c0, c1), nn.MaxPool2D(2, stride=2),           # H/4,  W/4
            cbr(c1, c2), nn.MaxPool2D((2, 1), stride=(2, 1)),  # H/8,  W/4
            cbr(c2, c3), nn.MaxPool2D((2, 1), stride=(2, 1)),  # H/16, W/4
            nn.Conv2D(c3, c3, (2, 1), bias_attr=False),       # H/32 -> 1
            nn.BatchNorm2D(c3), nn.ReLU(),
        )
        self.rnn = nn.LSTM(c3, hidden_size, direction="bidirect")
        self.fc = nn.Linear(2 * hidden_size, num_classes)

    def forward(self, x):
        f = self.conv(x)                       # (N, C, 1, T)
        f = paddle.squeeze(f, axis=2)          # (N, C, T)
        f = paddle.transpose(f, [0, 2, 1])     # (N, T, C)
        out, _ = self.rnn(f)
        return self.fc(out)                    # (N, T, num_classes)


def crnn_ctc_loss(logits, labels, label_lengths, blank=0):
    """Convenience: (N, T, C) logits -> mean CTC loss (all T frames valid)."""
    n, t, _ = logits.shape
    log_probs = F.log_softmax(paddle.transpose(logits, [1, 0, 2]), axis=-1)
    input_lengths = paddle.to_tensor(np.full((n,), t, np.int64))
    return F.ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=blank)


def ctc_greedy_decode(logits, blank=0):
    """Best-path decode: argmax per frame, collapse repeats, drop blanks.
    Host-side (numpy) — decoding is post-processing, not a traced op."""
    ids = np.asarray(paddle.argmax(logits, axis=-1)._value)
    out = []
    for seq in ids:
        collapsed = []
        prev = -1
        for s in seq:
            if s != prev and s != blank:
                collapsed.append(int(s))
            prev = s
        out.append(collapsed)
    return out


# ------------------------------------------------------------------ bucketing
DEFAULT_WIDTH_BUCKETS = (64, 96, 128, 192, 256, 320, 480, 640)


def bucket_width(w, buckets=DEFAULT_WIDTH_BUCKETS):
    """Smallest bucket >= w (clamped to the largest) — bounds the number of
    distinct compiled shapes for variable-width OCR crops."""
    for b in buckets:
        if w <= b:
            return b
    return buckets[-1]


def pad_to_width(img, width):
    """Right-pad (N)CHW or CHW image(s) to `width` with zeros; wider images are
    resized down to fit (aspect preserved by the caller's resize policy)."""
    arr = np.asarray(img)
    w = arr.shape[-1]
    if w == width:
        return arr
    if w > width:
        idx = np.linspace(0, w - 1, width).round().astype(int)
        return arr[..., idx]
    pad = [(0, 0)] * (arr.ndim - 1) + [(0, width - w)]
    return np.pad(arr, pad)


class WidthBucketBatchSampler:
    """Groups sample indices by bucketed width so every batch pads to ONE
    width (one XLA program per bucket, ref §7.3.4 dynamic-shape policy).

    `widths` is a sequence (or callable idx->width) of raw image widths."""

    def __init__(self, widths, batch_size, buckets=DEFAULT_WIDTH_BUCKETS,
                 shuffle=True, seed=0, drop_last=False):
        n = len(widths)
        self.batch_size = batch_size
        self.buckets = tuple(buckets)
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self._by_bucket: dict[int, list[int]] = {}
        for i in range(n):
            w = widths(i) if callable(widths) else widths[i]
            self._by_bucket.setdefault(bucket_width(w, self.buckets), []).append(i)
        self._epoch = 0

    def __iter__(self):
        rng = np.random.RandomState(self.seed + self._epoch)
        self._epoch += 1
        batches = []
        for bucket, idxs in sorted(self._by_bucket.items()):
            idxs = list(idxs)
            if self.shuffle:
                rng.shuffle(idxs)
            for i in range(0, len(idxs), self.batch_size):
                chunk = idxs[i:i + self.batch_size]
                if self.drop_last and len(chunk) < self.batch_size:
                    continue
                batches.append((bucket, chunk))
        if self.shuffle:
            rng.shuffle(batches)
        for bucket, chunk in batches:
            yield bucket, chunk

    def __len__(self):
        total = 0
        for idxs in self._by_bucket.values():
            q, r = divmod(len(idxs), self.batch_size)
            total += q + (0 if (self.drop_last or r == 0) else 1)
        return total
