"""PyLayer: user-defined forward/backward (ref: python/paddle/autograd/py_layer.py,
C++ glue fluid/eager/pylayer/).  Implemented directly on the tape: forward runs
eagerly, and a TapeNode is recorded whose vjp calls the user's backward."""
from __future__ import annotations

from ..tensor.tensor import Tensor
from . import tape


class PyLayerContext:
    def __init__(self):
        self._saved = []
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    def saved_tensor(self):
        return self._saved

    saved_tensors = saved_tensor


class PyLayer:
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with tape.no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outs, (tuple, list))
        outs_t = (outs,) if single else tuple(outs)
        outs_t = tuple(o if isinstance(o, Tensor) else o for o in outs_t)

        tensor_inputs = [a for a in args if isinstance(a, Tensor) and not a.stop_gradient]
        if tape.is_grad_enabled() and tensor_inputs:
            def vjp_fn(cotangents):
                cts = cotangents if isinstance(cotangents, tuple) else (cotangents,)
                ct_tensors = tuple(Tensor(c, stop_gradient=True) for c in cts)
                with tape.no_grad():
                    grads = cls.backward(ctx, *ct_tensors)
                grads = (grads,) if isinstance(grads, Tensor) or grads is None else tuple(grads)
                out = []
                gi = 0
                for a in args:
                    if isinstance(a, Tensor) and not a.stop_gradient:
                        g = grads[gi] if gi < len(grads) else None
                        out.append(g._value if isinstance(g, Tensor) else g)
                    if isinstance(a, Tensor):
                        gi += 1
                return tuple(out)

            avals = [(tuple(o._value.shape), o._value.dtype) for o in outs_t if isinstance(o, Tensor)]
            node = tape.TapeNode(vjp_fn, tensor_inputs, avals, name=cls.__name__)
            for i, o in enumerate(outs_t):
                if isinstance(o, Tensor):
                    o._node = node
                    o._out_index = i
                    o.stop_gradient = False
        return outs_t[0] if single else outs_t


PyLayerMeta = type(PyLayer)
