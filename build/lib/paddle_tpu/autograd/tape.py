"""Eager autograd tape.

Reference design: the eager autograd engine (`/root/reference/paddle/fluid/eager/`):
`GradNodeBase`+`Edge` (`grad_node_info.h:50,168`), `GradTensorHolder` accumulation and
the topological `RunBackward` loop (`backward.cc:556,666-700`).

TPU-native design: instead of per-op hand-written GradNodes, every primitive op call
obtains its VJP from `jax.vjp` at call time (trace-based AD — the JAX way), and records
one `TapeNode` holding the vjp closure.  `backward()` runs the same in-degree-counted
topological walk the reference uses.  Because the vjp closures are themselves pure JAX
functions, the whole tape degrades gracefully under `jax.jit` tracing (used by
`to_static`), where XLA fuses forward+backward into one program.
"""
from __future__ import annotations

import contextlib
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

# ----------------------------------------------------------------------------- grad mode

_grad_enabled: bool = True


def is_grad_enabled() -> bool:
    return _grad_enabled


def set_grad_enabled(mode: bool):
    global _grad_enabled
    _grad_enabled = bool(mode)


class no_grad(contextlib.ContextDecorator):
    """paddle.no_grad parity (context manager AND decorator)."""

    def __enter__(self):
        global _grad_enabled
        self._prev = _grad_enabled
        _grad_enabled = False
        return self

    def __exit__(self, *exc):
        global _grad_enabled
        _grad_enabled = self._prev
        return False


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        global _grad_enabled
        self._prev = _grad_enabled
        _grad_enabled = True
        return self

    def __exit__(self, *exc):
        global _grad_enabled
        _grad_enabled = self._prev
        return False


# ----------------------------------------------------------------------------- tape node


class TapeNode:
    """One recorded op: vjp closure + edges to input tensors.

    Ref analog: a generated `GradNodeBase` subclass (grad_node_info.h:168) whose
    TensorWrappers are subsumed by the residuals captured inside `vjp_fn`.
    `out_avals` lets the engine synthesize zero cotangents for unused outputs of
    multi-output ops (ref GradTensorHolder zero-fill).
    """

    __slots__ = ("vjp_fn", "inputs", "out_avals", "out_is_tuple", "name", "released",
                 "hooks", "primal_fn")

    def __init__(self, vjp_fn: Callable, inputs: Sequence[Any], out_avals, name: str = "op",
                 out_is_tuple: bool | None = None, primal_fn: Callable | None = None):
        self.vjp_fn = vjp_fn
        self.inputs = list(inputs)  # input Tensors that require grad (edges)
        self.out_avals = list(out_avals)  # (shape, dtype) per output
        self.out_is_tuple = len(self.out_avals) > 1 if out_is_tuple is None else out_is_tuple
        self.name = name
        self.released = False
        self.hooks: list[Callable] = []
        # pure fn over the diff inputs; enables create_graph (higher-order) backward
        # by re-linearizing inside a taped op (see run_backward create_graph path)
        self.primal_fn = primal_fn

    @property
    def n_outputs(self):
        return len(self.out_avals)

    def release(self):
        self.vjp_fn = None
        self.inputs = []
        self.released = True


# ----------------------------------------------------------------------------- engine


def _zero_ct(aval):
    """Zero cotangent for an unused output; float0 for non-inexact outputs
    (jax.vjp's required cotangent type for integer/bool primal outputs)."""
    import numpy as np

    shape, dtype = aval
    if not jnp.issubdtype(dtype, jnp.inexact):
        return np.zeros(shape, jax.dtypes.float0)
    return jnp.zeros(shape, dtype)


def _accumulate(store: dict, key, value):
    if key in store:
        store[key] = store[key] + value
    else:
        store[key] = value


def _taped_node_vjp(node: TapeNode, filled):
    """create_graph path: recompute the node's vjp INSIDE a taped op, so the produced
    cotangents carry their own tape history (true double backward).  The node's
    primal_fn is re-linearized w.r.t. its live input tensors; non-inexact cotangents
    (float0) pass through raw."""
    from ..tensor.tensor import Tensor, apply_op

    n_p = len(node.inputs)

    def recompute_vjp(*args):
        primals = args[:n_p]
        cts = args[n_p:]
        _, vfn = jax.vjp(node.primal_fn, *primals)
        seed = tuple(cts) if node.out_is_tuple else cts[0]
        return tuple(vfn(seed))

    ct_args = tuple(
        c if isinstance(c, Tensor) or not hasattr(c, "dtype") or not jnp.issubdtype(c.dtype, jnp.inexact)
        else Tensor(c)
        for c in filled
    )
    res = apply_op(recompute_vjp, (*node.inputs, *ct_args), name=f"grad:{node.name}")
    return res if isinstance(res, tuple) else (res,)


def run_backward(tensors, grad_tensors=None, retain_graph: bool = False, accumulate_fn=None,
                 create_graph: bool = False):
    """Topological reverse walk (ref eager/backward.cc:556 RunBackward).

    Seeds `tensors` with `grad_tensors` (None -> ones), walks producer nodes in
    reverse-topological order with in-degree counting (ref getInDegreeMap
    backward.cc:666-700), and accumulates into `.grad` of leaf tensors with
    stop_gradient=False.
    """
    from ..tensor.tensor import Tensor  # import-cycle-free at call time

    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)

    cts: dict[tuple[int, int], Any] = {}  # (id(node), out_index) -> cotangent
    roots: list[TapeNode] = []

    def leaf_accumulate(t, g):
        if accumulate_fn is not None:
            accumulate_fn(t, g)
        elif not t.stop_gradient:
            t._accumulate_grad(g)

    for t, g in zip(tensors, grad_tensors):
        if g is None:
            g = jnp.ones_like(t._value)
        elif isinstance(g, Tensor):
            g = g._value
        if t._node is None:
            leaf_accumulate(t, g)
            continue
        if t._node.released:
            raise RuntimeError(
                "Trying to run backward through the same graph a second time. "
                "Specify retain_graph=True on the first backward call."
            )
        _accumulate(cts, (id(t._node), t._out_index), g)
        roots.append(t._node)

    # discover reachable nodes + per-node consumer count
    pending: dict[int, int] = {}
    nodes: dict[int, TapeNode] = {}
    stack = list(roots)
    while stack:
        node = stack.pop()
        if id(node) in nodes:
            continue
        nodes[id(node)] = node
        for inp in node.inputs:
            prod = inp._node
            if prod is not None and not prod.released:
                pending[id(prod)] = pending.get(id(prod), 0) + 1
                stack.append(prod)

    queue = [n for i, n in nodes.items() if pending.get(i, 0) == 0]
    processed: set[int] = set()
    while queue:
        node = queue.pop()
        if id(node) in processed:
            continue
        processed.add(id(node))

        out_cts = [cts.pop((id(node), i), None) for i in range(node.n_outputs)]
        if any(c is not None for c in out_cts):
            filled = tuple(
                c if c is not None else _zero_ct(node.out_avals[i])
                for i, c in enumerate(out_cts)
            )
            if create_graph and node.primal_fn is not None:
                in_cts = _taped_node_vjp(node, filled)
            else:
                raw_filled = tuple(c._value if isinstance(c, Tensor) else c for c in filled)
                seed = raw_filled if node.out_is_tuple else raw_filled[0]
                in_cts = node.vjp_fn(seed)
            for hook in node.hooks:
                in_cts = hook(in_cts)
            for inp, ct in zip(node.inputs, in_cts):
                if ct is None:
                    continue
                for h in inp._grad_hooks:
                    ct = h(ct)
                    if ct is None:
                        break
                if ct is None:
                    continue
                prod = inp._node
                if prod is None or prod.released:
                    leaf_accumulate(inp, ct)
                else:
                    _accumulate(cts, (id(prod), inp._out_index), ct)

        for inp in node.inputs:
            prod = inp._node
            if prod is not None and id(prod) in nodes and not prod.released:
                pending[id(prod)] -= 1
                if pending[id(prod)] == 0:
                    queue.append(prod)
        if not retain_graph:
            node.release()


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph=None,
    create_graph=False,
    only_inputs=True,
    allow_unused=False,
    no_grad_vars=None,
):
    """paddle.grad parity (ref GeneralGrad, eager/backward.cc:859).

    Returns grads of `outputs` w.r.t. `inputs` without mutating `.grad` fields.
    NOTE: create_graph (higher-order through the tape) requires retained graphs;
    prefer `paddle.incubate.autograd` functional transforms for heavy higher-order use.
    """
    from ..tensor.tensor import Tensor

    outputs = list(outputs) if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = list(inputs) if isinstance(inputs, (list, tuple)) else [inputs]
    if retain_graph is None:
        retain_graph = create_graph

    grads: dict[int, Any] = {}
    targets = {id(t): t for t in inputs}

    def collect(t, g):
        if id(t) in targets:
            _accumulate(grads, id(t), g)

    # route every leaf cotangent through `collect`; also catch non-leaf inputs by
    # temporarily severing their producer edge so they behave as leaves.
    severed = []
    for t in inputs:
        if t._node is not None:
            severed.append((t, t._node))
            t._node = None
    try:
        run_backward(outputs, grad_outputs, retain_graph=bool(retain_graph),
                     accumulate_fn=collect, create_graph=create_graph)
    finally:
        for t, n in severed:
            t._node = n

    results = []
    for t in inputs:
        g = grads.get(id(t))
        if g is None:
            if not allow_unused:
                raise RuntimeError(
                    "One of the differentiated tensors appears to not have been used "
                    "in the graph. Set allow_unused=True if this is intended."
                )
            results.append(None)
        elif isinstance(g, Tensor):
            results.append(g)  # create_graph path: tape history preserved
        else:
            results.append(Tensor(g, stop_gradient=not create_graph))
    return results
