"""paddle.autograd namespace (ref: python/paddle/autograd/__init__.py)."""
from .tape import no_grad, enable_grad, is_grad_enabled, set_grad_enabled, grad  # noqa: F401
from .py_layer import PyLayer, PyLayerContext  # noqa: F401

backward = None  # populated lazily to avoid cycles


def _backward(tensors, grad_tensors=None, retain_graph=False):
    from .tape import run_backward

    tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    if grad_tensors is not None and not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]
    run_backward(list(tensors), grad_tensors, retain_graph=retain_graph)


backward = _backward
