"""paddle.static parity shims (ref: python/paddle/static/__init__.py).

The reference's static graph (ProgramDesc + Executor, §3.3 of SURVEY.md) has no
separate existence on TPU: a "static program" IS a jitted function.  We keep the
`enable_static`/`Executor`-shaped surface for script compatibility: `data` declares
InputSpec-like placeholders, `Executor.run` executes a to_static-compiled callable.
Control-flow ops (cond/while_loop/case) are real: they map to lax primitives and work
inside to_static traces — the TPU equivalent of conditional_block_op/while_op
(ref operators/controlflow/conditional_block_op.cc, while_op.cc).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor.tensor import Tensor, apply_op
from ..jit import InputSpec  # noqa: F401

_static_mode = False


def enable_static():
    global _static_mode
    _static_mode = True


def disable_static():
    global _static_mode
    _static_mode = False


def in_static_mode():
    return _static_mode


class Program:  # minimal placeholder graph object
    def __init__(self):
        self.ops = []

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self


def default_main_program():
    return Program()


def default_startup_program():
    return Program()


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape, dtype, name)


class Executor:
    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        if callable(program):
            out = program(**(feed or {}))
            return out if isinstance(out, (list, tuple)) else [out]
        return []


class nn:
    """Compiled control flow — the dy2static control-flow capture analog."""

    @staticmethod
    def cond(pred, true_fn, false_fn, name=None):
        def _f(p):
            return jax.lax.cond(jnp.all(p), lambda: _raw(true_fn()), lambda: _raw(false_fn()))

        return apply_op(_f, (pred,), name="cond")

    @staticmethod
    def while_loop(cond, body, loop_vars, name=None):
        raws = [v._value if isinstance(v, Tensor) else v for v in loop_vars]

        def _f(*vs):
            def c(vs_):
                r = cond(*[Tensor(v, stop_gradient=True) for v in vs_])
                return jnp.all(r._value if isinstance(r, Tensor) else r)

            def b(vs_):
                out = body(*[Tensor(v, stop_gradient=True) for v in vs_])
                out = out if isinstance(out, (list, tuple)) else [out]
                return tuple(o._value if isinstance(o, Tensor) else o for o in out)

            return jax.lax.while_loop(c, b, tuple(vs))

        return apply_op(_f, tuple(loop_vars), name="while_loop")

    @staticmethod
    def case(pred_fn_pairs, default=None, name=None):
        for pred, fn in pred_fn_pairs:
            v = pred.item() if isinstance(pred, Tensor) else bool(pred)
            if v:
                return fn()
        return default() if default is not None else None

    @staticmethod
    def switch_case(branch_index, branch_fns, default=None, name=None):
        idx = int(branch_index.item()) if isinstance(branch_index, Tensor) else int(branch_index)
        fns = dict(branch_fns) if isinstance(branch_fns, (list, tuple)) else branch_fns
        return fns.get(idx, default or (lambda: None))()


def _raw(x):
    if isinstance(x, (tuple, list)):
        return tuple(_raw(i) for i in x)
    return x._value if isinstance(x, Tensor) else x


def save(program, model_path, **kwargs):
    raise NotImplementedError(
        "paddle.static.save: static Programs have no serialized form on the TPU "
        "build (a 'program' is a jitted function) — save the Layer with "
        "paddle.jit.save(layer, path, input_spec=...) or its state with "
        "paddle.save(layer.state_dict(), path)")


def load(program, model_path, **kwargs):
    raise NotImplementedError(
        "paddle.static.load: use paddle.jit.load(path) for deployed programs or "
        "paddle.load(path) for state dicts")


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor, **kwargs):
    raise NotImplementedError(
        "paddle.static.save_inference_model: use paddle.jit.save(layer, "
        "path_prefix, input_spec=[...]) — the AOT-exported program is the TPU "
        "inference artifact (loaded by paddle.jit.load or inference.Predictor)")


def load_inference_model(path_prefix, executor, **kwargs):
    raise NotImplementedError("use paddle.jit.load for deployed programs")
