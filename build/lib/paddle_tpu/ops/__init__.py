"""Pallas TPU kernels + hand-rolled distributed primitives (flash attention, ring
attention, MoE dispatch) — the few ops where XLA's automatic lowering leaves MXU/HBM
performance on the table (see /opt/skills/guides/pallas_guide.md)."""

from .flash_attention import flash_attention  # noqa: F401
from .sequence_parallel import ring_attention, ulysses_attention  # noqa: F401
