"""paddle.device namespace (ref: python/paddle/device/__init__.py)."""
from ..core.device import (  # noqa: F401
    set_device, get_device, is_compiled_with_cuda, is_compiled_with_tpu,
    CPUPlace, CUDAPlace, TPUPlace, CustomPlace, Place,
)
import jax


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return get_available_device()


def device_count():
    return len(jax.devices())


class cuda:
    """paddle.device.cuda shims mapped to the accelerator."""

    @staticmethod
    def device_count():
        return len(jax.devices())

    @staticmethod
    def synchronize(device=None):
        import jax.numpy as jnp

        jnp.zeros(()).block_until_ready()

    @staticmethod
    def empty_cache():
        pass

    @staticmethod
    def max_memory_allocated(device=None):
        try:
            stats = jax.devices()[0].memory_stats()
            return stats.get("peak_bytes_in_use", 0)
        except Exception:
            return 0

    @staticmethod
    def memory_allocated(device=None):
        try:
            stats = jax.devices()[0].memory_stats()
            return stats.get("bytes_in_use", 0)
        except Exception:
            return 0


def synchronize(device=None):
    cuda.synchronize()


class Stream:
    """Streams are XLA's scheduling concern on TPU; kept as no-op parity objects."""

    def __init__(self, device=None, priority=2):
        pass

    def synchronize(self):
        cuda.synchronize()


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def synchronize(self):
        cuda.synchronize()
