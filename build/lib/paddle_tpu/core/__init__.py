from . import dtypes, device  # noqa: F401
