// paddle_tpu native runtime core.
//
// TPU-native equivalents of the reference's C++ runtime pieces that remain genuinely
// native in a JAX/XLA world (the kernels & executors collapsed into XLA; what's left
// is the host-side control plane and IO):
//
//  1. TCPStore  — rendezvous/control-plane KV store
//     (ref: paddle/fluid/distributed/store/tcp_store.h:120, store.h:26).
//     Same length-prefixed wire protocol as the Python fallback in
//     paddle_tpu/distributed/store.py: [op u8][klen u32][key][vlen u32][val].
//  2. Ring buffer — bounded MPMC byte-slot queue backing DataLoader prefetch
//     (ref: fluid/dataloader worker queues + paddle/fluid/framework/data_feed.cc);
//     blocking push/pop without holding the Python GIL.
//  3. Trace collector — lock-striped in-memory span buffer with chrome://tracing
//     JSON export (ref: paddle/fluid/platform/profiler/chrometracing_logger.cc,
//     RecordEvent event_tracing.h:49).
//  4. Host buffer pool — size-class free-list allocator for pinned host staging
//     buffers with live/peak stats (ref: memory/allocation/auto_growth_best_fit_
//     allocator.h:30 + memory/stats.cc).
//
// Exposed as a flat C ABI consumed via ctypes (pybind11 is not available in this
// image; see paddle_tpu/core/native/__init__.py).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cerrno>
#include <chrono>
#include <cstdlib>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

extern "C" {

// ----------------------------------------------------------------------------
// 1. TCPStore
// ----------------------------------------------------------------------------

namespace {

struct KVServer {
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> running{true};
  std::map<std::string, std::string> data;
  std::mutex mu;
  std::condition_variable cv;
  std::thread accept_thread;
  std::vector<std::thread> workers;
  std::vector<int> conn_fds;  // open connections, shut down on stop (guarded by mu)
};

bool recv_n(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  size_t got = 0;
  while (got < n) {
    ssize_t r = ::recv(fd, p + got, n - got, 0);
    if (r <= 0) return false;
    got += static_cast<size_t>(r);
  }
  return true;
}

bool send_n(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  size_t sent = 0;
  while (sent < n) {
    ssize_t r = ::send(fd, p + sent, n - sent, MSG_NOSIGNAL);
    if (r <= 0) return false;
    sent += static_cast<size_t>(r);
  }
  return true;
}

bool send_val(int fd, const std::string& v) {
  uint32_t len = static_cast<uint32_t>(v.size());
  if (!send_n(fd, &len, 4)) return false;
  return v.empty() ? true : send_n(fd, v.data(), v.size());
}

void serve_conn(KVServer* s, int fd) {
  for (;;) {
    unsigned char hdr[5];
    if (!recv_n(fd, hdr, 5)) break;
    char op = static_cast<char>(hdr[0]);
    uint32_t klen;
    std::memcpy(&klen, hdr + 1, 4);
    std::string key(klen, '\0');
    if (klen && !recv_n(fd, key.data(), klen)) break;
    uint32_t vlen;
    if (!recv_n(fd, &vlen, 4)) break;
    std::string val(vlen, '\0');
    if (vlen && !recv_n(fd, val.data(), vlen)) break;

    if (op == 'S') {
      {
        std::lock_guard<std::mutex> lk(s->mu);
        s->data[key] = val;
      }
      s->cv.notify_all();
      if (!send_val(fd, "ok")) break;
    } else if (op == 'A') {
      // strtoll with full error checking: a non-numeric stored value or payload
      // must produce an in-band error reply, not an exception that would
      // std::terminate() the rendezvous server's worker thread.
      auto parse_ll = [](const std::string& str, long long* out) -> bool {
        if (str.empty()) { *out = 0; return true; }
        errno = 0;
        char* end = nullptr;
        long long v = std::strtoll(str.c_str(), &end, 10);
        if (errno != 0 || end == str.c_str() || *end != '\0') return false;
        *out = v;
        return true;
      };
      long long cur = 0, inc = 0;
      bool parsed = true;
      {
        std::lock_guard<std::mutex> lk(s->mu);
        auto it = s->data.find(key);
        parsed = (it == s->data.end() || parse_ll(it->second, &cur)) &&
                 parse_ll(val, &inc);
        if (parsed) {
          cur += inc;
          s->data[key] = std::to_string(cur);
        }
      }
      if (!parsed) {
        if (!send_val(fd, "ERR non-integer value")) break;
        continue;
      }
      s->cv.notify_all();
      if (!send_val(fd, std::to_string(cur))) break;
    } else if (op == 'G') {  // blocking get (TCPStore::wait semantics)
      std::unique_lock<std::mutex> lk(s->mu);
      s->cv.wait(lk, [&] { return !s->running || s->data.count(key); });
      if (!s->running) break;
      std::string v = s->data[key];
      lk.unlock();
      if (!send_val(fd, v)) break;
    } else if (op == 'W') {  // check
      std::string v;
      {
        std::lock_guard<std::mutex> lk(s->mu);
        v = s->data.count(key) ? "1" : "0";
      }
      if (!send_val(fd, v)) break;
    } else if (op == 'N') {  // non-blocking get: 1-byte presence flag + value
      std::string v;
      {
        std::lock_guard<std::mutex> lk(s->mu);
        auto it = s->data.find(key);
        v = (it == s->data.end()) ? std::string("0") : "1" + it->second;
      }
      if (!send_val(fd, v)) break;
    } else if (op == 'D') {
      {
        std::lock_guard<std::mutex> lk(s->mu);
        s->data.erase(key);
      }
      if (!send_val(fd, "ok")) break;
    } else if (op == 'L') {  // list keys with prefix, newline-joined
      std::string out;
      {
        std::lock_guard<std::mutex> lk(s->mu);
        for (auto it = s->data.lower_bound(key);
             it != s->data.end() && it->first.compare(0, key.size(), key) == 0; ++it) {
          if (!out.empty()) out += '\n';
          out += it->first;
        }
      }
      if (!send_val(fd, out)) break;
    } else {
      break;
    }
  }
  ::close(fd);
  std::lock_guard<std::mutex> lk(s->mu);
  for (auto it = s->conn_fds.begin(); it != s->conn_fds.end(); ++it) {
    if (*it == fd) {
      s->conn_fds.erase(it);
      break;
    }
  }
}

}  // namespace

void* pt_store_server_start(int port) {
  auto* s = new KVServer();
  s->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  ::setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(s->listen_fd, 128) != 0) {
    ::close(s->listen_fd);
    delete s;
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  s->port = ntohs(addr.sin_port);
  s->accept_thread = std::thread([s] {
    while (s->running) {
      int fd = ::accept(s->listen_fd, nullptr, nullptr);
      if (fd < 0) return;
      {
        // register BEFORE spawning so stop()'s shutdown sweep can't miss it
        std::lock_guard<std::mutex> lk(s->mu);
        s->conn_fds.push_back(fd);
      }
      s->workers.emplace_back(serve_conn, s, fd);
    }
  });
  return s;
}

int pt_store_server_port(void* h) { return static_cast<KVServer*>(h)->port; }

void pt_store_server_stop(void* h) {
  auto* s = static_cast<KVServer*>(h);
  s->running = false;
  s->cv.notify_all();  // wake blocking-'G' waiters
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  {
    // unblock workers stuck in recv() so they can be joined (no detach: the
    // threads reference s->mu/cv/data, so s must outlive them)
    std::lock_guard<std::mutex> lk(s->mu);
    for (int fd : s->conn_fds) ::shutdown(fd, SHUT_RDWR);
  }
  for (auto& t : s->workers)
    if (t.joinable()) t.join();
  delete s;
}

// ----------------------------------------------------------------------------
// 2. Prefetch ring buffer (MPMC, byte slots)
// ----------------------------------------------------------------------------

namespace {

struct Ring {
  std::deque<std::string> q;
  size_t capacity;
  std::mutex mu;
  std::condition_variable not_full, not_empty;
  std::atomic<bool> closed{false};
  std::atomic<uint64_t> pushed{0}, popped{0};
};

}  // namespace

void* pt_ring_new(int capacity) {
  auto* r = new Ring();
  r->capacity = capacity > 0 ? static_cast<size_t>(capacity) : 1;
  return r;
}

// returns 1 on success, 0 if closed, -1 on timeout
int pt_ring_push(void* h, const char* data, int64_t n, double timeout_s) {
  auto* r = static_cast<Ring*>(h);
  std::unique_lock<std::mutex> lk(r->mu);
  auto pred = [&] { return r->closed || r->q.size() < r->capacity; };
  if (timeout_s < 0) {
    r->not_full.wait(lk, pred);
  } else if (!r->not_full.wait_for(lk, std::chrono::duration<double>(timeout_s), pred)) {
    return -1;
  }
  if (r->closed) return 0;
  r->q.emplace_back(data, static_cast<size_t>(n));
  r->pushed++;
  lk.unlock();
  r->not_empty.notify_one();
  return 1;
}

// returns size of popped item (>0), -3 for a popped zero-length item,
// 0 for closed-and-drained (end of stream), -1 on timeout, -2 buffer too small
int64_t pt_ring_pop(void* h, char* out, int64_t out_cap, double timeout_s) {
  auto* r = static_cast<Ring*>(h);
  std::unique_lock<std::mutex> lk(r->mu);
  auto pred = [&] { return r->closed || !r->q.empty(); };
  if (timeout_s < 0) {
    r->not_empty.wait(lk, pred);
  } else if (!r->not_empty.wait_for(lk, std::chrono::duration<double>(timeout_s), pred)) {
    return -1;
  }
  if (r->q.empty()) return 0;  // closed and drained
  std::string& front = r->q.front();
  int64_t n = static_cast<int64_t>(front.size());
  if (n > out_cap) return -2;  // caller buffer too small; item stays queued
  std::memcpy(out, front.data(), front.size());
  r->q.pop_front();
  r->popped++;
  lk.unlock();
  r->not_full.notify_one();
  return n == 0 ? -3 : n;  // -3 disambiguates an empty payload from end-of-stream
}

// peek size of the next item without popping (-1 if empty)
int64_t pt_ring_peek_size(void* h) {
  auto* r = static_cast<Ring*>(h);
  std::lock_guard<std::mutex> lk(r->mu);
  return r->q.empty() ? -1 : static_cast<int64_t>(r->q.front().size());
}

int pt_ring_size(void* h) {
  auto* r = static_cast<Ring*>(h);
  std::lock_guard<std::mutex> lk(r->mu);
  return static_cast<int>(r->q.size());
}

void pt_ring_close(void* h) {
  auto* r = static_cast<Ring*>(h);
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->closed = true;
  }
  r->not_empty.notify_all();
  r->not_full.notify_all();
}

void pt_ring_free(void* h) { delete static_cast<Ring*>(h); }

// ----------------------------------------------------------------------------
// 3. Trace collector (chrome://tracing)
// ----------------------------------------------------------------------------

namespace {

struct TraceEvent {
  std::string name;
  uint64_t ts_us;   // begin
  uint64_t dur_us;  // duration
  uint64_t tid;
};

struct Tracer {
  std::vector<TraceEvent> events;
  std::mutex mu;
  std::atomic<bool> enabled{false};
  std::chrono::steady_clock::time_point t0 = std::chrono::steady_clock::now();
};

Tracer g_tracer;

thread_local std::vector<std::pair<std::string, uint64_t>> tl_span_stack;

uint64_t now_us() {
  return static_cast<uint64_t>(std::chrono::duration_cast<std::chrono::microseconds>(
                                   std::chrono::steady_clock::now() - g_tracer.t0)
                                   .count());
}

uint64_t tid_hash() {
  return std::hash<std::thread::id>{}(std::this_thread::get_id()) % 100000;
}

}  // namespace

void pt_trace_enable(int on) { g_tracer.enabled = on != 0; }
int pt_trace_enabled() { return g_tracer.enabled ? 1 : 0; }

void pt_trace_begin(const char* name) {
  if (!g_tracer.enabled) return;
  tl_span_stack.emplace_back(name, now_us());
}

void pt_trace_end() {
  if (!g_tracer.enabled || tl_span_stack.empty()) return;
  auto [name, begin] = tl_span_stack.back();
  tl_span_stack.pop_back();
  TraceEvent ev{std::move(name), begin, now_us() - begin, tid_hash()};
  std::lock_guard<std::mutex> lk(g_tracer.mu);
  g_tracer.events.push_back(std::move(ev));
}

// complete event with explicit times (for python-side spans)
void pt_trace_complete(const char* name, uint64_t ts_us, uint64_t dur_us) {
  if (!g_tracer.enabled) return;
  TraceEvent ev{name, ts_us, dur_us, tid_hash()};
  std::lock_guard<std::mutex> lk(g_tracer.mu);
  g_tracer.events.push_back(std::move(ev));
}

int64_t pt_trace_count() {
  std::lock_guard<std::mutex> lk(g_tracer.mu);
  return static_cast<int64_t>(g_tracer.events.size());
}

void pt_trace_clear() {
  std::lock_guard<std::mutex> lk(g_tracer.mu);
  g_tracer.events.clear();
}

// Serialize to chrome://tracing JSON (ref chrometracing_logger.cc output format).
// Returns bytes written (excluding NUL), or required size if buf is null/small.
int64_t pt_trace_dump_json(char* buf, int64_t cap) {
  std::string out = "{\"traceEvents\":[";
  {
    std::lock_guard<std::mutex> lk(g_tracer.mu);
    bool first = true;
    for (const auto& ev : g_tracer.events) {
      if (!first) out += ',';
      first = false;
      out += "{\"name\":\"";
      for (char c : ev.name) {  // minimal JSON escape
        if (c == '"' || c == '\\') out += '\\';
        if (static_cast<unsigned char>(c) >= 0x20) out += c;
      }
      out += "\",\"ph\":\"X\",\"pid\":0,\"tid\":" + std::to_string(ev.tid) +
             ",\"ts\":" + std::to_string(ev.ts_us) +
             ",\"dur\":" + std::to_string(ev.dur_us) + "}";
    }
  }
  out += "]}";
  int64_t need = static_cast<int64_t>(out.size());
  if (buf == nullptr || cap < need) return need;
  std::memcpy(buf, out.data(), out.size());
  return need;
}

uint64_t pt_trace_now_us() { return now_us(); }

// ----------------------------------------------------------------------------
// 4. Host buffer pool (size-class free lists + stats)
// ----------------------------------------------------------------------------

namespace {

struct Pool {
  std::unordered_map<size_t, std::vector<void*>> free_lists;  // size-class -> buffers
  std::unordered_map<void*, size_t> live;                     // ptr -> class size
  std::mutex mu;
  std::atomic<int64_t> allocated{0};   // bytes held (live + cached)
  std::atomic<int64_t> in_use{0};      // bytes handed out
  std::atomic<int64_t> peak{0};
  std::atomic<int64_t> hits{0}, misses{0};
};

size_t size_class(size_t n) {
  // round up to the next power of two >= 256 (alignment-friendly for DMA staging)
  size_t c = 256;
  while (c < n) c <<= 1;
  return c;
}

}  // namespace

void* pt_pool_new() { return new Pool(); }

void* pt_pool_alloc(void* h, int64_t n) {
  auto* p = static_cast<Pool*>(h);
  size_t cls = size_class(static_cast<size_t>(n));
  void* buf = nullptr;
  {
    std::lock_guard<std::mutex> lk(p->mu);
    auto& fl = p->free_lists[cls];
    if (!fl.empty()) {
      buf = fl.back();
      fl.pop_back();
      p->hits++;
    }
  }
  if (buf == nullptr) {
    if (posix_memalign(&buf, 4096, cls) != 0) return nullptr;  // page-aligned
    p->misses++;
    p->allocated += static_cast<int64_t>(cls);
  }
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->live[buf] = cls;
  }
  p->in_use += static_cast<int64_t>(cls);
  int64_t u = p->in_use.load();
  int64_t pk = p->peak.load();
  while (u > pk && !p->peak.compare_exchange_weak(pk, u)) {
  }
  return buf;
}

int pt_pool_free(void* h, void* buf) {
  auto* p = static_cast<Pool*>(h);
  size_t cls;
  {
    std::lock_guard<std::mutex> lk(p->mu);
    auto it = p->live.find(buf);
    if (it == p->live.end()) return -1;
    cls = it->second;
    p->live.erase(it);
    p->free_lists[cls].push_back(buf);
  }
  p->in_use -= static_cast<int64_t>(cls);
  return 0;
}

// stats: [allocated, in_use, peak, hits, misses]
void pt_pool_stats(void* h, int64_t* out5) {
  auto* p = static_cast<Pool*>(h);
  out5[0] = p->allocated.load();
  out5[1] = p->in_use.load();
  out5[2] = p->peak.load();
  out5[3] = p->hits.load();
  out5[4] = p->misses.load();
}

void pt_pool_trim(void* h) {
  auto* p = static_cast<Pool*>(h);
  std::lock_guard<std::mutex> lk(p->mu);
  for (auto& [cls, fl] : p->free_lists) {
    for (void* b : fl) {
      ::free(b);
      p->allocated -= static_cast<int64_t>(cls);
    }
    fl.clear();
  }
}

void pt_pool_delete(void* h) {
  auto* p = static_cast<Pool*>(h);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    for (auto& [cls, fl] : p->free_lists)
      for (void* b : fl) ::free(b);
    for (auto& [b, cls] : p->live) ::free(b);
  }
  delete p;
}

int pt_native_abi_version() { return 1; }

}  // extern "C"
