"""Shared inner step builder for TrainStep / ShardedTrainStep.

One XLA program per optimizer update, with two orthogonal extensions the
reference implements as separate meta-optimizers:

- gradient accumulation (ref fleet/meta_optimizers/gradient_merge_optimizer.py,
  dygraph `no_sync` + manual accumulation): `accum_steps > 1` splits the batch
  into microbatches and lax.scan's the forward/backward, averaging grads into
  ONE optimizer update — large global batches without large activations.
- dynamic loss scaling in-graph (ref amp/grad_scaler.py:26 via
  check_finite_and_unscale + update_loss_scaling ops): the scaler's
  (scale, good, bad) counters live on device and the skip-update-on-overflow
  select happens inside the compiled step — fp16 runs on the fast path with no
  per-step host sync (the round-1 GradScaler pulled a bool to host every step).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor.tensor import Tensor
from ..autograd import tape
from ..framework import random as _random


def init_scaler_state(scaler):
    """Device-resident scaler state (None when no/disabled scaler)."""
    if scaler is None or not scaler._enable:
        return None
    return {
        "scale": jnp.asarray(scaler._scale, jnp.float32),
        "good": jnp.asarray(scaler._good_steps, jnp.int32),
        "bad": jnp.asarray(scaler._bad_steps, jnp.int32),
    }


def _update_scaler_state(scaler, st, found_inf):
    """In-graph twin of GradScaler.update() (update_loss_scaling op)."""
    if not scaler._dynamic:
        return {**st, "good": st["good"], "bad": st["bad"]}
    bad = jnp.where(found_inf, st["bad"] + 1, 0)
    good = jnp.where(found_inf, 0, st["good"] + 1)
    shrink = bad >= scaler._decr_every_n
    grow = good >= scaler._incr_every_n_steps
    scale = jnp.where(shrink, jnp.maximum(st["scale"] * scaler._decr_ratio, 1.0),
                      jnp.where(grow, st["scale"] * scaler._incr_ratio, st["scale"]))
    return {"scale": scale,
            "good": jnp.where(grow, 0, good),
            "bad": jnp.where(shrink, 0, bad)}


def build_step_fn(model, loss_fn, opt, named, trainable, accum_steps=1,
                  scaler=None, cast_loss_f32=False, mb_constraint=None):
    """Returns step(params, buffers, opt_state, scaler_state, lr, key, *batch)
    -> (new_params, new_buffers, new_opt, new_scaler_state, loss, aux).

    `scaler_state`/`new_scaler_state` are None when scaler is None/disabled.
    """
    accum = max(1, int(accum_steps))
    use_scaler = scaler is not None and scaler._enable

    def forward_loss(allp, buffers, key, batch):
        with _random.rng_key_scope(key):
            restore = model.bind_functional_state(allp, buffers)
            try:
                with tape.no_grad():
                    args = tuple(Tensor(b, stop_gradient=True) for b in batch)
                    out = loss_fn(*args)
                loss_t = out[0] if isinstance(out, (tuple, list)) else out
                aux_out = tuple(o._value if isinstance(o, Tensor) else o
                                for o in (out[1:] if isinstance(out, (tuple, list)) else ()))
                new_buffers = {kk: b._value for kk, b in model.named_buffers()}
            finally:
                restore()
        loss_v = loss_t._value
        if cast_loss_f32:
            loss_v = loss_v.astype(jnp.float32)
        return loss_v, (new_buffers, aux_out)

    def step(params, buffers, opt_state, scaler_state, lr, key, *batch):
        t_params = {k: v for k, v in params.items() if k in trainable}
        frozen = {k: v for k, v in params.items() if k not in trainable}
        scale = scaler_state["scale"] if use_scaler else None

        def pure_loss(tp, bufs, k, mb):
            loss, auxes = forward_loss({**tp, **frozen}, bufs, k, mb)
            scaled = loss * scale.astype(loss.dtype) if use_scaler else loss
            return scaled, (loss, *auxes)

        vgrad = jax.value_and_grad(pure_loss, has_aux=True)

        if accum == 1:
            (_, (loss, new_buffers, aux)), grads = vgrad(t_params, buffers, key, batch)
        else:
            for b in batch:
                if b.shape[0] % accum:
                    raise ValueError(
                        f"accum_steps={accum} does not divide the batch size "
                        f"{b.shape[0]} — gradient accumulation splits the batch "
                        f"axis into equal microbatches")
            mbs = tuple(b.reshape((accum, b.shape[0] // accum) + b.shape[1:])
                        for b in batch)
            if mb_constraint is not None:
                # keep the data sharding on the per-microbatch axis (axis 1),
                # not the scan axis — otherwise the partitioner fully
                # rematerializes every dynamic_slice of the scan
                mbs = tuple(mb_constraint(b) for b in mbs)
            keys = jax.random.split(key, accum)

            def body(carry, xs):
                bufs, gsum, lsum = carry
                k, mb = xs[0], xs[1:]
                (_, (l, nb, aux_i)), g = vgrad(t_params, bufs, k, mb)
                gsum = jax.tree.map(jnp.add, gsum, g)
                return (nb, gsum, lsum + l.astype(jnp.float32)), aux_i

            gzero = jax.tree.map(jnp.zeros_like, t_params)
            (new_buffers, gsum, lsum), aux_st = jax.lax.scan(
                body, (buffers, gzero, jnp.zeros((), jnp.float32)),
                (keys, *mbs))
            grads = jax.tree.map(lambda g: g / accum, gsum)
            loss = lsum / accum
            aux = jax.tree.map(lambda a: a[-1], aux_st)

        if use_scaler:
            inv = (1.0 / scale)
            grads = {k: (g.astype(jnp.float32) * inv).astype(g.dtype)
                     for k, g in grads.items()}
            found_inf = jnp.zeros((), bool)
            for g in grads.values():
                found_inf = found_inf | ~jnp.all(jnp.isfinite(g.astype(jnp.float32)))
        else:
            found_inf = None

        clipped = opt._clipped_grads(list(grads.items()))
        new_params = dict(frozen)
        new_opt = {}
        for k, g in clipped:
            np_k, no_k = opt._apply_update(
                params[k], g, opt_state[k], lr, opt._param_decay_coeff(named[k]))
            if use_scaler:
                # overflow step: keep params/opt-state (check_finite_and_unscale
                # + conditional update, done as a select so the step stays one
                # traced program)
                np_k = jnp.where(found_inf, params[k], np_k)
                no_k = jax.tree.map(lambda new, old: jnp.where(found_inf, old, new),
                                    no_k, opt_state[k])
            new_params[k], new_opt[k] = np_k, no_k

        new_scaler_state = (_update_scaler_state(scaler, scaler_state, found_inf)
                            if use_scaler else None)
        return new_params, new_buffers, new_opt, new_scaler_state, loss, aux

    return step
