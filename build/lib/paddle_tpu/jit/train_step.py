"""Compiled training step: forward+backward+optimizer in ONE XLA program.

Reference analog: the whole-Program path (`Executor.run` over a Program containing
forward, appended grad ops and optimizer ops — python/paddle/fluid/backward.py +
optimizer.minimize).  TPU-native: `jax.value_and_grad` over the model's functional
state, optimizer update rules applied in-graph, buffers donated so XLA updates
parameters in place (no host round-trip, no per-op dispatch).

This is the throughput path used by bench.py and hapi.Model.fit(jit=True).
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from ..tensor.tensor import Tensor
from ..framework import random as _random
from ..optimizer.optimizer import Optimizer
from ._step_impl import build_step_fn, init_scaler_state


class TrainStep:
    """train_step = TrainStep(model, loss_fn, optimizer); loss = train_step(x, y).

    `accum_steps > 1` accumulates gradients over that many microbatches (batch
    axis split in-graph, one optimizer update — ref gradient_merge_optimizer).
    `scaler=GradScaler(...)` runs dynamic fp16 loss scaling inside the compiled
    step (no host sync; overflow steps skip the update in-graph).
    """

    def __init__(self, model, loss_fn: Callable, optimizer: Optimizer, donate: bool = True,
                 accum_steps: int = 1, scaler=None):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self._jitted = None
        self._param_names = None
        self._opt_state = None
        self._donate = donate
        self.accum_steps = max(1, int(accum_steps))
        self.scaler = scaler
        self._scaler_state = None

    def _init(self):
        params, buffers = self.model.functional_state()
        self._param_names = list(params.keys())
        named = dict(self.model.named_parameters())
        restored = self._opt_state or {}
        self._opt_state = {
            k: (restored[k] if restored.get(k) is not None
                else self.optimizer._init_state(named[k]))
            for k in self._param_names if not named[k].stop_gradient
        }
        trainable = {k for k in self._param_names if not named[k].stop_gradient}
        self._scaler_state = init_scaler_state(self.scaler)

        step = build_step_fn(self.model, self.loss_fn, self.optimizer, named,
                             trainable, accum_steps=self.accum_steps,
                             scaler=self.scaler)
        donate = (0, 2) if self._donate else ()
        self._jitted = jax.jit(step, donate_argnums=donate)

    def __call__(self, *batch):
        if self._jitted is None:
            self._init()
        if self.scaler is not None and getattr(self.scaler, "_host_dirty", False):
            self._scaler_state = init_scaler_state(self.scaler)
            self.scaler._host_dirty = False
        params, buffers = self.model.functional_state()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        key = _random.get_rng_key()
        raw = tuple(b._value if isinstance(b, Tensor) else jnp.asarray(b) for b in batch)
        new_params, new_buffers, new_opt, new_scaler, loss, aux = self._jitted(
            params, buffers, self._opt_state, self._scaler_state, lr, key, *raw
        )
        self._opt_state = new_opt
        self._scaler_state = new_scaler
        if new_scaler is not None:
            self.scaler._attach_device_state(new_scaler)
        self.model.load_functional_state(new_params, new_buffers)
        self.optimizer._step_count += 1
        loss_t = Tensor(loss)
        if aux:
            return (loss_t, *[Tensor(a) for a in aux])
        return loss_t
