"""Mixture-of-Experts with expert parallelism.

Reference analog: `MoELayer` (`/root/reference/python/paddle/incubate/distributed/
models/moe/moe_layer.py:244`) with gshard/switch/naive gates (`moe/gate/*.py`) and
token exchange via the `global_scatter`/`global_gather` collective ops
(`/root/reference/paddle/fluid/operators/collective/global_scatter_op.cc`).

TPU-native: GShard-style DENSE dispatch — a [tokens, experts, capacity] one-hot
dispatch/combine pair built from top-k gating with a static capacity, so the whole
layer is jit-compilable with static shapes (no ragged sends).  Expert exchange:

- single device / pure data parallel: experts applied locally, no comms;
- expert parallel: called inside shard_map with `ep_axis` manual — the [E, C, d]
  expert-major tensor goes through ONE `lax.all_to_all` (split experts, concat
  capacity), local experts run, and a second all_to_all returns token-major.
  This is exactly the reference's global_scatter/global_gather pair, but as XLA
  collectives over ICI instead of NCCL alltoall.

Gate aux losses follow GShard/Switch: l_aux = E * Σ_e mean_probs_e · frac_tokens_e,
readable from `layer.l_aux` after forward (reference keeps it on the gate).
"""
from __future__ import annotations

import math as _math

import jax
import jax.numpy as jnp
from jax import lax

from .. import nn
from ..nn import functional as F
from ..tensor.tensor import Tensor, apply_op
from ..nn.layer.layers import Layer


class BaseGate(Layer):
    """Ref moe/gate/base_gate.py."""

    def __init__(self, d_model, num_expert, top_k):
        super().__init__()
        self.d_model = d_model
        self.num_expert = num_expert
        self.top_k = top_k
        self.gate = nn.Linear(d_model, num_expert, bias_attr=False)

    def logits(self, x):
        return self.gate(x)


class NaiveGate(BaseGate):
    """Plain top-k softmax gate, no aux loss (ref moe/gate/naive_gate.py)."""

    aux_loss_weight = 0.0

    def forward(self, x):
        logits = self.logits(x)
        probs = F.softmax(logits.astype("float32"), axis=-1)
        topv, topi = probs.topk(self.top_k, axis=-1)
        return probs, topv, topi


class GShardGate(NaiveGate):
    """Top-2 gate with load-balancing aux loss (ref moe/gate/gshard_gate.py)."""

    aux_loss_weight = 1.0


class SwitchGate(NaiveGate):
    """Top-1 gate (Switch Transformer; ref moe/gate/switch_gate.py)."""

    aux_loss_weight = 1.0

    def __init__(self, d_model, num_expert, top_k=1):
        super().__init__(d_model, num_expert, top_k=1)


_GATES = {"naive": NaiveGate, "gshard": GShardGate, "switch": SwitchGate}


def _dispatch_combine(probs, topv, topi, num_expert, capacity, top_k):
    """Build dense dispatch [T,E,C] bool and combine [T,E,C] f32 + aux loss.
    Raw-array function (called under apply_op)."""
    T = probs.shape[0]
    E, C = num_expert, capacity

    # renormalize the kept top-k probabilities (GShard)
    denom = jnp.sum(topv, axis=-1, keepdims=True)
    weights = topv / jnp.maximum(denom, 1e-9)

    counts = jnp.zeros((E,), jnp.int32)
    dispatch = jnp.zeros((T, E, C), jnp.float32)
    combine = jnp.zeros((T, E, C), jnp.float32)
    for j in range(top_k):
        idx_j = topi[:, j]                                  # [T]
        mask_j = jax.nn.one_hot(idx_j, E, dtype=jnp.int32)  # [T, E]
        pos_in_e = jnp.cumsum(mask_j, axis=0) - 1 + counts[None, :]  # [T, E]
        counts = counts + jnp.sum(mask_j, axis=0)
        pos_j = jnp.sum(pos_in_e * mask_j, axis=-1)         # [T] position in expert
        keep = pos_j < C
        oh_pos = jax.nn.one_hot(pos_j, C, dtype=jnp.float32)            # [T, C]
        contrib = (mask_j.astype(jnp.float32)[:, :, None] * oh_pos[:, None, :]
                   * keep.astype(jnp.float32)[:, None, None])           # [T, E, C]
        dispatch = jnp.maximum(dispatch, contrib)
        combine = combine + weights[:, j][:, None, None] * contrib

    # GShard load-balancing loss on the top-1 assignment
    me = jnp.mean(probs, axis=0)                            # [E]
    top1 = jax.nn.one_hot(topi[:, 0], E, dtype=jnp.float32)
    ce = jnp.mean(top1, axis=0)                             # [E]
    l_aux = E * jnp.sum(me * ce)
    return dispatch, combine, l_aux


class MoELayer(Layer):
    """Ref moe_layer.py:244 API: MoELayer(d_model, experts=LayerList, gate=cfg).

    forward(x: [B, S, d]) -> [B, S, d]; the gate aux loss is in `self.l_aux`.
    With `ep_axis`, call inside shard_map (manual over that axis): local experts
    are this rank's shard of the expert pool (total = axis_size * len(experts)).
    """

    def __init__(self, d_model, experts, gate="gshard", top_k=2,
                 capacity_factor=1.25, ep_axis=None, ep_size=1, moe_group=None, **kwargs):
        super().__init__()
        self.d_model = d_model
        self.experts = experts if isinstance(experts, nn.LayerList) else nn.LayerList(experts)
        self.num_local_experts = len(self.experts)
        self.ep_axis = ep_axis
        self.ep_size = ep_size if ep_axis is not None else 1
        self.capacity_factor = capacity_factor
        self.num_expert = self.num_local_experts * self.ep_size
        if isinstance(gate, dict):
            top_k = gate.get("top_k", top_k)
            gate = gate.get("type", "gshard")
        if isinstance(gate, str):
            self.gate_layer = _GATES[gate](d_model, self.num_expert, top_k=top_k)
        else:
            self.gate_layer = gate
        self.l_aux = None

    def forward(self, x):
        orig_shape = x.shape
        d = orig_shape[-1]
        xt = x.reshape([-1, d])                              # [T, d]
        T = xt.shape[0]
        E = self.num_expert
        k = self.gate_layer.top_k
        C = max(1, int(_math.ceil(self.capacity_factor * k * T / E)))

        probs, topv, topi = self.gate_layer(xt)

        disp_comb = apply_op(
            lambda p, tv, ti: _dispatch_combine(p, tv, ti, E, C, k),
            (probs, topv, topi), name="moe_dispatch")
        dispatch, combine, l_aux = disp_comb
        self.l_aux = l_aux * getattr(self.gate_layer, "aux_loss_weight", 1.0)

        # token-major -> expert-major [E, C, d]
        from ..tensor.linalg import einsum

        xe = einsum("tec,td->ecd", dispatch.astype(xt.dtype), xt)

        if self.ep_axis is not None:
            # global_scatter: experts split across ranks, capacity concat
            xe = apply_op(
                lambda a: lax.all_to_all(a, self.ep_axis, split_axis=0,
                                         concat_axis=1, tiled=True),
                (xe,), name="moe_global_scatter")

        # run local experts on their [C_eff, d] slices
        from ..tensor import manipulation as M

        outs = [self.experts[e](xe[e]) for e in range(self.num_local_experts)]
        ye = M.stack(outs, axis=0)                           # [E_local, C_eff, d]

        if self.ep_axis is not None:
            # global_gather: back to token-major expert layout
            ye = apply_op(
                lambda a: lax.all_to_all(a, self.ep_axis, split_axis=1,
                                         concat_axis=0, tiled=True),
                (ye,), name="moe_global_gather")

        y = einsum("tec,ecd->td", combine.astype(xt.dtype), ye)
        return y.reshape(list(orig_shape))
