"""paddle.incubate parity surface (ref: python/paddle/incubate/)."""
from . import autograd  # noqa: F401
from . import moe  # noqa: F401
from .moe import MoELayer  # noqa: F401
from ..autograd.tape import no_grad  # noqa: F401


class nn:  # incubate.nn fused layers namespace (fused == XLA-fused on TPU)
    from ..nn import (  # noqa: F401
        MultiHeadAttention as FusedMultiHeadAttention,
        TransformerEncoderLayer as FusedTransformerEncoderLayer,
    )


def graph_send_recv(*args, **kwargs):
    raise NotImplementedError


def segment_sum(data, segment_ids):
    import jax

    from ..tensor.tensor import apply_op

    def _f(d, s):
        import jax.numpy as jnp

        n = int(s.max()) + 1 if hasattr(s, "max") else 1
        return jax.ops.segment_sum(d, s.astype(jnp.int32), num_segments=None)

    return apply_op(_f, (data, segment_ids), name="segment_sum")


class autotune:
    @staticmethod
    def set_config(config=None):
        pass
