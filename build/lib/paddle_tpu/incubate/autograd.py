"""Functional/prim autodiff (ref: python/paddle/incubate/autograd/primapi.py
forward_grad/grad, primops.py — the reference's experimental JAX-like primitive
system).  Here the real JAX transforms ARE the implementation: jvp/vjp/vmap/jacobian/
hessian over functions of Tensors.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor.tensor import Tensor


def _wrap_fn(func):
    """Lift a Tensor->Tensor function to raw-array space."""

    def raw(*arrays):
        outs = func(*[Tensor(a, stop_gradient=True) for a in arrays])
        if isinstance(outs, (tuple, list)):
            return tuple(o._value if isinstance(o, Tensor) else o for o in outs)
        return outs._value if isinstance(outs, Tensor) else outs

    return raw


def _raws(xs):
    xs = xs if isinstance(xs, (tuple, list)) else [xs]
    return tuple(x._value if isinstance(x, Tensor) else jnp.asarray(x) for x in xs)


def _wrap_out(o):
    if isinstance(o, tuple):
        return tuple(Tensor(i) for i in o)
    return Tensor(o)


def jvp(func, xs, v=None):
    raw = _wrap_fn(func)
    primals = _raws(xs)
    tangents = _raws(v) if v is not None else tuple(jnp.ones_like(p) for p in primals)
    out, tangent_out = jax.jvp(raw, primals, tangents)
    return _wrap_out(out), _wrap_out(tangent_out)


def vjp(func, xs, v=None):
    raw = _wrap_fn(func)
    primals = _raws(xs)
    out, vjp_fn = jax.vjp(raw, *primals)
    if v is None:
        seed = jax.tree.map(jnp.ones_like, out)
    else:
        seed = _raws(v)
        seed = seed[0] if not isinstance(out, tuple) else seed
    grads = vjp_fn(seed)
    return _wrap_out(out), _wrap_out(grads if len(grads) > 1 else grads[0])


class Jacobian:
    def __init__(self, func, xs, is_batched=False):
        raw = _wrap_fn(func)
        primals = _raws(xs)
        jac = jax.jacrev(raw, argnums=tuple(range(len(primals))))(*primals)
        self._jac = jac

    def __getitem__(self, idx):
        j = self._jac
        if isinstance(j, tuple) and len(j) == 1:
            j = j[0]
        return Tensor(jnp.asarray(j)[idx])

    @property
    def shape(self):
        j = self._jac[0] if isinstance(self._jac, tuple) and len(self._jac) == 1 else self._jac
        return list(jnp.asarray(j).shape)


class Hessian:
    def __init__(self, func, xs, is_batched=False):
        raw = _wrap_fn(func)
        primals = _raws(xs)
        self._h = jax.hessian(raw)(*primals)

    def __getitem__(self, idx):
        return Tensor(jnp.asarray(self._h)[idx])


def jacobian(func, xs, create_graph=False, allow_unused=False):
    return Jacobian(func, xs)


def hessian(func, xs, create_graph=False, allow_unused=False):
    return Hessian(func, xs)


def vmap(func, in_axes=0, out_axes=0):
    raw = _wrap_fn(func)
    mapped = jax.vmap(raw, in_axes=in_axes, out_axes=out_axes)

    def wrapper(*xs):
        return _wrap_out(mapped(*_raws(xs)))

    return wrapper


def forward_grad(outputs, inputs, grad_inputs=None):
    raise NotImplementedError("use paddle_tpu.incubate.autograd.jvp")


def grad(outputs, inputs, grad_outputs=None):
    from ..autograd.tape import grad as _grad

    return _grad(outputs, inputs, grad_outputs)
