"""Global flag registry (ref: PADDLE_DEFINE_EXPORTED_* gflags, platform/flags.cc:65;
python surface paddle.set_flags/get_flags, fluid/framework.py:7125,7149).

TPU-natively most reference flags are XLA's business; we keep the registry for the
flags that change framework behavior and accept-and-ignore unknown FLAGS_* names.
"""
from __future__ import annotations

_FLAGS: dict = {
    "FLAGS_check_nan_inf": False,        # per-op NaN/Inf checks (framework/details/nan_inf_utils.h)
    "FLAGS_allocator_strategy": "xla",   # allocator is PJRT's; value kept for parity
    "FLAGS_cudnn_deterministic": False,
    "FLAGS_use_autotune": True,
    "FLAGS_eager_delete_tensor_gb": 0.0,
    "FLAGS_benchmark": False,
    "FLAGS_paddle_tpu_flash_attention_min_seq": 1024,
    "FLAGS_paddle_tpu_default_matmul_precision": "default",
}


def set_flags(flags: dict):
    for k, v in flags.items():
        _FLAGS[k] = v


def get_flags(keys):
    if isinstance(keys, str):
        keys = [keys]
    return {k: _FLAGS.get(k) for k in keys}


def get_flag(key, default=None):
    return _FLAGS.get(key, default)
