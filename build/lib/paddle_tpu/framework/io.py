"""paddle.save / paddle.load (ref: python/paddle/framework/io.py:574,791).

Pickle-based object save with tensors converted to numpy (the reference serializes
LoDTensor payloads inside the pickle too).  Large sharded checkpoints use
paddle_tpu.distributed.checkpoint (per-process shard volumes + chunk-table
reshard-on-load) — this is the single-file object path.
"""
from __future__ import annotations

import os
import pickle

import numpy as np
import jax.numpy as jnp

from ..tensor.tensor import Tensor, Parameter


def _pack(obj):
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "data": np.asarray(obj._value),
                "stop_gradient": obj.stop_gradient, "name": obj.name,
                "is_param": isinstance(obj, Parameter)}
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_pack(v) for v in obj)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            if return_numpy:
                return obj["data"]
            cls = Parameter if obj.get("is_param") else Tensor
            t = cls(jnp.asarray(obj["data"]))
            t.name = obj.get("name", "")
            if not obj.get("is_param"):
                t.stop_gradient = obj.get("stop_gradient", True)
            return t
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy=configs.get("return_numpy", False))
