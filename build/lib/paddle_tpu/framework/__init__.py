"""paddle.framework namespace."""
from .random import seed, get_rng_key, Generator, default_generator  # noqa: F401
from .param_attr import ParamAttr  # noqa: F401
from ..core.dtypes import set_default_dtype, get_default_dtype  # noqa: F401
