"""Pipeline layer descriptions (ref: fleet/meta_parallel/parallel_layers/pp_layers.py:58,77,162
— LayerDesc/SharedLayerDesc/PipelineLayer partitioning).

TPU-native: PipelineLayer keeps the declarative stage-partitioning API; the compiled
1F1B runtime lives in pipeline_parallel.py (shard_map + ppermute instead of the
reference's Python-driven NCCL p2p loop).
"""
from __future__ import annotations

import numpy as np

from ...nn.layer.layers import Layer
from ...nn.layer.container import LayerList


class LayerDesc:
    """Ref pp_layers.py:77."""

    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Ref pp_layers.py:162 — weight-tied layers across stages (e.g. embeddings)."""

    def __init__(self, key, layer_func, forward_func=None, shared_weight_attr="weight",
                 *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """Ref pp_layers.py:58 — builds all stages; stage assignment is by segmentation.

    On TPU the whole layer list is materialized on every host (SPMD); stage placement
    happens through the compiled pipeline's scan-over-stages sharding, so
    `num_stages` only records the logical split.
    """

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, **kwargs):
        super().__init__()
        self.layers_desc = list(layers)
        self._loss_fn = loss_fn
        self._num_stages = num_stages or 1
        self._shared = {}
        built = []
        for desc in self.layers_desc:
            if isinstance(desc, SharedLayerDesc):
                if desc.layer_name in self._shared:
                    layer = self._shared[desc.layer_name]
                else:
                    layer = desc.build_layer()
                    self._shared[desc.layer_name] = layer
                built.append((layer, desc.forward_func))
            elif isinstance(desc, LayerDesc):
                built.append((desc.build_layer(), None))
            elif isinstance(desc, Layer):
                built.append((desc, None))
            elif callable(desc):
                built.append((desc, "__callable__"))
            else:
                raise TypeError(f"bad layer desc {desc!r}")
        self.run_function = built
        self._layers_list = LayerList([l for l, f in built if isinstance(l, Layer)])
        # uniform segmentation bounds (ref SegmentLayers)
        n = len(built)
        per = int(np.ceil(n / self._num_stages))
        self.segment_parts = [min(i * per, n) for i in range(self._num_stages + 1)]
        self.segment_parts[-1] = n

    def get_stage_layers(self, stage_id):
        lo, hi = self.segment_parts[stage_id], self.segment_parts[stage_id + 1]
        return self.run_function[lo:hi]

    def forward(self, x):
        for layer, ffunc in self.run_function:
            if ffunc == "__callable__":
                x = layer(x)
            elif ffunc is not None:
                x = ffunc(layer, x)
            else:
                x = layer(x)
        return x
