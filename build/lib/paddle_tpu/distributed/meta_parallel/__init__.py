"""meta_parallel (ref: fleet/meta_parallel/) — TP/PP/sharded wrappers."""
from . import mp_layers  # noqa: F401
from .mp_layers import (  # noqa: F401
    VocabParallelEmbedding, ColumnParallelLinear, RowParallelLinear,
    ParallelCrossEntropy, get_rng_state_tracker, model_parallel_random_seed,
)
from .tensor_parallel import TensorParallel  # noqa: F401
from .pp_layers import LayerDesc, SharedLayerDesc, PipelineLayer  # noqa: F401
