"""TensorParallel wrapper (ref: fleet/meta_parallel/tensor_parallel.py:25).

The reference broadcasts params within the mp group and syncs; with SPMD shardings
parameter placement is handled by ShardedTrainStep from the layer annotations, so this
wrapper is transparent at forward time.
"""
from __future__ import annotations

from ...nn.layer.layers import Layer


class TensorParallel(Layer):
    def __init__(self, layers, hcg=None, strategy=None, **kwargs):
        super().__init__()
        self._layers = layers
        self._hcg = hcg

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, sd, *args, **kwargs):
        return self._layers.set_state_dict(sd, *args, **kwargs)
