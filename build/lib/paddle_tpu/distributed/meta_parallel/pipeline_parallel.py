"""Pipeline parallel runtime (ref: fleet/meta_parallel/pipeline_parallel.py:31,82 —
host-driven 1F1B over NCCL p2p, p2p_communication.py:232).

TPU-native: the schedule is COMPILED, not Python-driven.  `pipeline_train_step` builds
one XLA program that scans microbatches through the stage dimension with
`shard_map` over the 'pp' mesh axis + `ppermute` for stage-to-stage transfer
(GPipe-style fill/drain schedule; same bubble as 1F1B, weights kept resident).  The
PipelineParallel wrapper keeps the reference's `train_batch()` API.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...nn.layer.layers import Layer
from ...tensor.tensor import Tensor
from ...autograd import tape
from ...framework import random as _random
from ..sharding_ctx import mesh_scope


class PipelineParallel(Layer):
    """train_batch(data, optimizer) parity wrapper (ref pipeline_parallel.py:154)."""

    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = strategy.pipeline_configs if strategy is not None else {}
        self.accumulate_steps = cfg.get("accumulate_steps", 1)
        self.micro_batch_size = cfg.get("micro_batch_size", 1)
        self._step = None

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        from .pipeline_schedule import PipelineTrainStep

        if self._step is None:
            loss_fn = getattr(self._layers, "_loss_fn", None)
            self._step = PipelineTrainStep(
                self._layers, loss_fn, getattr(optimizer, "inner_opt", optimizer),
                self._hcg.mesh, n_microbatch=self.accumulate_steps,
            )
        x, y = data
        return self._step(x, y)
