"""Rendezvous / control-plane KV store.

Reference: C++ `TCPStore` (paddle/fluid/distributed/store/tcp_store.h:120, store.h:26)
used by init_parallel_env for NCCL-id exchange.  On TPU the data plane needs no
rendezvous (XLA collectives ride ICI, jax.distributed has its own coordinator), so
this store serves the *control* plane only: elastic membership, barriers, and
user-level coordination.  A C++ implementation (paddle_tpu/core/native) backs the same
wire protocol when built; this pure-socket Python fallback is always available.

Wire protocol (length-prefixed): 1-byte op (S/G/A/W/D), u32 key len, key bytes,
u32 value len, value bytes.  GET on a missing key blocks until set (reference
TCPStore::wait semantics).
"""
from __future__ import annotations

import socket
import struct
import threading
import time


class Store:
    """Ref store.h:26 abstract Store."""

    def set(self, key: str, value: bytes):
        raise NotImplementedError

    def get(self, key: str) -> bytes:
        raise NotImplementedError

    def add(self, key: str, amount: int) -> int:
        raise NotImplementedError

    def wait(self, keys, timeout=None):
        raise NotImplementedError


class _KVServer(threading.Thread):
    def __init__(self, port: int):
        super().__init__(daemon=True)
        self._data: dict[str, bytes] = {}
        self._cond = threading.Condition()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(128)
        self._running = True

    def run(self):
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                try:
                    hdr = _recvn(conn, 5)
                except ConnectionError:
                    return
                op = chr(hdr[0])
                klen = struct.unpack("<I", hdr[1:5])[0]
                key = _recvn(conn, klen).decode() if klen else ""
                vlen = struct.unpack("<I", _recvn(conn, 4))[0]
                val = _recvn(conn, vlen) if vlen else b""
                # NOTE: every branch copies under the lock and sends OUTSIDE it —
                # a stalled client must not wedge the whole store
                if op == "S":
                    with self._cond:
                        self._data[key] = val
                        self._cond.notify_all()
                    _send_val(conn, b"ok")
                elif op == "A":
                    try:
                        amt = int(val.decode())
                        with self._cond:
                            cur = int(self._data.get(key, b"0").decode() or 0)
                            cur += amt
                            self._data[key] = str(cur).encode()
                            self._cond.notify_all()
                        reply = str(cur).encode()
                    except ValueError:
                        reply = b"ERR non-integer value"
                    _send_val(conn, reply)
                elif op == "G":  # blocking get
                    with self._cond:
                        while key not in self._data and self._running:
                            self._cond.wait(timeout=1.0)
                        out = self._data.get(key)
                    if out is None:
                        return  # server stopping
                    _send_val(conn, out)
                elif op == "N":  # non-blocking get: presence flag + value
                    with self._cond:
                        out = self._data.get(key)
                    _send_val(conn, b"0" if out is None else b"1" + out)
                elif op == "W":  # non-blocking check
                    with self._cond:
                        present = key in self._data
                    _send_val(conn, b"1" if present else b"0")
                elif op == "D":
                    with self._cond:
                        self._data.pop(key, None)
                    _send_val(conn, b"ok")
                elif op == "L":  # list keys with prefix
                    with self._cond:
                        keys = [k for k in self._data if k.startswith(key)]
                    _send_val(conn, "\n".join(keys).encode())
                else:
                    return
        except (ConnectionError, OSError):
            return
        finally:
            conn.close()

    def stop(self):
        self._running = False
        with self._cond:
            self._cond.notify_all()  # release blocking-G waiters
        try:
            self._sock.close()
        except OSError:
            pass


def _recvn(conn, n):
    """Read exactly n bytes or raise ConnectionError (EOF / short read)."""
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf += chunk
    return buf


def _send_val(conn, val: bytes):
    conn.sendall(struct.pack("<I", len(val)) + val)


class TCPStore(Store):
    """Ref tcp_store.h:120 — host:port KV store; `is_master` runs the server."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, is_master: bool = False,
                 world_size: int = 1, timeout: float = 300.0, use_native: bool = True):
        self._server = None
        self.timeout = timeout
        if is_master:
            self._server = self._start_server(port, use_native)
            port = self._server.port
        self.host, self.port = host, port

    @staticmethod
    def _start_server(port: int, use_native: bool):
        """Prefer the C++ server (core/native) — same wire protocol; fall back to the
        Python thread server when the toolchain is unavailable."""
        if use_native:
            try:
                from ..core.native import NativeKVServer

                return NativeKVServer(port)
            except Exception:
                pass
        srv = _KVServer(port)
        srv.start()
        return srv

    def _rpc(self, op: str, key: str, value: bytes = b"") -> bytes:
        deadline = time.time() + self.timeout
        while True:
            try:
                with socket.create_connection((self.host, self.port), timeout=self.timeout) as s:
                    kb = key.encode()
                    s.sendall(op.encode() + struct.pack("<I", len(kb)) + kb
                              + struct.pack("<I", len(value)) + value)
                    vlen = struct.unpack("<I", _recvn(s, 4))[0]
                    return _recvn(s, vlen) if vlen else b""
            except (ConnectionError, OSError):
                if time.time() > deadline:
                    raise TimeoutError(f"TCPStore rpc {op} {key} timed out")
                time.sleep(0.1)

    def set(self, key, value):
        if isinstance(value, str):
            value = value.encode()
        self._rpc("S", key, value)

    def get(self, key) -> bytes:
        return self._rpc("G", key)

    def get_nb(self, key) -> bytes | None:
        """Non-blocking get: None if the key is absent (op 'N')."""
        out = self._rpc("N", key)
        return out[1:] if out[:1] == b"1" else None

    def add(self, key, amount: int) -> int:
        out = self._rpc("A", key, str(amount).encode())
        if out.startswith(b"ERR"):
            raise ValueError(
                f"TCPStore.add({key!r}): stored value is not an integer")
        return int(out.decode())

    def check(self, key) -> bool:
        return self._rpc("W", key) == b"1"

    def delete_key(self, key):
        self._rpc("D", key)

    def keys_with_prefix(self, prefix: str) -> list[str]:
        out = self._rpc("L", prefix).decode()
        return out.split("\n") if out else []

    def wait(self, keys, timeout=None):
        keys = [keys] if isinstance(keys, str) else list(keys)
        deadline = time.time() + (timeout or self.timeout)
        for k in keys:
            while not self.check(k):
                if time.time() > deadline:
                    raise TimeoutError(f"TCPStore wait({k}) timed out")
                time.sleep(0.05)

    def barrier(self, name: str, world_size: int, timeout=None):
        n = self.add(f"__barrier__/{name}", 1)
        deadline = time.time() + (timeout or self.timeout)
        while int(self._rpc("A", f"__barrier__/{name}", b"0").decode()) < world_size:
            if time.time() > deadline:
                raise TimeoutError(f"barrier {name} timed out ({n}/{world_size})")
            time.sleep(0.05)

    def close(self):
        if self._server is not None:
            self._server.stop()
