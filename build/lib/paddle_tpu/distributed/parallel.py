"""DataParallel (ref: python/paddle/fluid/dygraph/parallel.py:419 + EagerReducer
distributed/collective/reducer.h:88).

TPU-native: no gradient bucketing/fusing machinery — wrap the model so a jitted train
step shards the batch over the mesh 'dp' axis with NamedSharding; the XLA SPMD
partitioner inserts (and overlaps) the gradient all-reduce, which is exactly the job
EagerReducer did by hand.  Eagerly (single process) it is transparent.
"""
from __future__ import annotations

from ..nn.layer.layers import Layer
from .env import init_parallel_env, get_rank, get_world_size, ParallelEnv  # noqa: F401


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25, last_comm_buffer_size=1,
                 find_unused_parameters=False, group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters
        self.group = group

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    # passthroughs so the wrapper is transparent (ref parallel.py state_dict fwd)
    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass

    @property
    def _inner_layers(self):
        return self._layers


def scale_loss(loss):
    return loss
