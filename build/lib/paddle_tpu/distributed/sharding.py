"""group_sharded_parallel API (ref: python/paddle/distributed/sharding/group_sharded.py
wrapping GroupShardedStage2/3 + GroupShardedOptimizerStage2).

TPU-native: ZeRO is a sharding-rule decision, not a hook pipeline.  The requested
stage is recorded on the model/optimizer and CONSUMED by the compiled step:
`ShardedTrainStep` (and therefore `auto_parallel.Engine` / `fleet.distributed_model`
paths built on it) picks the stage up when `zero_stage` isn't set explicitly, and
shards optimizer state (stage 1/2) or parameters too (stage 3) over the 'sharding'
mesh axis — XLA emits the reduce-scatter/all-gather the reference's GroupSharded
hooks performed manually.

The eager (non-compiled) loop has no sharding benefit on a single process; ZeRO
takes effect on the ShardedTrainStep path only, which is where the reference's
GroupSharded classes were used for real training too.
"""
from __future__ import annotations


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False, buffer_max_size=2**23,
                           segment_size=2**20, sync_comm=False):
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError(
            f"group_sharded_parallel level must be one of 'os' (ZeRO-1), "
            f"'os_g' (ZeRO-2), 'p_g_os' (ZeRO-3); got {level!r}")
    if offload:
        raise NotImplementedError(
            "offload=True (CPU offload of sharded state) is not supported on the "
            "TPU build: XLA/PJRT manages device memory, use zero stage 3 "
            "(level='p_g_os') or activation recompute to reduce footprint")
    stage = {"os": 1, "os_g": 2, "p_g_os": 3}[level]
    model._group_sharded_stage = stage
    optimizer._group_sharded_stage = stage
    if scaler is not None:
        return model, optimizer, scaler
    return model, optimizer


def save_group_sharded_model(model, output, optimizer=None):
    from ..framework.io import save

    save(model.state_dict(), output + ".pdmodel.state")
    if optimizer is not None:
        save(optimizer.state_dict(), output + ".pdopt")
