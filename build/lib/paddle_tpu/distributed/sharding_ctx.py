"""Mesh/sharding context shared by distributed layers and train steps.

The scaling-book recipe: pick a Mesh, annotate shardings on params/activations, let
XLA's SPMD partitioner insert collectives.  Layers record a `sharding_spec` tuple on
their Parameters (e.g. ColumnParallelLinear weight -> (None, 'mp')); ShardedTrainStep
turns specs into NamedShardings.  `with_sharding_constraint` is a no-op outside a mesh
context so the same layer code runs eagerly on one chip.
"""
from __future__ import annotations

import contextlib

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_current_mesh: list = []


@contextlib.contextmanager
def mesh_scope(mesh: Mesh):
    _current_mesh.append(mesh)
    try:
        with mesh:
            yield mesh
    finally:
        _current_mesh.pop()


def current_mesh() -> Mesh | None:
    if _current_mesh:
        return _current_mesh[-1]
    return None


def constraint(x, *spec):
    """Apply a sharding constraint if a mesh is active and x is traced."""
    mesh = current_mesh()
    if mesh is None or not isinstance(x, jax.core.Tracer):
        return x
    # drop axis names the mesh doesn't have (e.g. running tp code on a dp-only mesh)
    clean = tuple(s if (s is None or _axes_in(mesh, s)) else None for s in spec)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*clean)))


def _axes_in(mesh, s):
    names = mesh.axis_names
    if isinstance(s, (tuple, list)):
        return all(n in names for n in s)
    return s in names


def param_sharding(mesh: Mesh, spec):
    if spec is None:
        return NamedSharding(mesh, P())
    clean = tuple(s if (s is None or _axes_in(mesh, s)) else None for s in spec)
    return NamedSharding(mesh, P(*clean))


def annotate(param, *spec):
    """Record the logical sharding of a Parameter (consumed by ShardedTrainStep)."""
    param.sharding_spec = tuple(spec)
    return param
