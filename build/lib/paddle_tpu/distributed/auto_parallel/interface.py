"""shard_tensor / shard_op markers (ref: distributed/auto_parallel/interface.py:34,73).

In the reference these attach DistAttr to variables in a serial Program; the
completion pass (completion.py) propagates them and the partitioner rewrites the
program per rank.  TPU-native: `shard_tensor` immediately places the array with a
NamedSharding (the annotation IS the dist-attr) and records the spec so compiled
steps reuse it; propagation and program slicing are XLA GSPMD's job.
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...tensor.tensor import Tensor
from .process_mesh import ProcessMesh, get_current_process_mesh


def shard_tensor(x, process_mesh: ProcessMesh | None = None, shard_spec=None):
    """Annotate + place `x` per `shard_spec` (a list of dim names / None per axis).

    Ref interface.py:34.  Returns the same Tensor, now backed by a sharded array.
    Inside a trace it becomes a with_sharding_constraint.
    """
    pm = process_mesh or get_current_process_mesh()
    if pm is None:
        raise ValueError("shard_tensor needs a ProcessMesh (argument or context)")
    if shard_spec is None:
        shard_spec = [None] * len(x.shape)
    sharding = pm.named_sharding(shard_spec)
    t = x if isinstance(x, Tensor) else Tensor(x)
    if isinstance(t._value, jax.core.Tracer):
        t._rebind(jax.lax.with_sharding_constraint(t._value, sharding))
    else:
        t._rebind(jax.device_put(t._value, sharding))
    t.sharding_spec = tuple(s if s is None else s for s in shard_spec)
    t.process_mesh = pm
    return t


def shard_op(op_fn, process_mesh: ProcessMesh | None = None, in_shard_specs=None,
             out_shard_specs=None):
    """Ref interface.py:73 — wrap a callable so its inputs/outputs are resharded per
    the given specs on entry/exit."""
    pm = process_mesh or get_current_process_mesh()

    def wrapped(*args, **kwargs):
        if pm is not None and in_shard_specs is not None:
            args = tuple(
                shard_tensor(a, pm, spec) if isinstance(a, Tensor) and spec is not None else a
                for a, spec in zip(args, in_shard_specs)
            )
        out = op_fn(*args, **kwargs)
        if pm is not None and out_shard_specs is not None:
            outs = out if isinstance(out, (tuple, list)) else (out,)
            outs = tuple(
                shard_tensor(o, pm, spec) if isinstance(o, Tensor) and spec is not None else o
                for o, spec in zip(outs, out_shard_specs)
            )
            out = outs if isinstance(out, (tuple, list)) else outs[0]
        return out

    return wrapped


def reshard(x, process_mesh: ProcessMesh, shard_spec):
    """Explicit cross-sharding move (ref reshard.py's Resharder, collapsed to a
    device_put with the target NamedSharding — XLA plans the collective moves)."""
    return shard_tensor(x, process_mesh, shard_spec)
