"""ProcessMesh (ref: python/paddle/distributed/auto_parallel/process_mesh.py:39).

The reference's ProcessMesh is a logical N-D array of process ranks with named dims;
dist-attrs are propagated over it by the completion pass and the partitioner slices
the serial program per rank.  TPU-native: a ProcessMesh *is* a jax.sharding.Mesh over
real devices — the "completion + partition" pipeline collapses into XLA's SPMD
partitioner, driven by NamedSharding annotations (see interface.shard_tensor).
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_g_process_mesh_stack: list = []


class ProcessMesh:
    """A named logical mesh of processes/devices.

    `mesh` is a (nested) list / ndarray of global device ids; `dim_names` names each
    mesh dimension for use in shard_spec annotations.
    """

    def __init__(self, mesh=None, dim_names=None, shape=None, process_ids=None):
        if mesh is None and shape is not None:
            ids = process_ids if process_ids is not None else list(range(int(np.prod(shape))))
            mesh = np.asarray(ids).reshape(shape)
        arr = np.asarray(mesh)
        self._mesh = arr
        self._shape = tuple(arr.shape)
        self._process_ids = [int(i) for i in arr.flatten()]
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        if len(dim_names) != arr.ndim:
            raise ValueError(f"dim_names {dim_names} must match mesh ndim {arr.ndim}")
        self._dim_names = list(dim_names)
        self._jax_mesh = None

    # --- reference-shaped accessors (process_mesh.py)
    @property
    def mesh(self):
        return self._mesh

    @property
    def shape(self):
        return list(self._shape)

    @property
    def ndim(self):
        return self._mesh.ndim

    @property
    def dim_names(self):
        return self._dim_names

    @property
    def process_ids(self):
        return self._process_ids

    @property
    def processes(self):  # legacy alias
        return self._process_ids

    def get_dim_size(self, dim_name):
        return self._shape[self._dim_names.index(dim_name)]

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh) and self._shape == other._shape
                and self._process_ids == other._process_ids
                and self._dim_names == other._dim_names)

    def __ne__(self, other):
        return not self.__eq__(other)

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self._dim_names})"

    # --- TPU-native bridge
    def to_jax_mesh(self) -> Mesh:
        """Materialize as a jax Mesh over real devices.  Device i in jax.devices()
        backs logical process id i (single-host: ids index local devices; multi-host:
        the launch layer guarantees global device ordering)."""
        if self._jax_mesh is None:
            devs = jax.devices()
            if len(self._process_ids) > len(devs):
                raise ValueError(
                    f"ProcessMesh needs {len(self._process_ids)} devices, have {len(devs)}")
            arr = np.asarray([devs[i] for i in self._process_ids]).reshape(self._shape)
            self._jax_mesh = Mesh(arr, tuple(self._dim_names))
        return self._jax_mesh

    def named_sharding(self, shard_spec) -> NamedSharding:
        for s in shard_spec or []:
            if s is not None and s not in self._dim_names:
                raise ValueError(
                    f"shard_spec dim {s!r} is not one of this mesh's dim_names "
                    f"{self._dim_names}")
        return NamedSharding(self.to_jax_mesh(), P(*(shard_spec or [])))

    def __enter__(self):
        _g_process_mesh_stack.append(self)
        return self

    def __exit__(self, *exc):
        _g_process_mesh_stack.pop()
        return False


def get_current_process_mesh() -> ProcessMesh | None:
    return _g_process_mesh_stack[-1] if _g_process_mesh_stack else None
