"""`python -m paddle_tpu.distributed.launch` (ref: launch/main.py:18).

On TPU the launch topology is one process per HOST (all local chips belong to one
process and jax.distributed coordinates hosts), unlike the reference's
one-process-per-GPU — `--nproc_per_node` therefore defaults to 1 and is honored only
for CPU-simulation runs.
"""
from .main import launch, parse_args  # noqa: F401
