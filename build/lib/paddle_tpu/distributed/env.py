"""Process/bootstrap environment (ref: python/paddle/distributed/parallel.py:94
init_parallel_env + TCPStore rendezvous, launch/controllers/collective.py env vars).

TPU-native: jax.distributed.initialize handles rendezvous (its coordinator service is
the TCPStore analog); PADDLE_* env vars are honored for launch compatibility.
"""
from __future__ import annotations

import os

import jax


_initialized = False


class ParallelEnv:
    """Ref: fluid/dygraph/parallel.py ParallelEnv."""

    def __init__(self):
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        self._world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def local_rank(self):
        return int(os.environ.get("PADDLE_LOCAL_RANK", 0))

    @property
    def dev_id(self):
        return self.local_rank

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:0")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else []


def init_parallel_env():
    """Ref parallel.py:94.  Multi-host: jax.distributed.initialize from PADDLE_* env."""
    global _initialized
    if _initialized:
        return ParallelEnv()
    n_procs = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
    # NB: don't probe jax.process_count() here — it would initialize the XLA
    # backend, after which jax.distributed.initialize refuses to run
    if n_procs > 1 and not jax.distributed.is_initialized():
        coord = os.environ.get("PADDLE_MASTER") or os.environ.get("MASTER_ADDR")
        pid = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        if coord:
            jax.distributed.initialize(coordinator_address=coord, num_processes=n_procs,
                                       process_id=pid)
    _initialized = True
    return ParallelEnv()


def get_rank(group=None):
    if group is not None and hasattr(group, "rank"):
        return group.rank
    try:
        return jax.process_index()
    except Exception:
        return int(os.environ.get("PADDLE_TRAINER_ID", 0))


def get_world_size(group=None):
    if group is not None and hasattr(group, "nranks"):
        return group.nranks
    try:
        return jax.process_count()
    except Exception:
        return int(os.environ.get("PADDLE_TRAINERS_NUM", 1))


def is_initialized():
    return _initialized


def parallel_device_count():
    return len(jax.devices())
