"""Distributed sharded checkpoint with reshard-on-load.

Reference analog: `distributed/auto_parallel/dist_saver.py` (per-rank sharded
save), `distributed/auto_parallel/converter.py` (merge + re-slice when the
parallel config changes between save and load), and
`fluid/incubate/checkpoint/auto_checkpoint.py:267` (periodic auto-checkpoint
keyed for job restart).

TPU-native design: every leaf of the state pytree is a (possibly sharded)
jax.Array.  Each process writes only the addressable shards it uniquely owns
(``replica_id == 0``) into its own ``volume_p{proc}.npz``; process 0 also
writes ``index.json`` mapping each leaf to its global shape/dtype and chunk
table (offset, shape, volume, key) plus a pickled pytree skeleton.  Loading
rebuilds each leaf with ``jax.make_array_from_callback`` under the *new*
mesh/sharding: every device slice requested by the new sharding is assembled
from whatever stored chunks overlap it.  A tp=2 checkpoint therefore restores
under tp=4 (or pp=2, or a single chip) with no separate converter pass — the
chunk table plays the role of the reference's Converter merge/slice machinery.
"""
from __future__ import annotations

import json
import os
import pickle
import shutil

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = [
    "save_state", "load_state", "latest_step", "CheckpointManager",
    "save_train_state", "load_train_state",
]

_INDEX = "index.json"
_SKELETON = "skeleton.pkl"


# --------------------------------------------------------------------- pytree
class _Leaf:
    __slots__ = ("key",)

    def __init__(self, key):
        self.key = key


def _flatten(obj, prefix, out):
    """Flatten nested dict/list/tuple into {path: array-leaf}; returns skeleton."""
    if isinstance(obj, dict):
        return {k: _flatten(v, f"{prefix}/{k}" if prefix else str(k), out)
                for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        seq = [_flatten(v, f"{prefix}/{i}" if prefix else str(i), out)
               for i, v in enumerate(obj)]
        return type(obj)(seq)
    if hasattr(obj, "shape") and hasattr(obj, "dtype"):
        out[prefix] = obj
        return _Leaf(prefix)
    return obj  # plain scalar/str — lives in the skeleton


def _unflatten(skel, leaves):
    if isinstance(skel, _Leaf):
        return leaves[skel.key]
    if isinstance(skel, dict):
        return {k: _unflatten(v, leaves) for k, v in skel.items()}
    if isinstance(skel, (list, tuple)):
        return type(skel)(_unflatten(v, leaves) for v in skel)
    return skel


def _to_storable(data):
    """npz can't round-trip ml_dtypes (bfloat16/float8 come back as raw void):
    store such chunks as flat uint8 bytes; _from_storable reinterprets."""
    if data.dtype.kind == "V" or data.dtype.name.startswith(("bfloat", "float8")):
        return np.ascontiguousarray(data).reshape(-1).view(np.uint8)
    return data


def _from_storable(data, dtype, sizes):
    dtype = np.dtype(dtype)
    if data.dtype == np.uint8 and dtype != np.uint8:
        return data.view(dtype).reshape(sizes)
    return data


def _norm_index(index, shape):
    """Normalize a shard index (tuple of slices) to (starts, sizes)."""
    starts, sizes = [], []
    for sl, dim in zip(index, shape):
        lo, hi, _ = sl.indices(dim)
        starts.append(lo)
        sizes.append(hi - lo)
    return starts, sizes


# ----------------------------------------------------------------------- save
def _step_dir(path, step):
    return os.path.join(path, f"step_{int(step):010d}") if step is not None else path


def save_state(path, state, step=None, process_index=None, process_count=None):
    """Write `state` (a pytree of arrays) as a sharded checkpoint.

    Each process saves only shards it owns; callers on multi-host must call this
    on every process (the volumes are disjoint).  Returns the checkpoint dir.
    """
    proc = jax.process_index() if process_index is None else process_index
    nprocs = jax.process_count() if process_count is None else process_count
    if step is None and (nprocs > 1 or proc > 0):
        # without a step there is no generation marker to tell a fresh sidecar
        # from a stale one left by a previous, wider save
        raise ValueError(
            "save_state(step=None) is single-process only; multi-host saves "
            "must pass a step so each save generation is distinguishable")
    ckpt = _step_dir(path, step)
    os.makedirs(ckpt, exist_ok=True)

    leaves: dict = {}
    skel = _flatten(state, "", leaves)

    chunks = {}      # key -> np array to store in this process's volume
    index = {}       # leaf path -> {shape, dtype, chunks: [...]}
    vol_name = f"volume_p{proc:05d}.npz"
    for key, arr in leaves.items():
        if isinstance(arr, jax.Array):
            shards = [s for s in arr.addressable_shards if s.replica_id == 0]
            global_shape = arr.shape
        else:
            shards = None
            global_shape = tuple(np.asarray(arr).shape)

        entry = {"shape": list(global_shape),
                 "dtype": str(np.dtype(arr.dtype) if hasattr(arr, "dtype") else np.asarray(arr).dtype),
                 "chunks": []}
        if shards is None:
            if proc == 0:
                ck = f"{key}#0"
                chunks[ck] = _to_storable(np.asarray(arr))
                entry["chunks"].append({"volume": vol_name, "key": ck,
                                        "offset": [0] * len(global_shape),
                                        "sizes": list(global_shape)})
        else:
            seen = set()
            for i, sh in enumerate(shards):
                starts, sizes = _norm_index(sh.index, global_shape)
                sig = tuple(starts)
                if sig in seen:   # same slice on several local devices (replicated axis)
                    continue
                seen.add(sig)
                ck = f"{key}#{i}"
                chunks[ck] = _to_storable(np.asarray(sh.data))
                entry["chunks"].append({"volume": vol_name, "key": ck,
                                        "offset": starts, "sizes": sizes})
        index[key] = entry

    if chunks:
        np.savez(os.path.join(ckpt, vol_name), **chunks)

    if proc == 0:
        idx_path = os.path.join(ckpt, _INDEX)
        # drop stale artifacts from a previous save generation: step=None dirs
        # are single-process (enforced above), so ALL sidecars/foreign volumes
        # are stale; step dirs drop sidecars whose recorded step mismatches
        for name in os.listdir(ckpt):
            full = os.path.join(ckpt, name)
            if name.startswith("index_p") and name.endswith(".json"):
                if step is None:
                    os.remove(full)
                    continue
                try:
                    with open(full) as f:
                        if json.load(f).get("step") != step:
                            os.remove(full)
                except (OSError, ValueError):
                    # unreadable != stale: sidecars are written atomically
                    # (tmp + rename), so this is a transient read race — leave
                    # it; _read_index skips mismatched/garbled sidecars anyway
                    pass
            elif step is None and name.startswith("volume_p") and \
                    name != vol_name and name.endswith(".npz"):
                os.remove(full)
        with open(idx_path, "w") as f:
            json.dump({"version": 1, "step": step, "leaves": index}, f)
        with open(os.path.join(ckpt, _SKELETON), "wb") as f:
            pickle.dump(skel, f)
        if step is not None:
            tmp = os.path.join(path, ".latest.tmp")
            with open(tmp, "w") as f:
                f.write(str(int(step)))
            os.replace(tmp, os.path.join(path, "latest"))
    elif chunks:
        # non-zero process: publish our chunk table so proc 0 can merge it, or —
        # shared-filesystem case — just append via a sidecar the loader also reads.
        side = os.path.join(ckpt, f"index_p{proc:05d}.json")
        tmp_side = side + ".tmp"
        with open(tmp_side, "w") as f:
            json.dump({"step": step, "leaves": index}, f)
        os.replace(tmp_side, side)  # atomic: readers never see a partial file
    return ckpt


# ----------------------------------------------------------------------- load
def latest_step(path):
    p = os.path.join(path, "latest")
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return int(f.read().strip())


class _VolumeCache:
    def __init__(self, ckpt):
        self.ckpt = ckpt
        self._open = {}

    def get(self, volume, key):
        if volume not in self._open:
            self._open[volume] = np.load(os.path.join(self.ckpt, volume))
        return self._open[volume][key]


def _read_index(ckpt):
    with open(os.path.join(ckpt, _INDEX)) as f:
        index = json.load(f)
    leaves = index["leaves"]
    # merge sidecar indices from other processes (shared filesystem); a sidecar
    # from a different save generation (mismatched step) is stale — skip it
    for name in sorted(os.listdir(ckpt)):
        if name.startswith("index_p") and name.endswith(".json"):
            try:
                with open(os.path.join(ckpt, name)) as f:
                    side_doc = json.load(f)
            except (OSError, ValueError):
                continue  # transient write race; chunk coverage check catches real gaps
            if side_doc.get("step") != index.get("step"):
                continue
            side = side_doc["leaves"]
            for k, e in side.items():
                if k not in leaves:
                    leaves[k] = e
                    continue
                have = {tuple(c["offset"]) for c in leaves[k]["chunks"]}
                leaves[k]["chunks"] += [c for c in e["chunks"]
                                        if tuple(c["offset"]) not in have]
    return index


def _assemble(entry, req_slices, vols):
    """Assemble the requested slice of a leaf from overlapping stored chunks."""
    shape = entry["shape"]
    starts, sizes = _norm_index(req_slices, shape)
    out = np.empty(sizes, dtype=np.dtype(entry["dtype"]))
    covered = 0
    for ch in entry["chunks"]:
        off, csz = ch["offset"], ch["sizes"]
        lo = [max(s, o) for s, o in zip(starts, off)]
        hi = [min(s + z, o + c) for s, z, o, c in zip(starts, sizes, off, csz)]
        if any(h <= l for l, h in zip(lo, hi)):
            continue
        src = tuple(slice(l - o, h - o) for l, h, o in zip(lo, hi, off))
        dst = tuple(slice(l - s, h - s) for l, h, s in zip(lo, hi, starts))
        data = _from_storable(vols.get(ch["volume"], ch["key"]),
                              entry["dtype"], csz)
        out[dst] = data[src]
        covered += int(np.prod([h - l for l, h in zip(lo, hi)]))
    want = int(np.prod(sizes)) if sizes else 1
    if covered < want:
        raise ValueError(
            f"checkpoint chunk table does not cover the requested slice "
            f"({covered}/{want} elements) — was the checkpoint written by all hosts?")
    return out


def load_state(path, step=None, shardings=None, template=None):
    """Load a checkpoint, resharding each leaf onto a new mesh if asked.

    ``shardings`` may be: None (leaves come back as host jnp arrays), a pytree
    matching the saved structure whose leaves are ``jax.sharding.Sharding`` or
    None, or a callable ``(leaf_path, shape) -> Sharding | None``.
    """
    if step is None and os.path.exists(os.path.join(path, "latest")):
        step = latest_step(path)
    ckpt = _step_dir(path, step)
    index = _read_index(ckpt)
    with open(os.path.join(ckpt, _SKELETON), "rb") as f:
        skel = pickle.load(f)

    shard_leaves = {}
    if shardings is not None and not callable(shardings):
        def _walk(obj, prefix):
            if isinstance(obj, jax.sharding.Sharding):
                shard_leaves[prefix] = obj
            elif isinstance(obj, dict):
                for k, v in obj.items():
                    _walk(v, f"{prefix}/{k}" if prefix else str(k))
            elif isinstance(obj, (list, tuple)):
                for i, v in enumerate(obj):
                    _walk(v, f"{prefix}/{i}" if prefix else str(i))
        _walk(shardings, "")

    vols = _VolumeCache(ckpt)
    leaves = {}
    for key, entry in index["leaves"].items():
        shape = tuple(entry["shape"])
        dtype = np.dtype(entry["dtype"])
        if callable(shardings):
            sh = shardings(key, shape)
        else:
            sh = shard_leaves.get(key)
        if isinstance(sh, _Leaf):   # sharding pytree had a plain array here
            sh = None
        if sh is None:
            full = _assemble(entry, tuple(slice(0, d) for d in shape), vols)
            leaves[key] = jnp.asarray(full)
        else:
            leaves[key] = jax.make_array_from_callback(
                shape, sh, lambda idx, e=entry: _assemble(e, idx, vols))
    return _unflatten(skel, leaves)


# ------------------------------------------------------------------- manager
class CheckpointManager:
    """Step-indexed checkpoint dir with retention (ref auto_checkpoint.py:267
    TrainEpochRange: periodic snapshot + restore-latest on job restart).
    """

    def __init__(self, path, keep=3, save_interval=1):
        self.path = path
        self.keep = keep
        self.save_interval = max(1, int(save_interval))
        os.makedirs(path, exist_ok=True)

    def should_save(self, step):
        return step % self.save_interval == 0

    def save(self, step, state, force=False):
        if not force and not self.should_save(step):
            return None
        ckpt = save_state(self.path, state, step=step)
        if jax.process_index() == 0:
            self._gc()
        return ckpt

    def _gc(self):
        steps = self.all_steps()
        for s in steps[:-self.keep] if self.keep else []:
            shutil.rmtree(os.path.join(self.path, f"step_{s:010d}"),
                          ignore_errors=True)

    def all_steps(self):
        out = []
        for name in os.listdir(self.path):
            if name.startswith("step_"):
                try:
                    out.append(int(name[5:]))
                except ValueError:
                    pass
        return sorted(out)

    def latest_step(self):
        return latest_step(self.path)

    def restore(self, step=None, shardings=None):
        return load_state(self.path, step=step, shardings=shardings)


# --------------------------------------------------- train-state convenience
def _model_state(model, optimizer=None, train_step=None, step=None):
    params, buffers = model.functional_state()
    state = {"params": dict(params), "buffers": dict(buffers),
             "meta": {"step": step}}
    if train_step is not None and getattr(train_step, "_opt_state", None) is not None:
        state["opt_state"] = train_step._opt_state
        state["meta"]["step_count"] = train_step.optimizer._step_count
    elif optimizer is not None:
        named = {id(p): k for k, p in model.named_parameters()}
        state["opt_state"] = {
            named[pid]: st for pid, st in optimizer._accumulators.items()
            if pid in named
        }
        state["meta"]["step_count"] = optimizer._step_count
    return state


def save_train_state(path, model, optimizer=None, train_step=None, step=None):
    """Sharded save of model params/buffers + optimizer state.

    Works for the eager optimizer (`_accumulators`) and for
    ShardedTrainStep-managed state (arrays stay sharded; each process writes
    its own shards).
    """
    return save_state(path, _model_state(model, optimizer, train_step, step),
                      step=step)


def load_train_state(path, model, optimizer=None, train_step=None, step=None):
    """Restore params/buffers (+optimizer state) into `model`, resharding onto
    `train_step`'s mesh if given (the tp=2 → tp=4 path)."""
    shardings = None
    if train_step is not None:
        pshard, oshard = train_step._specs()
        rep = NamedSharding(train_step.mesh, P())

        def shardings(key, shape):
            if key.startswith("params/"):
                return pshard.get(key[len("params/"):], rep)
            if key.startswith("buffers/"):
                return rep
            if key.startswith("opt_state/"):
                rest = key[len("opt_state/"):]
                name = rest.split("/")[0]
                sh = oshard.get(name)
                named = dict(model.named_parameters())
                if sh is not None and name in named and \
                        tuple(shape) == tuple(named[name]._value.shape):
                    return sh
                return rep
            return None

    state = load_state(path, step=step, shardings=shardings)
    model.load_functional_state(state.get("params"), state.get("buffers"))
    meta = state.get("meta", {})
    if train_step is not None and "opt_state" in state:
        train_step._opt_state = state["opt_state"]
        if train_step._jitted is None:
            # params were just rebound host-side; _init will re-place them
            pass
        train_step.optimizer._step_count = int(meta.get("step_count", 0) or 0)
    elif optimizer is not None and "opt_state" in state:
        named = dict(model.named_parameters())
        for name, st in state["opt_state"].items():
            if name in named:
                optimizer._accumulators[id(named[name])] = st
        optimizer._step_count = int(meta.get("step_count", 0) or 0)
    return meta
