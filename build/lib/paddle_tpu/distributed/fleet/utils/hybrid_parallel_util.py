"""Hybrid-parallel parameter/gradient sync helpers.

Reference: `fleet/utils/hybrid_parallel_util.py` (broadcast_dp_parameters,
fused_allreduce_gradients, ...).  Under SPMD/pjit these are no-ops or thin
mesh-collective wrappers: XLA's partitioner inserts the gradient all-reduces the
reference did with EagerReducer hooks, and parameter consistency across data-parallel
replicas is a property of replicated NamedShardings rather than an explicit broadcast.
The functions exist so reference-shaped training scripts run unchanged; eagerly they
re-place tensors with the replicated sharding to force consistency.
"""
from __future__ import annotations

from ...collective import ReduceOp, all_reduce, broadcast


def broadcast_input_data(hcg, *inputs, **kwargs):
    return inputs if len(inputs) != 1 else inputs[0]


def _broadcast_params(model, group):
    for _, p in model.named_parameters():
        broadcast(p, src=0, group=group)


def broadcast_mp_parameters(model, hcg):
    _broadcast_params(model, hcg.get_model_parallel_group())


def broadcast_dp_parameters(model, hcg):
    _broadcast_params(model, hcg.get_data_parallel_group())


def broadcast_sharding_parameters(model, hcg):
    _broadcast_params(model, hcg.get_sharding_parallel_group())


def fused_allreduce_gradients(parameter_list, hcg):
    """Ref: fused_allreduce_gradients — dp-group grad allreduce.  The reference
    (_apply_collective_grads_eager, hybrid_parallel_util.py:83) scales grads by
    1/nranks before the allreduce, i.e. the contract is an AVERAGE over the dp
    group; ReduceOp.AVG (lax.pmean in-trace) matches that."""
    from ....tensor.tensor import Tensor

    group = hcg.get_data_parallel_group() if hcg is not None else None
    for p in parameter_list:
        if getattr(p, "_grad", None) is not None:
            out = all_reduce(Tensor(p._grad, stop_gradient=True),
                             op=ReduceOp.AVG, group=group)
            p._grad = out._value if isinstance(out, Tensor) else out


def sharding_reduce_gradients(parameter_list, hcg):
    fused_allreduce_gradients(parameter_list, hcg)
