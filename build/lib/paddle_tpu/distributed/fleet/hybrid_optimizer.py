"""HybridParallelOptimizer (ref: fleet/meta_parallel/dygraph_optimizer/
hybrid_parallel_optimizer.py:172 — TP-aware grad clip + inner optimizer).

With SPMD shardings the global-norm clip is already global (XLA reduces over all
shards), so this wrapper mostly forwards; it keeps the reference surface
(inner_opt, _dp_enable etc.) for script parity.
"""
from __future__ import annotations

from ...optimizer.optimizer import Optimizer


class HybridParallelOptimizer:
    def __init__(self, optimizer: Optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy

    @property
    def inner_opt(self):
        return self._inner_opt

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        return self._inner_opt.minimize(loss, startup_program, parameters, no_grad_set)

    def clear_grad(self, set_to_zero=False):
        self._inner_opt.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        self._inner_opt.set_state_dict(sd)


class HybridParallelGradScaler:
    def __init__(self, scaler, hcg=None):
        self._scaler = scaler

    def __getattr__(self, item):
        return getattr(self._scaler, item)
