"""Elastic training (ref: fleet/elastic/__init__.py:48 launch_elastic,
fleet/elastic/manager.py:131 ElasticManager)."""
from .manager import ElasticManager, ElasticStatus, enable_elastic, launch_elastic  # noqa: F401
