"""fleet facade (ref: python/paddle/distributed/fleet/base/fleet_base.py:144,211,890,947
and DistributedStrategy fleet/base/distributed_strategy.py:110 over
framework/distributed_strategy.proto's 28 messages).

fleet.init builds the HybridCommunicateGroup Mesh from strategy.hybrid_configs;
distributed_model/distributed_optimizer return wrappers whose compiled path is
ShardedTrainStep (dp/mp/sharding via NamedSharding, pp via the compiled pipeline).
"""
from __future__ import annotations

from ..topology import (
    CommunicateTopology,
    HybridCommunicateGroup,
    get_hybrid_communicate_group,
    set_hybrid_communicate_group,
)
from ..env import init_parallel_env, get_rank, get_world_size
from ..parallel import DataParallel
from .. import collective as _collective
from ...optimizer.optimizer import Optimizer
from .. import meta_parallel  # noqa: F401
from . import utils  # noqa: F401
from . import elastic  # noqa: F401
from ..meta_parallel import mp_layers  # noqa: F401
from ..meta_parallel.mp_layers import (  # noqa: F401 (fleet.meta_parallel re-exports)
    VocabParallelEmbedding, ColumnParallelLinear, RowParallelLinear, ParallelCrossEntropy,
    get_rng_state_tracker,
)


class DistributedStrategy:
    """Ref distributed_strategy.py:110 — the single knob surface."""

    def __init__(self):
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {}
        self.hybrid_configs = {
            "dp_degree": 1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sharding_degree": 1,
            "sep_degree": 1,
        }
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.lamb = False
        self.lars = False
        self.dgc = False
        self.localsgd = False
        self.gradient_scale_configs = {"scale_strategy": "avg"}
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.heter_ccl_mode = False
        self.without_graph_optimization = False

    def __repr__(self):
        return f"DistributedStrategy(hybrid={self.hybrid_configs})"


class _Fleet:
    def __init__(self):
        self._strategy = None
        self._hcg = None
        self._is_initialized = False
        self.worker_num_ = 1

    def init(self, role_maker=None, is_collective=False, strategy=None, log_level="INFO"):
        """Ref fleet_base.py:211."""
        init_parallel_env()
        self._strategy = strategy or DistributedStrategy()
        hc = self._strategy.hybrid_configs
        self._hcg = HybridCommunicateGroup(
            dp=hc.get("dp_degree", 1), mp=hc.get("mp_degree", 1),
            pp=hc.get("pp_degree", 1), sharding=hc.get("sharding_degree", 1),
            sep=hc.get("sep_degree", 1),
        )
        set_hybrid_communicate_group(self._hcg)
        self._is_initialized = True
        return self

    def is_first_worker(self):
        return get_rank() == 0

    def worker_index(self):
        return get_rank()

    def worker_num(self):
        return get_world_size()

    def is_worker(self):
        return True

    def worker_endpoints(self, to_string=False):
        import os

        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
        return ",".join(eps) if to_string else eps

    def barrier_worker(self):
        _collective.barrier()

    def get_hybrid_communicate_group(self):
        return self._hcg

    @property
    def _hcg_prop(self):
        return self._hcg

    def distributed_model(self, model):
        """Ref fleet_base.py:947,1052-1077 — wrap per strategy.  With SPMD shardings
        the tp/sharding wrappers are no-ops (annotations live on the layers); pp wraps
        into the compiled PipelineParallel; pure-dp wraps in DataParallel."""
        if self._hcg is not None and self._hcg.get_pipe_parallel_world_size() > 1:
            from ..meta_parallel.pipeline_parallel import PipelineParallel

            if not isinstance(model, PipelineParallel):
                model = PipelineParallel(model, self._hcg, self._strategy)
            return model
        if self._hcg is not None and self._hcg.get_model_parallel_world_size() > 1:
            from ..meta_parallel.tensor_parallel import TensorParallel

            return TensorParallel(model, self._hcg, strategy=self._strategy)
        return DataParallel(model)

    def distributed_optimizer(self, optimizer, strategy=None):
        """Ref fleet_base.py:890 → HybridParallelOptimizer."""
        from .hybrid_optimizer import HybridParallelOptimizer

        return HybridParallelOptimizer(optimizer, self._hcg, self._strategy)

    # PS-mode stubs (SURVEY.md §7.4: parameter-server stack is an explicit non-goal)
    def is_server(self):
        return False

    def init_worker(self):
        pass

    def init_server(self, *args, **kwargs):
        raise NotImplementedError("parameter-server mode is out of scope for the TPU build")

    def run_server(self):
        raise NotImplementedError("parameter-server mode is out of scope for the TPU build")

    def stop_worker(self):
        pass

    def save_inference_model(self, *args, **kwargs):
        pass

    def save_persistables(self, *args, **kwargs):
        pass


fleet = _Fleet()

# module-level function aliases (paddle.distributed.fleet.init style)
init = fleet.init
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
worker_index = fleet.worker_index
worker_num = fleet.worker_num
is_first_worker = fleet.is_first_worker
get_hybrid_communicate_group_fn = fleet.get_hybrid_communicate_group


class UserDefinedRoleMaker:
    def __init__(self, *args, **kwargs):
        pass


class PaddleCloudRoleMaker:
    def __init__(self, is_collective=False, **kwargs):
        self._is_collective = is_collective
