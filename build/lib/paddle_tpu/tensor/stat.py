"""Statistics re-exports (ref: python/paddle/tensor/stat.py)."""
from .math import mean, std, var, median, quantile, nanmean, nansum  # noqa: F401
