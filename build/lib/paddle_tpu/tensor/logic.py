"""Comparison / logical ops (ref: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .tensor import Tensor, apply_op, _unwrap


def _cmp(name, fn):
    def op(x, y, name=None):
        return apply_op(fn, (x, y), name=name)

    op.__name__ = name
    return op


equal = _cmp("equal", lambda a, b: jnp.equal(a, b))
not_equal = _cmp("not_equal", lambda a, b: jnp.not_equal(a, b))
greater_than = _cmp("greater_than", lambda a, b: jnp.greater(a, b))
greater_equal = _cmp("greater_equal", lambda a, b: jnp.greater_equal(a, b))
less_than = _cmp("less_than", lambda a, b: jnp.less(a, b))
less_equal = _cmp("less_equal", lambda a, b: jnp.less_equal(a, b))
logical_and = _cmp("logical_and", lambda a, b: jnp.logical_and(a, b))
logical_or = _cmp("logical_or", lambda a, b: jnp.logical_or(a, b))
logical_xor = _cmp("logical_xor", lambda a, b: jnp.logical_xor(a, b))
bitwise_and = _cmp("bitwise_and", lambda a, b: jnp.bitwise_and(a, b))
bitwise_or = _cmp("bitwise_or", lambda a, b: jnp.bitwise_or(a, b))
bitwise_xor = _cmp("bitwise_xor", lambda a, b: jnp.bitwise_xor(a, b))


def logical_not(x, name=None):
    return apply_op(lambda a: jnp.logical_not(a), (x,), name="logical_not")


def bitwise_not(x, name=None):
    return apply_op(lambda a: jnp.bitwise_not(a), (x,), name="bitwise_not")


def equal_all(x, y, name=None):
    return apply_op(lambda a, b: jnp.array_equal(a, b), (x, y), name="equal_all")


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op(
        lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        (x, y),
        name="allclose",
    )


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op(
        lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        (x, y),
        name="isclose",
    )


def is_tensor(x):
    return isinstance(x, Tensor)


def is_empty(x):
    return Tensor(jnp.asarray(x.size == 0))


def all(x, axis=None, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply_op(lambda v: jnp.all(v, axis=ax, keepdims=keepdim), (x,), name="all")


def any(x, axis=None, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply_op(lambda v: jnp.any(v, axis=ax, keepdims=keepdim), (x,), name="any")
