"""Search / sort ops (ref: python/paddle/tensor/search.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .tensor import Tensor, apply_op, _unwrap


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    def _f(v):
        if axis is None:
            return jnp.argmax(v.reshape(-1))
        out = jnp.argmax(v, axis=axis)
        if keepdim:
            out = jnp.expand_dims(out, axis)
        return out

    return apply_op(_f, (x,), name="argmax")


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    def _f(v):
        if axis is None:
            return jnp.argmin(v.reshape(-1))
        out = jnp.argmin(v, axis=axis)
        if keepdim:
            out = jnp.expand_dims(out, axis)
        return out

    return apply_op(_f, (x,), name="argmin")


def argsort(x, axis=-1, descending=False, name=None):
    def _f(v):
        out = jnp.argsort(v, axis=axis)
        if descending:
            out = jnp.flip(out, axis=axis)
        return out

    return apply_op(_f, (x,), name="argsort")


def sort(x, axis=-1, descending=False, name=None):
    def _f(v):
        out = jnp.sort(v, axis=axis)
        if descending:
            out = jnp.flip(out, axis=axis)
        return out

    return apply_op(_f, (x,), name="sort")


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k.item())

    def _f(v):
        ax = v.ndim - 1 if axis is None else axis % v.ndim
        moved = jnp.moveaxis(v, ax, -1)
        src = moved if largest else -moved
        vals, idx = jax.lax.top_k(src, k)
        if not largest:
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx, -1, ax).astype(jnp.int64)

    return apply_op(_f, (x,), name="topk")


def kthvalue(x, k, axis=None, keepdim=False):
    def _f(v):
        ax = v.ndim - 1 if axis is None else axis % v.ndim
        moved = jnp.moveaxis(v, ax, -1)
        vals = jnp.sort(moved, axis=-1)[..., k - 1]
        idx = jnp.argsort(moved, axis=-1)[..., k - 1]
        if keepdim:
            vals, idx = jnp.expand_dims(vals, ax), jnp.expand_dims(idx, ax)
        return vals, idx.astype(jnp.int64)

    return apply_op(_f, (x,), name="kthvalue")


def mode(x, axis=-1, keepdim=False):
    def _f(v):
        moved = jnp.moveaxis(v, axis, -1)
        s = jnp.sort(moved, axis=-1)
        # run-length trick: count equal runs
        eq = (s[..., 1:] == s[..., :-1]).astype(jnp.int32)
        run = jnp.concatenate([jnp.zeros_like(s[..., :1], jnp.int32), eq], -1)
        run = jax.lax.associative_scan(lambda a, b: (a + b) * (b > 0) + b * (b == 0), run, axis=-1) if False else _runlen(run)
        best = jnp.argmax(run, axis=-1)
        vals = jnp.take_along_axis(s, best[..., None], axis=-1)[..., 0]
        idx = jnp.argmax(jnp.moveaxis(v, axis, -1) == vals[..., None], axis=-1)
        if keepdim:
            vals, idx = jnp.expand_dims(vals, axis), jnp.expand_dims(idx, axis)
        return vals, idx.astype(jnp.int64)

    def _runlen(r):
        def step(carry, x):
            c = (carry + x) * x
            return c, c

        _, out = jax.lax.scan(step, jnp.zeros(r.shape[:-1], r.dtype), jnp.moveaxis(r, -1, 0))
        return jnp.moveaxis(out, 0, -1)

    return apply_op(_f, (x,), name="mode")


def nonzero(x, as_tuple=False):
    # dynamic output shape -> host eager
    v = np.asarray(_unwrap(x))
    res = np.nonzero(v)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(r[:, None])) for r in res)
    return Tensor(jnp.asarray(np.stack(res, axis=1).astype(np.int64)))


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply_op(lambda c, a, b: jnp.where(c, a, b), (condition, x, y), name="where")


def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    side = "right" if right else "left"

    def _f(s, v):
        if s.ndim == 1:
            out = jnp.searchsorted(s, v, side=side)
        else:
            out = jax.vmap(lambda a, b: jnp.searchsorted(a, b, side=side))(
                s.reshape(-1, s.shape[-1]), v.reshape(-1, v.shape[-1])
            ).reshape(v.shape)
        return out.astype(jnp.int32 if out_int32 else jnp.int64)

    return apply_op(_f, (sorted_sequence, values), name="searchsorted")


def bucketize(x, sorted_sequence, out_int32=False, right=False):
    return searchsorted(sorted_sequence, x, out_int32, right)


def index_of_max(x):  # helper used by metrics
    return argmax(x)


def masked_select(x, mask, name=None):
    from . import manipulation

    return manipulation.masked_select(x, mask)
