"""model summary / flops (ref: python/paddle/hapi/model_summary.py, hapi/dynamic_flops.py)."""
from __future__ import annotations

import numpy as np

from ..nn.layer.layers import Layer
from ..tensor.tensor import Tensor
from ..tensor import creation


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    rows = []
    hooks = []

    def register(layer):
        def hook(l, inputs, outputs):
            n_params = sum(int(np.prod(p.shape)) for p in l._parameters.values() if p is not None)
            out_shape = outputs.shape if isinstance(outputs, Tensor) else "-"
            rows.append((type(l).__name__, str(out_shape), n_params))

        if not layer._sub_layers:
            hooks.append(layer.register_forward_post_hook(hook))

    net.apply(register)
    try:
        if input is None and input_size is not None:
            sizes = [input_size] if isinstance(input_size, tuple) else input_size
            if isinstance(input_size, tuple) and input_size and isinstance(input_size[0], int):
                sizes = [input_size]
            inputs = [creation.zeros([s if s is not None else 1 for s in sz],
                                     (dtypes[i] if isinstance(dtypes, (list, tuple)) else dtypes) or "float32")
                      for i, sz in enumerate(sizes)]
            was_training = net.training
            net.eval()
            net(*inputs)
            if was_training:
                net.train()
    finally:
        for h in hooks:
            h.remove()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters() if not p.stop_gradient)
    lines = ["-" * 70, f"{'Layer':<28}{'Output Shape':<28}{'Param #':<12}", "=" * 70]
    for name, shape, n in rows:
        lines.append(f"{name:<28}{shape:<28}{n:<12}")
    lines += ["=" * 70, f"Total params: {total:,}", f"Trainable params: {trainable:,}",
              f"Non-trainable params: {total - trainable:,}", "-" * 70]
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Rough analytic flops via XLA cost analysis when available."""
    import jax

    try:
        x = np.zeros(input_size, np.float32)
        params, buffers = net.functional_state()

        def f(params, buffers, x):
            restore = net.bind_functional_state(params, buffers)
            try:
                out = net(Tensor(x))
            finally:
                restore()
            return out._value if isinstance(out, Tensor) else out

        lowered = jax.jit(f).lower(params, buffers, x)
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        return int(cost.get("flops", 0))
    except Exception:
        return 0
