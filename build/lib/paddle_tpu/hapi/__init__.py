"""hapi: paddle.Model high-level API (ref: python/paddle/hapi/model.py:915 Model,
.fit:1574, callbacks, summary)."""
from .model import Model  # noqa: F401
from .summary import summary, flops  # noqa: F401
from . import callbacks  # noqa: F401
