"""Subprocess DataLoader workers with shared-memory transport.

Reference analog: `_DataLoaderIterMultiProcess` + `_worker_loop`
(fluid/dataloader/dataloader_iter.py:342, worker.py) — N forked workers pull
(ordinal, indices) tasks from an index queue, run `dataset[i]` + collate with a
REAL extra core each (no GIL), and return batches through POSIX shared memory;
the parent strictly preserves sampler order via an `_rcvd_idx`-style reorder
cache.  This is the path that feeds JPEG-decode-heavy input pipelines at
ImageNet rates; pure-numpy datasets can also use the in-process thread ring
(`_NativeWorkerIter`).

Workers never touch JAX: payloads are numpy; the training step's H2D copy is
async under PJRT.
"""
from __future__ import annotations

import multiprocessing as mp
import os
import pickle
import queue as _queue
import traceback
from multiprocessing import shared_memory

import numpy as np

_WORKER_INFO = None


class WorkerInfo:
    """Ref: fluid/dataloader/worker.py WorkerInfo."""

    def __init__(self, id, num_workers, seed, dataset):
        self.id = id
        self.num_workers = num_workers
        self.seed = seed
        self.dataset = dataset


def get_worker_info():
    return _WORKER_INFO


# --------------------------------------------------------------- shm codec
def _pack(obj, shms):
    """Replace numpy arrays in a collated pytree with shared-memory refs."""
    if isinstance(obj, np.ndarray) and obj.nbytes > 0:
        shm = shared_memory.SharedMemory(create=True, size=obj.nbytes)
        np.ndarray(obj.shape, obj.dtype, buffer=shm.buf)[...] = obj
        shms.append(shm)
        return {"__shm__": shm.name, "shape": obj.shape, "dtype": str(obj.dtype)}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_pack(v, shms) for v in obj)
    if isinstance(obj, dict):
        return {k: _pack(v, shms) for k, v in obj.items()}
    return obj


def _unpack(obj):
    if isinstance(obj, dict) and "__shm__" in obj:
        shm = shared_memory.SharedMemory(name=obj["__shm__"])
        try:
            view = np.ndarray(obj["shape"], np.dtype(obj["dtype"]), buffer=shm.buf)
            out = view.copy()
        finally:
            shm.close()
            shm.unlink()
        return out
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unpack(v) for v in obj)
    if isinstance(obj, dict):
        return {k: _unpack(v) for k, v in obj.items()}
    return obj


def _worker_loop(dataset, collate_fn, index_q, result_q, worker_id, num_workers,
                 seed, worker_init_fn, use_shared_memory):
    """Ref worker.py _worker_loop: task pull -> fetch -> collate -> send."""
    global _WORKER_INFO
    _WORKER_INFO = WorkerInfo(worker_id, num_workers, seed + worker_id, dataset)
    np.random.seed((seed + worker_id) % (2 ** 31))
    if worker_init_fn is not None:
        try:
            worker_init_fn(worker_id)
        except Exception:
            result_q.put(("__error__", traceback.format_exc()))
            return
    while True:
        task = index_q.get()
        if task is None:
            break
        ordinal, indices = task
        try:
            batch = collate_fn([dataset[i] for i in indices])
            if use_shared_memory:
                shms = []
                payload = _pack(batch, shms)
                result_q.put((ordinal, payload))
                for shm in shms:
                    shm.close()  # parent unlinks after copying out
            else:
                result_q.put((ordinal, batch))
        except Exception:
            result_q.put(("__error__", traceback.format_exc()))
            return


def _cleanup(workers, index_q, result_q, use_shm, reorder):
    """Stop workers and free any shared memory they parked (used by
    MultiprocessIter's finalizer; must not reference the iterator)."""
    try:
        for _ in workers:
            index_q.put(None)
        for w in workers:
            w.join(timeout=2.0)
            if w.is_alive():
                w.terminate()
        if use_shm:
            # payloads parked in the reorder cache hold live segments too
            for payload in reorder.values():
                try:
                    _unpack(payload)
                except Exception:
                    pass
            reorder.clear()
        # timed drain catches results still in the queue feeder's pipe buffer
        misses = 0
        while misses < 3:
            try:
                item = result_q.get(timeout=0.1)
            except _queue.Empty:
                misses += 1
                continue
            if item[0] != "__error__" and use_shm:
                try:
                    _unpack(item[1])
                except Exception:
                    pass
    except Exception:
        pass


class MultiprocessIter:
    """Parent side: index-queue feeder + shared-memory receiver + reorder cache."""

    def __init__(self, loader, num_workers, prefetch_factor=2, timeout=0,
                 worker_init_fn=None, use_shared_memory=True, mp_context=None):
        self._loader = loader
        # timeout=0 means NO deadline (reference semantics); health of workers
        # is still checked every poll interval
        self._timeout = float(timeout) if timeout else None
        self._use_shm = use_shared_memory
        # start the resource tracker BEFORE forking: children must inherit the
        # parent's tracker, or each worker spawns its own and the parent's
        # unlink/unregister messages never reach it (ghost "leaked shared
        # memory" warnings at exit)
        try:
            from multiprocessing import resource_tracker as _rt

            _rt.ensure_running()
        except Exception:
            pass
        if mp_context is None:
            # forkserver forks workers from a clean single-threaded server —
            # forking the JAX parent directly (XLA thread pools live there)
            # risks deadlocked children.  But forkserver can't unpickle classes
            # defined in __main__ (scripts/notebooks), so fall back to fork for
            # those — matching the reference's Linux default.
            main_defined = any(
                getattr(type(o) if not callable(o) else o, "__module__", "")
                == "__main__"
                for o in (loader.dataset, loader.collate_fn, worker_init_fn)
                if o is not None)
            if not main_defined and "forkserver" in mp.get_all_start_methods():
                mp_context = "forkserver"
            elif "fork" in mp.get_all_start_methods():
                mp_context = "fork"
            else:
                mp_context = "spawn"
        ctx = mp.get_context(mp_context if mp_context in mp.get_all_start_methods()
                             else "spawn")
        self._index_q = ctx.Queue()
        self._result_q = ctx.Queue()
        self._tasks = list(enumerate(loader.batch_sampler))
        self._n_batches = len(self._tasks)
        self._next_task = 0
        self._received = 0
        self._reorder = {}
        self._depth = max(2, num_workers * prefetch_factor)
        seed = int.from_bytes(os.urandom(2), "little")
        self._workers = [
            ctx.Process(
                target=_worker_loop,
                args=(loader.dataset, loader.collate_fn, self._index_q,
                      self._result_q, w, num_workers, seed, worker_init_fn,
                      use_shared_memory),
                daemon=True)
            for w in range(num_workers)
        ]
        started = []
        try:
            for p in self._workers:
                p.start()
                started.append(p)
        except Exception:
            # don't leak half a worker pool on failure (the caller may fall
            # back to the thread path)
            for p in started:
                p.terminate()
            raise
        # weakref.finalize (not __del__): guaranteed to run at interpreter exit
        # BEFORE multiprocessing teardown, so parked shared-memory blocks are
        # freed even when an iterator is dropped unconsumed
        import weakref

        self._finalizer = weakref.finalize(
            self, _cleanup, self._workers, self._index_q, self._result_q,
            use_shared_memory, self._reorder)
        # prime the pipeline (outstanding tasks bounded by depth, like the
        # reference's _outstanding_capacity)
        for _ in range(min(self._depth, self._n_batches)):
            self._put_next()

    def _put_next(self):
        if self._next_task < self._n_batches:
            self._index_q.put(self._tasks[self._next_task])
            self._next_task += 1

    def __iter__(self):
        return self

    def __next__(self):
        if self._received >= self._n_batches:
            self._shutdown()
            raise StopIteration
        waited = 0.0
        while self._received not in self._reorder:
            try:
                item = self._result_q.get(timeout=5.0)
            except _queue.Empty:
                waited += 5.0
                dead = [w.pid for w in self._workers if not w.is_alive()]
                if dead and self._result_q.empty():
                    self._shutdown()
                    raise RuntimeError(
                        f"DataLoader worker process(es) died: {dead}")
                if self._timeout is not None and waited >= self._timeout:
                    self._shutdown()
                    raise RuntimeError(
                        f"DataLoader batch not produced within timeout="
                        f"{self._timeout}s")
                continue
            if item[0] == "__error__":
                self._shutdown()
                raise RuntimeError(f"DataLoader worker failed:\n{item[1]}")
            ordinal, payload = item
            self._reorder[ordinal] = payload
        payload = self._reorder.pop(self._received)
        self._received += 1
        self._put_next()
        batch = _unpack(payload) if self._use_shm else payload
        return self._loader._to_tensors(batch)

    def _shutdown(self):
        self._finalizer()
