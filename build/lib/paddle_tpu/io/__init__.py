"""paddle.io parity: Dataset/DataLoader/Sampler (ref: python/paddle/io/__init__.py,
fluid/reader.py:275 DataLoader, fluid/dataloader/*).

TPU-native notes: the loader yields host numpy batches; device transfer happens inside
the (jitted) step, letting XLA overlap H2D with compute.  Multi-worker prefetch uses a
thread pool (JAX arrays are produced on the main thread; numpy collation releases the
GIL in practice).  A per-host `DistributedBatchSampler` shards the global batch the way
fleet's dataloader does (ref distributed/fleet/utils/...).
"""
from __future__ import annotations

import itertools
import queue as _queue
import threading

import numpy as np

from ..framework import random as _random
from ..tensor.tensor import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(np.asarray(t._value)[idx] if isinstance(t, Tensor) else np.asarray(t)[idx] for t in self.tensors)

    def __len__(self):
        t = self.tensors[0]
        return t.shape[0] if isinstance(t, Tensor) else len(t)


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for ds in self.datasets:
            item = ds[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)

    def __len__(self):
        return min(len(d) for d in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for ds in self.datasets:
            yield from ds


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets])

    def __len__(self):
        return int(self.cum[-1])

    def __getitem__(self, idx):
        di = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if di == 0 else int(self.cum[di - 1])
        return self.datasets[di][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    n = len(dataset)
    if all(isinstance(l, float) for l in lengths):
        lengths = [int(round(l * n)) for l in lengths]
        lengths[-1] = n - sum(lengths[:-1])
    perm = np.random.permutation(n)
    out, offset = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[offset:offset + l].tolist()))
        offset += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self.num_samples = num_samples or len(data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        return iter(np.random.choice(len(p), self.num_samples, replace=self.replacement, p=p).tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    """Ref: fluid/dataloader/batch_sampler.py."""

    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Per-host shard of the global batch (ref: distributed fleet dataloader sampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None, shuffle=False, drop_last=False):
        from .. import distributed as dist

        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else dist.get_world_size()
        self.local_rank = rank if rank is not None else dist.get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices = np.concatenate([indices, indices[: self.total_size - n]])
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices:
            batch.append(int(idx))
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    """Ref: fluid/dataloader/collate.py."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.number)):
        return np.asarray(batch)
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s._value) for s in batch])
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn([b[i] for b in batch]) for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


class _PrefetchIter:
    def __init__(self, gen_fn, depth):
        self._q = _queue.Queue(maxsize=depth)
        self._done = object()
        self._thread = threading.Thread(target=self._fill, args=(gen_fn,), daemon=True)
        self._thread.start()

    def _fill(self, gen_fn):
        try:
            for item in gen_fn():
                self._q.put(item)
        except BaseException as e:  # propagate to consumer
            self._q.put(("__error__", e))
        self._q.put(self._done)

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._done:
            raise StopIteration
        if isinstance(item, tuple) and len(item) == 2 and item[0] == "__error__":
            raise item[1]
        return item


class _NativeWorkerIter:
    """Multi-worker prefetch over the C++ ring (core/native NativeRing).

    Reference analog: the multiprocess `_DataLoaderIterMultiProcess`
    (fluid/dataloader/dataloader_iter.py:342) whose workers push batches through
    shared memory.  Here N fetcher threads run __getitem__ + collate (numpy releases
    the GIL for the heavy copies) and push pickled batches into a GIL-free C++ MPMC
    ring.  Each batch is tagged with its sampler ordinal and the consumer reorders
    via a small cache, preserving strict sampler order exactly like the reference's
    `_rcvd_idx` reorder cache (dataloader_iter.py:356)."""

    def __init__(self, loader, num_workers, depth):
        import pickle

        from ..core.native import NativeRing

        self._pickle = pickle
        self._ring = NativeRing(depth)
        self._loader = loader
        indices = list(loader.batch_sampler)
        self._n_batches = len(indices)
        self._received = 0
        self._reorder = {}  # sampler ordinal -> collated batch
        # producer-side window: a worker may only fetch ordinal o once
        # o < received + window, bounding outstanding batches (ring + reorder
        # cache) the way the reference bounds _outstanding_capacity — otherwise
        # one slow worker lets the fast ones park a whole epoch in the cache
        self._window = max(depth, num_workers)
        self._win_cv = threading.Condition()
        self._stopped = False
        # shard round-robin: worker w owns ordinals w, w+N, w+2N, ...
        self._shards = [
            [(w + k * num_workers, idx_batch)
             for k, idx_batch in enumerate(indices[w::num_workers])]
            for w in range(num_workers)
        ]
        self._threads = [
            threading.Thread(target=self._worker, args=(shard,), daemon=True)
            for shard in self._shards if shard
        ]
        self._live = len(self._threads)
        self._live_lock = threading.Lock()
        for t in self._threads:
            t.start()

    def _worker(self, shard):
        try:
            for ordinal, idx_batch in shard:
                with self._win_cv:
                    while (not self._stopped
                           and ordinal >= self._received + self._window):
                        self._win_cv.wait(0.1)
                    if self._stopped:
                        return
                batch = [self._loader.dataset[i] for i in idx_batch]
                collated = self._loader.collate_fn(batch)
                payload = self._pickle.dumps((ordinal, collated), protocol=4)
                if not self._ring.push(payload):
                    return  # ring closed by consumer
        except BaseException as e:
            try:
                payload = self._pickle.dumps(("__error__", e), protocol=4)
            except Exception:
                # unpicklable exception payload: surface type + message, not silence
                payload = self._pickle.dumps(
                    ("__error__", RuntimeError(f"{type(e).__name__}: {e}")), protocol=4)
            try:
                self._ring.push(payload)
            except Exception:
                pass
        finally:
            with self._live_lock:
                self._live -= 1
                if self._live == 0:
                    self._ring.close()

    def __iter__(self):
        return self

    def __next__(self):
        if self._received >= self._n_batches:
            self._ring.close()
            raise StopIteration
        while self._received not in self._reorder:
            data = self._ring.pop()
            if data is None:
                raise StopIteration
            item = self._pickle.loads(data)
            if (isinstance(item, tuple) and len(item) == 2
                    and isinstance(item[0], str) and item[0] == "__error__"):
                raise item[1]
            ordinal, collated = item
            self._reorder[ordinal] = collated
        item = self._reorder.pop(self._received)
        with self._win_cv:
            self._received += 1
            self._win_cv.notify_all()
        return self._loader._to_tensors(item)

    def __del__(self):
        # free the C++ ring only once every worker thread is done with it
        try:
            with self._win_cv:
                self._stopped = True
                self._win_cv.notify_all()
            self._ring.close()
            for t in self._threads:
                t.join(timeout=1.0)
            if all(not t.is_alive() for t in self._threads):
                self._ring.free()
        except Exception:
            pass


class DataLoader:
    """Ref: fluid/reader.py:275 DataLoader (+dataloader_iter.py:148,342).

    num_workers>0 prefetches in the background.  With use_shared_memory=True
    (default, the reference's semantics) batches come from N forked worker
    PROCESSES through POSIX shared memory (io/_mp_loader.py) — real extra cores
    for JPEG-decode-heavy pipelines, no GIL.  use_shared_memory=False keeps the
    work in-process: N threads feeding a GIL-free C++ ring (core/native),
    falling back to a single Python prefetch thread.  All paths preserve strict
    sampler order (the reference's _rcvd_idx reorder contract).
    """

    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.return_list = return_list
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
        elif not self._iterable_mode and batch_size is not None:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size, drop_last=drop_last)
        else:
            self.batch_sampler = None
        self.batch_size = batch_size
        self._use_shared_memory = use_shared_memory
        self._timeout = timeout
        self._worker_init_fn = worker_init_fn

    def _gen(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size or 1))
                if not batch:
                    return
                yield self._to_tensors(self.collate_fn(batch))
        else:
            for indices in self.batch_sampler:
                batch = [self.dataset[i] for i in indices]
                yield self._to_tensors(self.collate_fn(batch))

    def _to_tensors(self, collated):
        if isinstance(collated, np.ndarray):
            return Tensor(collated)
        if isinstance(collated, (tuple, list)):
            return [self._to_tensors(c) for c in collated]
        if isinstance(collated, dict):
            return {k: self._to_tensors(v) for k, v in collated.items()}
        return collated

    def __iter__(self):
        if self.num_workers and self.num_workers > 0:
            if self.batch_sampler is not None and self._use_shared_memory:
                try:
                    from ._mp_loader import MultiprocessIter

                    return MultiprocessIter(
                        self, self.num_workers,
                        prefetch_factor=self.prefetch_factor,
                        timeout=self._timeout,
                        worker_init_fn=self._worker_init_fn)
                except Exception as e:
                    # thread paths can't honor per-process init; degrading
                    # silently would change semantics the user asked for
                    if self._worker_init_fn is not None:
                        raise RuntimeError(
                            "multiprocess DataLoader workers failed to start and "
                            "worker_init_fn only runs in process workers — fix "
                            "the cause (often an unpicklable dataset/collate_fn) "
                            "or drop worker_init_fn") from e
                    import warnings

                    warnings.warn(
                        f"multiprocess DataLoader workers unavailable "
                        f"({type(e).__name__}: {e}); falling back to in-process "
                        f"worker threads", stacklevel=2)
            if self.batch_sampler is not None:
                try:
                    return _NativeWorkerIter(self, self.num_workers,
                                             self.num_workers * self.prefetch_factor)
                except Exception:
                    pass
            return _PrefetchIter(self._gen, self.num_workers * self.prefetch_factor)
        return self._gen()

    def __len__(self):
        if self.batch_sampler is not None:
            return len(self.batch_sampler)
        raise TypeError("length undefined for iterable dataset loader")


def get_worker_info():
    """Ref worker.py get_worker_info — non-None only inside a worker process."""
    from ._mp_loader import get_worker_info as _gwi

    return _gwi()
