"""Build script (ref: the reference's CMake superbuild, CMakeLists.txt:49-257).

The TPU build's native surface is one host-side C++ library (TCPStore server,
DataLoader ring, trace collector, host staging pool — see
paddle_tpu/core/native/native.cc); the device side is XLA/PJRT, so there is no
vendor-kernel build matrix.  `build_ext` compiles the library into the package
at install time; at import time the package falls back to an mtime-cached g++
build (dev checkouts) or pure-Python implementations (no toolchain).
"""
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py


class BuildNative(build_py):
    def run(self):
        super().run()
        try:
            import sys, os

            sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
            from paddle_tpu.core.native import build as build_native

            lib = build_native(verbose=True)
            # copy the built lib into the staged package
            rel = os.path.relpath(lib, os.path.dirname(os.path.abspath(__file__)))
            dst = os.path.join(self.build_lib, rel)
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            self.copy_file(lib, dst)
        except (ImportError, subprocess.CalledProcessError, OSError) as e:
            print(f"[setup.py] native library build skipped ({e}); "
                  f"pure-Python fallbacks will be used")


setup(cmdclass={"build_py": BuildNative})
