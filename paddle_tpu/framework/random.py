"""Stateful RNG facade over JAX threefry keys.

Reference: `phi/core/generator.h:23` (stateful per-device Generator) and
`paddle.seed` (`python/paddle/framework/random.py`).  JAX RNG is functional; we keep a
stateful key that is split on every draw.  Under `to_static`/jit tracing, the traced
program receives a fresh key argument each call via `push_key` so dropout masks are not
baked in as constants.
"""
from __future__ import annotations

import contextlib

import jax
import numpy as np


_RNG_IMPL = None  # resolved lazily: "rbg" on TPU, jax default elsewhere


def _rng_impl():
    """TPU uses the hardware RBG bit generator: dropout-mask generation for
    one ERNIE b512xs128 step measured 48.3 ms (threefry) vs 13.4 ms (rbg) on
    v5e — threefry burns VPU cycles hashing counters while rbg reads the
    on-chip RNG.  CPU/GPU keep the jax default (threefry) so host-side tests
    and golden sequences are unchanged.  Override with set_rng_impl()."""
    global _RNG_IMPL
    if _RNG_IMPL is None:
        from ..core.device import is_tpu_backend

        _RNG_IMPL = "rbg" if is_tpu_backend() else "threefry2x32"
    return _RNG_IMPL


def set_rng_impl(impl: str):
    """Force the PRNG implementation ('threefry2x32' | 'rbg'); takes effect at
    the next paddle.seed()/key creation."""
    global _RNG_IMPL
    _RNG_IMPL = impl


def make_key(seed: int):
    """Create a PRNG key with the framework-selected implementation.  EVERY
    key-creation site must use this (not bare jax.random.key/PRNGKey) or the
    TPU rbg fast path silently reverts to threefry for that stream."""
    return jax.random.key(int(seed), impl=_rng_impl())


class Generator:
    """Stateful key-splitting generator (ref phi/core/generator.h:23)."""

    def __init__(self, seed: int = 0):
        self._seed = int(seed)
        self._key = None  # lazy: don't touch the backend at import time

    @property
    def key(self):
        if self._key is None:
            self._key = jax.random.key(self._seed, impl=_rng_impl())
        return self._key

    def manual_seed(self, seed: int):
        self._seed = int(seed)
        self._key = jax.random.key(self._seed, impl=_rng_impl())
        return self

    def initial_seed(self) -> int:
        return self._seed

    def set_key(self, key):
        self._key = key

    def split(self):
        self._key, sub = jax.random.split(self.key)
        return sub


_default_generator = Generator(np.random.randint(0, 2**31 - 1))
_key_stack: list[Generator] = []


def default_generator() -> Generator:
    return _key_stack[-1] if _key_stack else _default_generator


def seed(s: int):
    """paddle.seed parity."""
    _default_generator.manual_seed(s)
    return _default_generator


def get_rng_key():
    """Split the current generator and return a fresh subkey."""
    return default_generator().split()


@contextlib.contextmanager
def rng_key_scope(key):
    """Run a region drawing randomness from `key` (used by to_static tracing)."""
    gen = Generator(0)
    gen.set_key(key)
    _key_stack.append(gen)
    try:
        yield gen
    finally:
        _key_stack.pop()


def get_cuda_rng_state():  # parity shims
    return default_generator().key


def set_cuda_rng_state(state):
    default_generator().set_key(state)
