"""Rendezvous / control-plane KV store.

Reference: C++ `TCPStore` (paddle/fluid/distributed/store/tcp_store.h:120, store.h:26)
used by init_parallel_env for NCCL-id exchange.  On TPU the data plane needs no
rendezvous (XLA collectives ride ICI, jax.distributed has its own coordinator), so
this store serves the *control* plane only: elastic membership, barriers, and
user-level coordination.  A C++ implementation (paddle_tpu/core/native) backs the same
wire protocol when built; this pure-socket Python fallback is always available.

Wire protocol (length-prefixed): 1-byte op (S/G/A/W/D), u32 key len, key bytes,
u32 value len, value bytes.  GET on a missing key blocks until set (reference
TCPStore::wait semantics).
"""
from __future__ import annotations

import socket
import struct
import threading
import time

from ..observability import metrics as _obs

# Control-plane telemetry (README §Observability): per-op rate + latency,
# reconnect churn, and deadline hits — the straggler/partition signals.
_OP_NAMES = {"S": "set", "G": "get", "N": "get_nb", "A": "add", "W": "check",
             "D": "delete", "L": "list"}
_M_OPS = _obs.counter(
    "store_ops_total", "TCPStore client ops completed", labelnames=("op",))
_M_OP_SECONDS = _obs.histogram(
    "store_op_duration_seconds",
    "TCPStore rpc latency (connect + round-trip, including retries)",
    labelnames=("op",))
_M_RECONNECTS = _obs.counter(
    "store_reconnects_total",
    "TCPStore reconnect attempts after a connection failure")
_M_DEADLINE_HITS = _obs.counter(
    "store_deadline_hits_total",
    "TCPStore rpcs abandoned at their per-op deadline")


class Store:
    """Ref store.h:26 abstract Store."""

    def set(self, key: str, value: bytes):
        raise NotImplementedError

    def get(self, key: str) -> bytes:
        raise NotImplementedError

    def add(self, key: str, amount: int) -> int:
        raise NotImplementedError

    def wait(self, keys, timeout=None):
        raise NotImplementedError


class _KVServer(threading.Thread):
    def __init__(self, port: int):
        super().__init__(daemon=True)
        self._data: dict[str, bytes] = {}
        self._cond = threading.Condition()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(128)
        self._running = True

    def run(self):
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                try:
                    hdr = _recvn(conn, 5)
                except ConnectionError:
                    return
                op = chr(hdr[0])
                klen = struct.unpack("<I", hdr[1:5])[0]
                key = _recvn(conn, klen).decode() if klen else ""
                vlen = struct.unpack("<I", _recvn(conn, 4))[0]
                val = _recvn(conn, vlen) if vlen else b""
                # NOTE: every branch copies under the lock and sends OUTSIDE it —
                # a stalled client must not wedge the whole store
                if op == "S":
                    with self._cond:
                        self._data[key] = val
                        self._cond.notify_all()
                    _send_val(conn, b"ok")
                elif op == "A":
                    try:
                        amt = int(val.decode())
                        with self._cond:
                            cur = int(self._data.get(key, b"0").decode() or 0)
                            cur += amt
                            self._data[key] = str(cur).encode()
                            self._cond.notify_all()
                        reply = str(cur).encode()
                    except ValueError:
                        reply = b"ERR non-integer value"
                    _send_val(conn, reply)
                elif op == "G":  # blocking get
                    with self._cond:
                        while key not in self._data and self._running:
                            self._cond.wait(timeout=1.0)
                        out = self._data.get(key)
                    if out is None:
                        return  # server stopping
                    _send_val(conn, out)
                elif op == "N":  # non-blocking get: presence flag + value
                    with self._cond:
                        out = self._data.get(key)
                    _send_val(conn, b"0" if out is None else b"1" + out)
                elif op == "W":  # non-blocking check
                    with self._cond:
                        present = key in self._data
                    _send_val(conn, b"1" if present else b"0")
                elif op == "D":
                    with self._cond:
                        self._data.pop(key, None)
                    _send_val(conn, b"ok")
                elif op == "L":  # list keys with prefix
                    with self._cond:
                        keys = [k for k in self._data if k.startswith(key)]
                    _send_val(conn, "\n".join(keys).encode())
                else:
                    return
        except (ConnectionError, OSError):
            return
        finally:
            conn.close()

    def stop(self):
        self._running = False
        with self._cond:
            self._cond.notify_all()  # release blocking-G waiters
        try:
            self._sock.close()
        except OSError:
            pass


def _recvn(conn, n):
    """Read exactly n bytes or raise ConnectionError (EOF / short read)."""
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf += chunk
    return buf


def _send_val(conn, val: bytes):
    conn.sendall(struct.pack("<I", len(val)) + val)


def _recvn_deadline(s, n, deadline):
    """Client-side _recvn with a HARD deadline: the socket timeout shrinks
    to the remaining budget before every recv, so a peer dripping one byte
    per timeout window cannot stretch one rpc past its deadline."""
    buf = b""
    while len(buf) < n:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise socket.timeout("rpc deadline exceeded mid-read")
        s.settimeout(remaining)
        chunk = s.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf += chunk
    return buf


class TCPStore(Store):
    """Ref tcp_store.h:120 — host:port KV store; `is_master` runs the server.

    Hardened client (fault-tolerance layer): every op carries a deadline
    (``timeout`` is the default, each public op takes a per-op override —
    the reference ``TCPStore::wait`` timeout semantics), reconnects are
    bounded by that deadline with jittered exponential backoff, and the
    non-idempotent ``add`` never blind-retries once its request may have
    been applied.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, is_master: bool = False,
                 world_size: int = 1, timeout: float = 300.0, use_native: bool = True,
                 backoff=None, sleep=time.sleep):
        from .fault_tolerance import ExponentialBackoff

        self._server = None
        self.timeout = timeout
        # seed=None -> OS entropy: clients must NOT share a jitter stream,
        # or every rank reconnects to a reborn master in lockstep (tests
        # wanting determinism inject their own backoff)
        self._backoff = backoff if backoff is not None else \
            ExponentialBackoff(base=0.05, factor=2.0, max_delay=1.0,
                               jitter=0.25, seed=None)
        self._sleep = sleep
        if is_master:
            self._server = self._start_server(port, use_native)
            port = self._server.port
        self.host, self.port = host, port

    @staticmethod
    def _start_server(port: int, use_native: bool):
        """Prefer the C++ server (core/native) — same wire protocol; fall back to the
        Python thread server when the toolchain is unavailable."""
        if use_native:
            try:
                from ..core.native import NativeKVServer

                return NativeKVServer(port)
            except Exception:
                pass
        srv = _KVServer(port)
        srv.start()
        return srv

    def _rpc(self, op: str, key: str, value: bytes = b"",
             timeout: float | None = None, idempotent: bool = True) -> bytes:
        """One request under a per-op deadline.  Reconnects with jittered
        exponential backoff until the deadline; the socket timeout shrinks
        to the remaining budget so a hung peer cannot exceed it."""
        timeout = self.timeout if timeout is None else timeout
        deadline = time.monotonic() + timeout
        attempt = 0
        last = None
        record = _obs.enabled()
        t0 = time.perf_counter() if record else 0.0
        opname = _OP_NAMES.get(op, op)
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                _M_DEADLINE_HITS.inc()
                raise TimeoutError(
                    f"TCPStore rpc {op} {key!r} timed out after {timeout:.3g}s "
                    f"({attempt} attempts; last error: {last!r})")
            sent = False
            try:
                with socket.create_connection(
                        (self.host, self.port),
                        timeout=min(remaining, 5.0)) as s:
                    kb = key.encode()
                    s.settimeout(max(deadline - time.monotonic(), 0.001))
                    s.sendall(op.encode() + struct.pack("<I", len(kb)) + kb
                              + struct.pack("<I", len(value)) + value)
                    sent = True
                    vlen = struct.unpack(
                        "<I", _recvn_deadline(s, 4, deadline))[0]
                    out = _recvn_deadline(s, vlen, deadline) if vlen else b""
                    if record:
                        _M_OPS.labels(op=opname).inc()
                        _M_OP_SECONDS.labels(op=opname).observe(
                            time.perf_counter() - t0)
                    return out
            except (ConnectionError, OSError) as e:
                last = e
                if sent and not idempotent:
                    # the server may have applied the mutation — a blind
                    # retry could double-count; the caller owns this flag
                    raise ConnectionError(
                        f"TCPStore {op} {key!r} failed after the request "
                        f"was sent; the mutation may or may not have been "
                        f"applied: {e!r}") from e
                attempt += 1
                _M_RECONNECTS.inc()
                remaining = deadline - time.monotonic()
                if remaining > 0:
                    self._sleep(min(self._backoff.delay(attempt), remaining))

    def set(self, key, value, timeout=None):
        if isinstance(value, str):
            value = value.encode()
        self._rpc("S", key, value, timeout=timeout)

    def get(self, key, timeout=None) -> bytes:
        return self._rpc("G", key, timeout=timeout)

    def get_nb(self, key, timeout=None) -> bytes | None:
        """Non-blocking get: None if the key is absent (op 'N')."""
        out = self._rpc("N", key, timeout=timeout)
        return out[1:] if out[:1] == b"1" else None

    def add(self, key, amount: int, timeout=None) -> int:
        # add(key, 0) is a pure read (barrier polls) and stays retryable
        out = self._rpc("A", key, str(amount).encode(), timeout=timeout,
                        idempotent=(int(amount) == 0))
        if out.startswith(b"ERR"):
            raise ValueError(
                f"TCPStore.add({key!r}): stored value is not an integer")
        return int(out.decode())

    def check(self, key, timeout=None) -> bool:
        return self._rpc("W", key, timeout=timeout) == b"1"

    def delete_key(self, key, timeout=None):
        self._rpc("D", key, timeout=timeout)

    def keys_with_prefix(self, prefix: str, timeout=None) -> list[str]:
        out = self._rpc("L", prefix, timeout=timeout).decode()
        return out.split("\n") if out else []

    def wait(self, keys, timeout=None):
        """Block until every key exists (ref TCPStore::wait): raises
        TimeoutError naming the keys still missing at the deadline."""
        keys = [keys] if isinstance(keys, str) else list(keys)
        timeout = self.timeout if timeout is None else timeout
        deadline = time.monotonic() + timeout
        pending = list(keys)
        while pending:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                _M_DEADLINE_HITS.inc()
                raise TimeoutError(
                    f"TCPStore wait timed out after {timeout:.3g}s; "
                    f"still missing: {pending}")
            # sweep EVERY pending key each round so the timeout error names
            # only keys that are genuinely absent, not merely unchecked; a
            # check that itself times out (dead master) counts as absent so
            # the documented "still missing" error is what callers see
            still = []
            for k in pending:
                try:  # each check gets the full remaining budget: a slow-
                    # but-healthy master must not be misread as "missing"
                    present = self.check(k, timeout=max(
                        deadline - time.monotonic(), 0.001))
                except TimeoutError:
                    present = False
                if not present:
                    still.append(k)
            pending = still
            if pending:
                self._sleep(min(0.05, max(deadline - time.monotonic(), 0)))

    def barrier(self, name: str, world_size: int, timeout=None):
        timeout = self.timeout if timeout is None else timeout
        deadline = time.monotonic() + timeout
        n = self.add(f"__barrier__/{name}", 1, timeout=timeout)
        arrived = n
        while arrived < world_size:
            if time.monotonic() > deadline:
                _M_DEADLINE_HITS.inc()
                raise TimeoutError(
                    f"barrier {name} timed out ({arrived}/{world_size})")
            try:  # poll (add 0 = pure read); a timed-out poll is just
                arrived = int(self._rpc(  # "not there yet"
                    "A", f"__barrier__/{name}", b"0",
                    timeout=max(deadline - time.monotonic(), 0.001)
                    ).decode())
            except TimeoutError:
                pass
            if arrived < world_size:
                self._sleep(0.05)

    def close(self):
        if self._server is not None:
            self._server.stop()
