"""FleetExecutor — actor-style multi-program runtime.

Reference analog: `paddle/fluid/distributed/fleet_executor/` — `TaskNode`
graphs (task_node.h:32) executed by `Interceptor` message loops
(interceptor.h:46) owned by a per-rank `Carrier` (carrier.h:49), with a brpc
`MessageBus` bridging ranks.  The reference used it for pipeline/heterogeneous
cluster orchestration where one SPMD program can't express the job.

TPU-native: the hot pipeline path is COMPILED (meta_parallel.pipeline_schedule
— shard_map + ppermute), so this runtime serves the control-plane role:
streaming task graphs around the compiled steps (data ingestion -> train ->
eval/checkpoint side-tasks), and cross-process task graphs bridged by the
TCPStore instead of brpc.  Interceptors are threads with mailboxes; credit
messages bound buffering exactly like the reference's scheduling messages.
"""
from __future__ import annotations

import queue as _queue
import threading
import time

__all__ = ["TaskNode", "Interceptor", "Carrier", "MessageBus", "FleetExecutor"]


class _Stop:
    """Termination sentinel that survives pickling across the message bus
    (a bare object() would unpickle to a different identity)."""

    def __reduce__(self):
        return (_get_stop, ())


def _get_stop():
    return _STOP


_STOP = _Stop()


class TaskNode:
    """Ref task_node.h:32 — one unit of the job graph.

    `program` is any callable payload(batch) -> batch (the reference held a
    ProgramDesc section; here the payload is usually a compiled step or host
    IO fn).  max_run_times bounds how many microbatches stream through."""

    def __init__(self, rank, task_id, program=None, max_run_times=None,
                 node_type="Compute"):
        self.rank = int(rank)
        self.task_id = int(task_id)
        self.program = program
        self.max_run_times = max_run_times
        self.node_type = node_type
        self.upstream: list[int] = []
        self.downstream: list[int] = []

    def add_upstream_task(self, task_id, buffs_size=2):
        self.upstream.append(int(task_id))

    def add_downstream_task(self, task_id, buffs_size=2):
        self.downstream.append(int(task_id))


class MessageBus:
    """Ref message_bus.cc — routes InterceptorMessages between carriers.

    In-process: direct queue handoff.  Cross-process: messages serialize into
    the control-plane KV store under {job}/msg/{dst_rank}/{seq} and a poller
    thread drains them (the TCPStore replaces brpc)."""

    def __init__(self, rank=0, store=None, job_id="fleet_exec", poll_interval=0.01):
        self.rank = int(rank)
        self.store = store
        self.job_id = job_id
        self.poll_interval = poll_interval
        self._local: dict[int, "Carrier"] = {}
        self._recv_seq = 0
        self._stop = threading.Event()
        self._poller = None

    def register_carrier(self, carrier):
        self._local[carrier.rank] = carrier
        # start polling only once a carrier can consume — a message read
        # before registration would be dropped and its sequence burned
        if self.store is not None and self._poller is None:
            self._poller = threading.Thread(target=self._poll_loop, daemon=True)
            self._poller.start()

    def send(self, dst_rank, task_id, payload):
        if dst_rank in self._local:
            self._local[dst_rank].deliver(task_id, payload)
            return
        if self.store is None:
            raise RuntimeError(f"rank {dst_rank} is not local and no store "
                               "was given to bridge processes")
        import pickle

        # per-destination ATOMIC sequence: multiple sender ranks must not
        # overwrite each other's slots
        seq = self.store.add(f"{self.job_id}/msgctr/{dst_rank}", 1) - 1
        key = f"{self.job_id}/msg/{dst_rank}/{seq}"
        self.store.set(key, pickle.dumps((task_id, payload), protocol=4))

    def _poll_loop(self):
        import pickle

        # prefer the non-blocking read (TCPStore.get blocks until the key
        # exists, which would stall the poll loop's stop check)
        getter = getattr(self.store, "get_nb", None) or self.store.get
        while not self._stop.wait(self.poll_interval):
            key = f"{self.job_id}/msg/{self.rank}/{self._recv_seq}"
            try:
                raw = getter(key)
            except Exception:
                continue
            if raw is None:
                continue
            self._recv_seq += 1
            try:
                task_id, payload = pickle.loads(raw)
                carrier = self._local.get(self.rank)
                if carrier is not None:
                    carrier.deliver(task_id, payload)
            except Exception as e:
                # one bad message must not kill the poller; surface it to the
                # consumer instead of silently hanging the graph
                carrier = self._local.get(self.rank)
                if carrier is not None:
                    carrier.results.put((-1, ("__error__", e)))

    def shutdown(self):
        self._stop.set()
        if self._poller is not None:
            self._poller.join(timeout=1.0)


class Interceptor(threading.Thread):
    """Ref interceptor.h:46 — one actor: mailbox + handler loop.

    Source nodes pull from the carrier feed; compute nodes apply
    node.program; sink nodes collect into carrier.results."""

    def __init__(self, carrier, node: TaskNode, mailbox_size=4):
        super().__init__(daemon=True)
        self.carrier = carrier
        self.node = node
        self.inbox: _queue.Queue = _queue.Queue(maxsize=mailbox_size)
        self._n_done = 0
        # fan-in: terminate only after EVERY upstream has sent its STOP
        self._stops_needed = max(len(node.upstream), 1)

    def enqueue(self, payload):
        self.inbox.put(payload)

    def _emit(self, payload):
        for dst in self.node.downstream:
            self.carrier.route(dst, payload)
        if not self.node.downstream:
            self.carrier.results.put((self.node.task_id, payload))

    def run(self):
        try:
            if self.node.node_type == "Source":
                for item in self.carrier.feed_iter():
                    out = self.node.program(item) if self.node.program else item
                    self._emit(out)
                    self._n_done += 1
                    if (self.node.max_run_times
                            and self._n_done >= self.node.max_run_times):
                        break
                self._emit(_STOP)
                return
            stops = 0
            while True:
                item = self.inbox.get()
                if item is _STOP:
                    stops += 1
                    if stops >= self._stops_needed:
                        self._emit(_STOP)
                        return
                    continue
                out = self.node.program(item) if self.node.program else item
                if self.node.node_type != "Sink":
                    self._emit(out)
                else:
                    self.carrier.results.put((self.node.task_id, out))
                self._n_done += 1
        except Exception as e:  # surface actor failures to the consumer
            self.carrier.results.put((self.node.task_id, ("__error__", e)))
            self._emit(_STOP)


class Carrier:
    """Ref carrier.h:49 — owns this rank's interceptors and routes messages."""

    def __init__(self, rank=0, bus: MessageBus | None = None):
        self.rank = int(rank)
        self.bus = bus or MessageBus(rank)
        self.bus.register_carrier(self)
        self.interceptors: dict[int, Interceptor] = {}
        self.results: _queue.Queue = _queue.Queue()
        self._feed = None
        self._task_ranks: dict[int, int] = {}

    def add_task_node(self, node: TaskNode):
        self._task_ranks[node.task_id] = node.rank
        if node.rank == self.rank:
            self.interceptors[node.task_id] = Interceptor(self, node)

    def route(self, task_id, payload):
        dst_rank = self._task_ranks.get(task_id, self.rank)
        if dst_rank == self.rank:
            self.deliver(task_id, payload)
        else:
            self.bus.send(dst_rank, task_id, payload)

    def deliver(self, task_id, payload):
        self.interceptors[task_id].enqueue(payload)

    def feed_iter(self):
        return iter(self._feed or [])

    def start(self, feed=None):
        self._feed = feed
        for it in self.interceptors.values():
            it.start()

    def wait(self, timeout=60.0):
        """Collect sink outputs until every interceptor finishes.

        `timeout` is an IDLE timeout: it resets whenever a result arrives, so
        a long-running but progressing graph never trips it."""
        out = []
        deadline = time.monotonic() + timeout  # monotonic: NTP-slew-proof

        def _collect(tid, payload):
            if isinstance(payload, tuple) and len(payload) == 2 \
                    and payload[0] == "__error__":
                raise RuntimeError("task node failed") from payload[1]
            if payload is not _STOP:
                out.append((tid, payload))

        live = list(self.interceptors.values())
        while any(t.is_alive() for t in live):
            try:
                tid, payload = self.results.get(timeout=0.05)
            except _queue.Empty:
                if time.monotonic() > deadline:
                    raise TimeoutError("fleet executor made no progress "
                                       f"for {timeout}s")
                continue
            deadline = time.monotonic() + timeout  # progress resets the idle clock
            _collect(tid, payload)
        while not self.results.empty():
            _collect(*self.results.get_nowait())
        return out


class FleetExecutor:
    """Ref fleet_executor.h:35 — top-level: init with a task graph, run it.

    `run(feed)` streams the feed through the graph and returns
    {sink_task_id: [outputs in arrival order]}.
    """

    def __init__(self, rank=0, store=None, job_id="fleet_exec"):
        self.bus = MessageBus(rank=rank, store=store, job_id=job_id)
        self.carrier = Carrier(rank=rank, bus=self.bus)
        self._nodes: list[TaskNode] = []

    def init(self, task_nodes):
        for node in task_nodes:
            self._nodes.append(node)
            self.carrier.add_task_node(node)
        return self

    def run(self, feed=None, timeout=60.0):
        self.carrier.start(feed=feed)
        pairs = self.carrier.wait(timeout=timeout)
        out: dict[int, list] = {}
        for tid, payload in pairs:
            out.setdefault(tid, []).append(payload)
        return out

    def shutdown(self):
        self.bus.shutdown()
