"""paddle.distributed.utils (ref: python/paddle/distributed/utils.py).

global_scatter/global_gather are the reference's MoE dispatch ops
(operators/collective/global_scatter_op.cc): rows of `x` are routed to
(expert, rank) buckets by count tensors.  On TPU the compiled MoE path is
`incubate.MoELayer`'s dense-capacity `lax.all_to_all` (SURVEY §7.1); these
functions cover the eager/debug path.
"""
from __future__ import annotations

import logging
import socket

import numpy as np

from ..tensor.tensor import Tensor

__all__ = ["global_scatter", "global_gather", "get_logger", "get_host_name_ip"]


def _world(group):
    from .env import get_world_size

    return get_world_size() if group is None else getattr(group, "nranks", 1)


def global_scatter(x, local_count, global_count, group=None, use_calc_stream=True):
    """Route rows of x to experts by counts (ref distributed/utils.py:57).

    Single-process: every destination is local, so the op is the identity on
    the row payload (rows are already expert-ordered by construction).
    Multi-process eager dispatch is not supported — use incubate.MoELayer,
    whose all_to_all compiles onto ICI."""
    n = _world(group)
    if n > 1:
        raise NotImplementedError(
            "eager multi-process global_scatter is not supported on the TPU "
            "build; use paddle.incubate.MoELayer (compiled all_to_all) instead")
    lc = np.asarray(local_count._value if isinstance(local_count, Tensor) else local_count)
    if int(lc.sum()) != int(x.shape[0]):
        raise ValueError(
            f"local_count sums to {int(lc.sum())} but x has {x.shape[0]} rows")
    return x


def global_gather(x, local_count, global_count, group=None, use_calc_stream=True):
    """Inverse of global_scatter (ref distributed/utils.py:180)."""
    n = _world(group)
    if n > 1:
        raise NotImplementedError(
            "eager multi-process global_gather is not supported on the TPU "
            "build; use paddle.incubate.MoELayer (compiled all_to_all) instead")
    return x


def get_logger(log_level=20, name="root"):
    logger = logging.getLogger(name)
    logger.setLevel(log_level)
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(name)s %(levelname)s: %(message)s"))
        logger.addHandler(h)
    return logger


def get_host_name_ip():
    try:
        host = socket.gethostname()
        return host, socket.gethostbyname(host)
    except OSError:
        return None
