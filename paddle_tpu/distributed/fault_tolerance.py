"""Fault-tolerance primitives: retry policies and a self-healing train loop.

Reference analog: `fluid/incubate/checkpoint/auto_checkpoint.py` restarts a
job from its periodic snapshot; Piper (PAPERS.md) treats preemption-safe
training as a first-class system property.  This module supplies the pieces
the rest of the stack composes:

- ``ExponentialBackoff`` — bounded jittered delay schedule; jitter draws
  from OS entropy by default (ranks must not share a retry schedule), and
  determinism is opt-in via an explicit ``seed`` or ``jitter=0`` for tests;
- ``RetryPolicy`` / ``retry_call`` — transient-I/O retry used by
  ``CheckpointManager.save`` (ENOSPC/EIO/EAGAIN style errors) and available
  to any caller;
- ``Preemption`` — the simulated/real preemption signal the fault harness
  (`paddle_tpu.testing.faults`) raises and ``run_with_recovery`` catches;
- ``run_with_recovery`` — a training supervisor that checkpoints through a
  ``CheckpointManager``, catches recoverable failures, restores the latest
  *valid* checkpoint (corrupt steps are quarantined by the loader) and
  replays from the restored step counter.  With a deterministic step
  function the recovered run's final state is bitwise identical to an
  uninterrupted run (tests/test_fault_tolerance.py).
"""
from __future__ import annotations

import contextlib
import errno
import os
import random
import signal as _signal
import threading
import time

from ..observability import flight_recorder as _flight
from ..observability import goodput as _goodput
from ..observability import metrics as _obs
from ..observability import tracing as _tracing
from ..observability.spans import span as _span

__all__ = [
    "Preemption", "AlertRestart", "ExponentialBackoff", "RetryPolicy",
    "retry_call", "run_with_recovery", "TRANSIENT_ERRNOS",
    "install_preemption_handler", "PreemptionNotice",
]

# Recovery telemetry (README §Observability): restart/restore/preemption
# rates are the self-healing loop's health signals.
_M_RETRIES = _obs.counter(
    "retry_attempts_total",
    "Transient-failure retries issued by retry_call", labelnames=("op",))
_M_PREEMPTIONS = _obs.counter(
    "preemptions_total",
    "Preemption notices received (SIGTERM/SIGINT adapter fires)")
_M_RESTARTS = _obs.counter(
    "recovery_restarts_total",
    "run_with_recovery restarts after a recoverable failure")
_M_RESTORES = _obs.counter(
    "recovery_restores_total",
    "Checkpoint restores performed by run_with_recovery")
_M_RESTORED_STEP = _obs.gauge(
    "recovery_last_restored_step",
    "Completed-step counter of the last checkpoint restore")

#: OSError errnos considered transient (worth retrying): disk-full windows,
#: flaky media, interrupted syscalls, device contention.
TRANSIENT_ERRNOS = frozenset({
    errno.ENOSPC, errno.EIO, errno.EAGAIN, errno.EINTR, errno.EBUSY,
})


class Preemption(Exception):
    """A (simulated or real) preemption signal: the host is going away.

    Raised by the fault-injection harness and by SIGTERM adapters; caught by
    ``run_with_recovery`` which restores the latest valid checkpoint.
    """


class AlertRestart(Preemption):
    """A telemetry-driven restart decision: an ``AlertPolicy`` mapped a
    firing alert to the ``restart`` action (ISSUE 7's sense->decide->act
    loop).  Subclasses ``Preemption`` so the default ``recoverable`` set of
    ``run_with_recovery`` already heals it with a checkpoint restore."""

    def __init__(self, decision):
        self.decision = decision
        super().__init__(
            f"alert {decision.alert!r} (episode {decision.episode}, labels "
            f"{decision.labels}) fired with action 'restart'")


class ExponentialBackoff:
    """delay(attempt) = min(base * factor^(attempt-1), max_delay) * jitter.

    The default ``seed=None`` draws jitter from OS entropy so concurrent
    ranks never share a retry schedule (the thundering-herd breaker).
    Tests wanting reproducible timing pass an explicit seed or
    ``jitter=0``.
    """

    def __init__(self, base=0.05, factor=2.0, max_delay=2.0, jitter=0.25,
                 seed=None):
        self.base = float(base)
        self.factor = float(factor)
        self.max_delay = float(max_delay)
        self.jitter = float(jitter)
        self._rng = random.Random(seed)

    def delay(self, attempt: int) -> float:
        # exponent capped: factor**64 already dwarfs any max_delay, and an
        # uncapped float pow overflows after ~1000 attempts
        d = min(self.base * self.factor ** min(max(0, attempt - 1), 64),
                self.max_delay)
        if self.jitter:
            d *= 1.0 + self.jitter * self._rng.random()
        return d


class RetryPolicy:
    """How many times to retry, on which errors, sleeping how long.

    ``retryable`` may be a callable ``(exc) -> bool``; the default retries
    OSErrors whose errno is in ``TRANSIENT_ERRNOS``.  ``sleep`` is injectable
    so tests record the schedule instead of waiting it out.
    """

    def __init__(self, max_attempts=3, backoff=None, retryable=None,
                 sleep=time.sleep):
        self.max_attempts = max(1, int(max_attempts))
        self.backoff = backoff if backoff is not None else ExponentialBackoff()
        self._retryable = retryable
        self.sleep = sleep

    def is_retryable(self, exc) -> bool:
        if self._retryable is not None:
            return bool(self._retryable(exc))
        return isinstance(exc, OSError) and exc.errno in TRANSIENT_ERRNOS


def retry_call(fn, *args, policy: RetryPolicy | None = None, **kwargs):
    """Call ``fn``; on a retryable exception back off and try again (up to
    ``policy.max_attempts`` total attempts).  The last error propagates."""
    policy = policy if policy is not None else RetryPolicy()
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn(*args, **kwargs)
        except Exception as e:
            if attempt >= policy.max_attempts or not policy.is_retryable(e):
                raise
            op = getattr(fn, "__name__", "call")
            _M_RETRIES.labels(op=op).inc()
            _flight.record_event("retry", op=op, attempt=attempt,
                                 error=repr(e))
            policy.sleep(policy.backoff.delay(attempt))


def run_with_recovery(step_fn, num_steps, manager, get_state, set_state, *,
                      recoverable=(Preemption,), max_restarts=10,
                      save_initial=True, on_event=None,
                      flight_recorder_dir=None, telemetry_port=None,
                      healthy_step_age=600.0, alert_policy=None,
                      alert_every=1, restart_backoff=None,
                      goodput_ledger=None):
    """Run ``num_steps`` training steps under checkpoint-restore supervision.

    ``step_fn(step)`` performs one training step (a closure over the model /
    optimizer / data; ``step`` is the 0-based index of the step about to
    run).  ``manager`` is a ``checkpoint.CheckpointManager``; ``get_state()``
    returns the checkpointable state pytree and ``set_state(state)`` installs
    a restored one.  The step counter in checkpoints counts *completed*
    steps: a checkpoint at step k holds the state after steps [0, k).

    On an exception in ``recoverable`` the supervisor restores the newest
    valid checkpoint (the loader quarantines corrupt ones and falls back)
    and replays from its step count — with a deterministic ``step_fn`` the
    final state is bitwise identical to an uninterrupted run.  Other
    exceptions propagate.  Returns ``{"completed", "restarts"}``.

    Telemetry plane: every step runs inside a ``recovery_step`` span (so
    the black box records what was executing), restores/restarts land
    flight-recorder events, and BOTH a recoverable failure and a fatal
    (propagating) one dump the ring to ``flight_recorder_dir`` — default
    ``<manager.path>/flight_recorder``, the black box next to the
    checkpoints; pass ``False`` to disable.  ``telemetry_port`` (0 =
    ephemeral) serves `/metrics` + `/healthz` for the duration of the run;
    its ``last_step_age`` check fails when no step has completed for
    ``healthy_step_age`` seconds (a wedged loop looks unhealthy, not idle).

    Alerting plane: ``alert_policy`` (an ``observability.alerts.
    AlertPolicy``) is polled after every ``alert_every``-th completed step
    — sense (scrape the fleet, or read the local registry), decide
    (evaluate the rules), act.  A decision whose action is ``"restart"``
    raises :class:`AlertRestart` (a ``Preemption``), so the supervisor
    checkpoint-restores exactly as it would for an eviction — the restart
    decision is finally driven by the scraped series, as the telemetry
    plane left open.  A policy that should never restart this supervisor
    simply maps no alert to ``"restart"``.  A scraper-backed policy
    self-throttles (``AlertPolicy.min_interval_s``, default 15 s), so
    per-step polling never puts a fleet HTTP scrape on the hot path;
    ``alert_every`` additionally coarsens by step count.

    Request-scoped tracing: the whole supervised run is ONE trace
    (``run_with_recovery``) — an ``episode`` span per restart attempt
    (the restart's error and start step as attributes), each checkpoint
    save/load nested inside it, a ``restore`` span per recovery, and the
    steps between saves coalesced into bounded ``steps`` summary spans.
    Restart episodes keep the trace in the tail sampler (any restart is a
    keep), flight events carry its ``trace_id``, and the checkpoint
    histograms carry it as an exemplar — the crash dump's sibling
    ``traces_*.json`` holds the run's causal timeline.

    Goodput plane (ISSUE 20): the whole run keeps a train
    ``goodput.TimeLedger`` — step/compile (backend-compile seconds carved
    out by the PR-14 ``record_compile`` hook)/checkpoint_save/restore/
    restart_backoff leaves, idle the residual — published at every
    episode boundary and conservation-checked + closed at run end; the
    final snapshot is returned under ``"goodput"``.  Pass
    ``goodput_ledger`` to own the ledger (e.g. to attribute
    ``data_wait`` from inside ``step_fn``); ``restart_backoff`` (an
    ``ExponentialBackoff``, default ``None`` = no delay) sleeps between
    a recoverable failure and its restore — the production anti-herd
    pause, attributed to the ``restart_backoff`` bucket.
    """
    recoverable = tuple(recoverable)
    if flight_recorder_dir is None:
        flight_recorder_dir = os.path.join(
            str(manager.path), "flight_recorder")
    flight_dir = flight_recorder_dir or None  # False/"" -> disabled

    def _dump(reason, **extra):
        # best-effort: safe_dump never masks the crash that triggered it
        _flight.safe_dump(flight_dir, reason=reason, extra=extra)

    last_step_mono = [time.monotonic()]
    server = None
    if telemetry_port is not None:
        from ..observability.exporter import TelemetryServer

        def _check_step_age():
            age = time.monotonic() - last_step_mono[0]
            return age < healthy_step_age, f"last completed step {age:.1f}s ago"

        server = TelemetryServer(port=telemetry_port,
                                 recorder=_flight.RECORDER)
        server.register_healthcheck("last_step_age", _check_step_age)
        if alert_policy is not None:
            # /alertz on the training endpoint reports the very engine
            # driving the restarts.  eval_on_request=False: the policy's
            # poll is the one tick source — a scrape must not feed LOCAL
            # registry samples into an engine evaluating SCRAPED ones
            server.attach_alerts(alert_policy.engine,
                                 eval_on_request=False)
        server.start()
    restarts = 0
    dumped_exc = [None]  # the exception the inner handler already dumped
    tr = _tracing.start_trace("run_with_recovery", num_steps=int(num_steps))
    # installed process-wide so CheckpointManager.save's async blocking
    # slice and record_compile's backend-compile seconds land on THIS run
    led = goodput_ledger if goodput_ledger is not None \
        else _goodput.TimeLedger("train")
    _goodput.install(led)
    # per-restart-attempt "episode" span, held open across the step loop;
    # steps coalesce into bounded "steps" summary spans inside it
    ep = {"span": None, "index": 0, "steps": 0, "t0": None}

    def _open_episode(start_step):
        ep["index"] += 1
        ep["span"] = tr.span("episode", index=ep["index"],
                             start_step=int(start_step)).open()
        ep["steps"] = 0
        ep["t0"] = time.perf_counter()

    def _flush_steps():
        if ep["steps"]:
            tr.add_span("steps",
                        duration_s=max(0.0,
                                       time.perf_counter() - ep["t0"]),
                        count=ep["steps"])
        ep["steps"] = 0
        ep["t0"] = time.perf_counter()

    def _close_episode(error=None):
        if ep["span"] is not None:
            _flush_steps()
            ep["span"].close(error=error)
            ep["span"] = None
            led.publish()

    try:
        if manager.latest_step() is not None:
            with led.section("restore"), tr.span("restore", resume=True):
                completed = _restore(manager, set_state, trace=tr)
            _flight.record_event("recovery_resumed", step=completed,
                                 **({"trace_id": tr.trace_id}
                                    if tr.trace_id else {}))
            if on_event:
                on_event("resumed", {"step": completed})
        else:
            completed = 0
            if save_initial:
                # without an initial snapshot, a failure before the first
                # periodic save would leave nothing to restore
                with led.section("checkpoint_save"):
                    manager.save(0, get_state(), force=True, trace=tr)
        _open_episode(completed)
        while completed < num_steps:
            try:
                with led.section("step"), _span("recovery_step"):
                    step_fn(completed)
                completed += 1
                ep["steps"] += 1
                last_step_mono[0] = time.monotonic()
                # get_state() can materialize the whole train state (device
                # -> host sync) — only pay for it on steps that save
                if completed == num_steps:
                    _flush_steps()
                    with led.section("checkpoint_save"):
                        manager.save(completed, get_state(), force=True,
                                     trace=tr)
                elif manager.should_save(completed):
                    _flush_steps()
                    with led.section("checkpoint_save"):
                        manager.save(completed, get_state(), trace=tr)
                if alert_policy is not None \
                        and completed % max(1, int(alert_every)) == 0:
                    for d in alert_policy.poll():
                        if d.action == "restart":
                            raise AlertRestart(d)
                        # this supervisor only executes restarts; other
                        # string actions are for an ElasticManager — and
                        # since the policy marked the episode acted, they
                        # are gone.  Leave a black-box trace, never drop
                        # an actuation silently.
                        _flight.record_event(
                            "alert_decision_unhandled", alert=d.alert,
                            action=d.action, episode=d.episode,
                            handler="run_with_recovery")
            except recoverable as e:
                restarts += 1
                _flight.record_event("recoverable_failure", step=completed,
                                     restarts=restarts, error=repr(e),
                                     **({"trace_id": tr.trace_id}
                                        if tr.trace_id else {}))
                _close_episode(error=repr(e))
                tr.inc_attr("restart_episodes")
                _dump("recoverable", step=completed, error=repr(e))
                dumped_exc[0] = e
                if restarts > max_restarts:
                    raise
                _M_RESTARTS.inc()
                if restart_backoff is not None:
                    pause = restart_backoff.delay(restarts)
                    if pause > 0:
                        with led.section("restart_backoff"):
                            time.sleep(pause)
                with led.section("restore"), tr.span("restore",
                                                     after=repr(e)):
                    completed = _restore(manager, set_state, cause=e,
                                         trace=tr)
                _flight.record_event("recovery_restored", step=completed)
                _open_episode(completed)
                if on_event:
                    on_event("restored", {"step": completed, "error": e})
        _close_episode()
        tr.end("ok", completed=completed, restarts=restarts)
        # close asserts conservation: sum(buckets) == wall span (1e-6)
        snap = led.close(reason="run_end")
        return {"completed": completed, "restarts": restarts,
                "goodput": snap}
    except BaseException as e:
        # anything escaping the supervisor is fatal to THIS run — including
        # a recoverable raised outside the step loop (a Preemption landing
        # mid-restore or mid-initial-save); dump unless the inner handler
        # already dumped this very exception (restarts exhausted)
        _close_episode(error=repr(e))
        tr.end("error", error=repr(e), restarts=restarts)
        if e is not dumped_exc[0]:
            _flight.record_event("fatal_failure", error=repr(e))
            _dump("fatal", error=repr(e))
        # suppressed: a ledger bug must never mask the fatal error
        with contextlib.suppress(Exception):
            led.close(reason="fatal")
        raise
    finally:
        _goodput.uninstall(led)
        if server is not None:
            server.stop()


def _restore(manager, set_state, cause=None, trace=None):
    """Restore the newest valid checkpoint and return ITS step count.

    The loader quarantines corrupt steps and falls back, so the step
    actually restored may be older than latest_step() read beforehand —
    the step returned WITH the state is authoritative (a later
    latest_step() can still name a newer step when the fallback was for a
    transient, non-quarantinable reason)."""
    try:
        state, step = manager.restore(return_step=True, trace=trace)
    except Exception as e:
        # chain from the RESTORE failure (it carries the diagnosis: which
        # step, which digest); the triggering failure rides in the message
        raise RuntimeError(
            "run_with_recovery: no valid checkpoint to restore from"
            + (f" (while recovering from: {cause!r})" if cause else "")
        ) from e
    if step is None:
        raise RuntimeError(
            "run_with_recovery: restored a step-less checkpoint dir — "
            "the manager's path holds no step_* structure to resume from")
    set_state(state)
    _M_RESTORES.inc()
    _M_RESTORED_STEP.set(int(step))
    return int(step)


# ------------------------------------------------------------ signal adapter
class PreemptionNotice:
    """Handle returned by ``install_preemption_handler``: records whether/
    how often the adapter fired (``count``, ``last_signum``) and exposes
    ``preempted`` for polling-style loops (``mode='flag'``)."""

    def __init__(self):
        self._event = threading.Event()
        self.count = 0
        self.last_signum = None

    @property
    def preempted(self) -> bool:
        return self._event.is_set()


@contextlib.contextmanager
def install_preemption_handler(signals=(_signal.SIGTERM, _signal.SIGINT), *,
                               mode="raise", on_preempt=None):
    """Adapt OS termination signals into the ``Preemption`` exception.

    The ROADMAP "real TPU preemption notices" hook: cloud preemption
    delivers SIGTERM ahead of the kill, so a training loop wrapped as ::

        with install_preemption_handler():
            run_with_recovery(step_fn, n, manager, get_state, set_state)

    self-heals on a real eviction exactly like on an injected one — the
    handler raises ``Preemption`` in the main thread, ``run_with_recovery``
    checkpoint-restores, and `preemptions_total` counts the notice.

    ``mode='raise'`` (default) raises from the handler; ``mode='flag'``
    only records — poll the yielded ``PreemptionNotice.preempted`` between
    steps and raise at a safe point yourself.  Previous handlers are
    restored on exit.  Must be entered from the main thread (CPython
    delivers signals there).
    """
    if mode not in ("raise", "flag"):
        raise ValueError(f"mode must be 'raise' or 'flag', got {mode!r}")
    notice = PreemptionNotice()

    def _handler(signum, frame):
        notice.count += 1
        notice.last_signum = signum
        notice._event.set()
        _M_PREEMPTIONS.inc()
        _flight.record_event("preemption", signum=int(signum))
        if on_preempt is not None:
            on_preempt(signum)
        if mode == "raise":
            raise Preemption(
                f"received signal {_signal.Signals(signum).name}: "
                f"the host is being preempted")

    prev = {}
    try:
        for s in signals:
            prev[s] = _signal.signal(s, _handler)
    except ValueError:
        for s, h in prev.items():  # not the main thread: undo partial install
            _signal.signal(s, h)
        raise
    try:
        yield notice
    finally:
        for s, h in prev.items():
            _signal.signal(s, h)
