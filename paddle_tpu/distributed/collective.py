"""Collective communication (ref: python/paddle/distributed/collective.py;
C++ ProcessGroup.h:53; operators/collective/ 148 files; SURVEY.md §5.8).

TPU-native design ("ProcessGroupXLA"): a Group carries mesh-axis metadata; collectives
called INSIDE jit/shard_map emit jax.lax collectives (psum/all_gather/ppermute/
all_to_all) over the named axis — compiled onto ICI by XLA.  Called EAGERLY they
operate on the device-local view: with a single participant they are identity (the
degenerate case the reference handles via ring of size 1); true multi-host eager mode
routes through shard_map over the global mesh.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor.tensor import Tensor, apply_op, _unwrap
from . import env as _env


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


@dataclasses.dataclass
class Group:
    """Ref ProcessGroup (ProcessGroup.h:53) — here: ranks + optional mesh axis name."""

    ranks: list
    gid: int = 0
    axis_name: str | None = None  # set when the group maps onto a mesh axis

    @property
    def nranks(self):
        return len(self.ranks)

    @property
    def world_size(self):
        return len(self.ranks)

    @property
    def rank(self):
        r = _env.get_rank()
        return self.ranks.index(r) if r in self.ranks else -1

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    @property
    def process_group(self):
        return self

    @property
    def id(self):
        return self.gid

    @property
    def name(self):
        return f"group_{self.gid}"


_group_counter = [0]
_default_group: Group | None = None


def _get_default_group() -> Group:
    global _default_group
    if _default_group is None:
        ws = _env.get_world_size()
        _default_group = Group(list(range(ws)), 0, axis_name=None)
    return _default_group


def new_group(ranks=None, backend=None, timeout=None, axis_name=None):
    """Ref collective.py:366."""
    _group_counter[0] += 1
    if ranks is None:
        ranks = list(range(_env.get_world_size()))
    return Group(list(ranks), _group_counter[0], axis_name=axis_name)


def get_group(gid=0):
    return _get_default_group()


def _axis(group):
    g = group or _get_default_group()
    return g.axis_name


def _in_trace(x):
    return isinstance(x, jax.core.Tracer)


def _n_procs():
    try:
        return jax.process_count()
    except Exception:
        return 1


def _eager_allgather(v, group=None):
    """Cross-process eager gather (jax.experimental.multihost_utils): stacks
    each process's local value along a new axis 0 on every host.

    WORLD group only: multihost_utils collectives are global, so a subgroup
    here would silently mix values across groups (or hang when only some
    processes participate) — subgroup communication belongs to the compiled
    path, where mesh axes express it."""
    _require_world_group(group)
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(v)


def _group_mode(group):
    """'world' (communicate over all processes), 'skip' (1-rank group: no
    communication), or raise for true subgroups — eager multihost collectives
    are global, and silently mixing groups is the worst failure mode."""
    if group is None:
        return "world"
    n = _n_procs()
    nranks = getattr(group, "nranks", None)
    if nranks == 1:
        return "skip"
    ax = getattr(group, "axis_name", None)
    if ax is not None:
        # axis groups are built with world-sized rank lists; the axis only
        # covers the world when every OTHER mesh axis has size 1
        from . import topology as _topo

        hcg = _topo.get_hybrid_communicate_group()
        mesh = getattr(hcg, "mesh", None) if hcg is not None else None
        if mesh is not None and ax in mesh.axis_names:
            import numpy as _nx

            world = int(_nx.prod([mesh.shape[a] for a in mesh.axis_names]))
            if int(mesh.shape[ax]) == world:
                return "world"
            if int(mesh.shape[ax]) == 1:
                return "skip"
            raise NotImplementedError(
                f"eager cross-process collective over mesh axis {ax!r} "
                f"(a subgroup of the {world}-device world): run it inside a "
                "jitted/shard_map step where the mesh axis expresses the group")
        # axis not resolvable against any mesh: fall through to the rank-count
        # check below — never assume world for an unverified subgroup
    if nranks in (None, n):
        return "world"
    raise NotImplementedError(
        f"eager cross-process collectives support only the world group "
        f"({n} processes); got a {nranks}-rank subgroup. Run subgroup "
        "collectives inside a jitted/shard_map step where the mesh axis "
        "expresses the group.")


def _require_world_group(group):
    return _group_mode(group)


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """Ref collective.py:711.  In-jit w/ axis: lax.psum over ICI; eager 1-rank: identity."""
    ax = _axis(group)

    def _f(v):
        if ax is not None and _in_trace(v):
            if op == ReduceOp.SUM:
                return jax.lax.psum(v, ax)
            if op == ReduceOp.MAX:
                return jax.lax.pmax(v, ax)
            if op == ReduceOp.MIN:
                return jax.lax.pmin(v, ax)
            if op == ReduceOp.AVG:
                return jax.lax.pmean(v, ax)
            raise NotImplementedError("PROD all_reduce inside jit")
        if not _in_trace(v) and _n_procs() > 1:
            if _group_mode(group) == "skip":
                return v
            g = _eager_allgather(v, group)   # [n_procs, ...]
            if op == ReduceOp.SUM:
                return jnp.sum(g, 0)
            if op == ReduceOp.MAX:
                return jnp.max(g, 0)
            if op == ReduceOp.MIN:
                return jnp.min(g, 0)
            if op == ReduceOp.AVG:
                return jnp.mean(g, 0)
            if op == ReduceOp.PROD:
                return jnp.prod(g, 0)
            raise ValueError(f"unknown ReduceOp {op!r}")
        return v  # single-participant eager view

    out = apply_op(_f, (tensor,), name="all_reduce")
    if isinstance(tensor, Tensor) and not _in_trace(tensor._value):
        tensor.set_value(out._value)
        return tensor
    return out


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    """Ref collective.py:915.  In-jit: lax.all_gather; returns list for API parity."""
    ax = _axis(group)
    g = group or _get_default_group()

    def _f(v):
        if ax is not None and _in_trace(v):
            return jax.lax.all_gather(v, ax)
        if not _in_trace(v) and _n_procs() > 1:
            if _group_mode(group) == "skip":
                return v[None]
            return _eager_allgather(v, group)
        return v[None]

    out = apply_op(_f, (tensor,), name="all_gather")
    if tensor_list is not None:
        n = out.shape[0]
        for i in range(n):
            tensor_list.append(out[i])
        return
    return out


def all_gather_object(object_list, obj, group=None):
    object_list.append(obj)


def broadcast(tensor, src, group=None, sync_op=True):
    """In-jit SPMD: values are already consistent per sharding; eager
    multi-process: every rank adopts rank `src`'s value."""
    v = tensor._value if isinstance(tensor, Tensor) else tensor
    if not _in_trace(v) and _n_procs() > 1:
        if _group_mode(group) == "skip":
            return tensor
        if not 0 <= int(src) < _n_procs():
            raise ValueError(
                f"broadcast src={src} out of range for {_n_procs()} processes")
        from jax.experimental import multihost_utils

        # one-to-all primitive: ships ONE copy instead of allgathering
        # n_procs copies and keeping a slice
        out = multihost_utils.broadcast_one_to_all(
            v, is_source=jax.process_index() == int(src))
        if isinstance(tensor, Tensor):
            tensor.set_value(out)
            return tensor
        return Tensor(out)
    return tensor


def broadcast_object_list(object_list, src=0, group=None):
    return object_list


def reduce(tensor, dst, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op, group, sync_op)


def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM, group=None, sync_op=True):
    ax = _axis(group)

    def _f(*vs):
        v = jnp.stack(vs) if len(vs) > 1 else vs[0]
        if ax is not None and _in_trace(v):
            return jax.lax.psum_scatter(v, ax, tiled=False)
        return vs[0] if len(vs) == 1 else v[0]

    src = tensor_list if isinstance(tensor_list, (list, tuple)) else [tensor_list]
    out = apply_op(_f, tuple(src), name="reduce_scatter")
    if isinstance(tensor, Tensor):
        tensor.set_value(out._value if isinstance(out, Tensor) else out)
    return out


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if tensor_list:
        tensor.set_value(_unwrap(tensor_list[0]))
    return tensor


def alltoall(in_tensor_list, out_tensor_list, group=None, sync_op=True):
    """Ref collective.py:1844 (+ global_scatter/global_gather MoE ops).
    In-jit: lax.all_to_all over the axis."""
    ax = _axis(group)
    if isinstance(in_tensor_list, Tensor):
        # tensor form: split axis 0 across ranks
        def _f(v):
            if ax is not None and _in_trace(v):
                n = jax.lax.axis_size(ax)
                vr = v.reshape(n, v.shape[0] // n, *v.shape[1:])
                return jax.lax.all_to_all(vr, ax, split_axis=0, concat_axis=0, tiled=False).reshape(v.shape)
            return v

        return apply_op(_f, (in_tensor_list,), name="alltoall")
    # list form, eager single-rank: identity copy
    for t in in_tensor_list:
        out_tensor_list.append(t.clone() if isinstance(t, Tensor) else t)


def all_to_all(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    return alltoall(in_tensor_list, out_tensor_list, group, sync_op)


def send(tensor, dst=0, group=None, sync_op=True):
    raise NotImplementedError(
        "point-to-point send/recv are expressed as ppermute inside compiled pipeline "
        "programs on TPU (see meta_parallel.pipeline_parallel); eager p2p is not supported"
    )


def recv(tensor, src=0, group=None, sync_op=True):
    raise NotImplementedError(
        "point-to-point send/recv are expressed as ppermute inside compiled pipeline "
        "programs on TPU (see meta_parallel.pipeline_parallel); eager p2p is not supported"
    )


isend = send
irecv = recv


def barrier(group=None):
    jnp.zeros(()).block_until_ready()


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor) and hasattr(tensor._value, "block_until_ready"):
        tensor._value.block_until_ready()


def destroy_process_group(group=None):
    global _default_group
    _default_group = None


# in-jit helpers used by meta_parallel layers (explicit-axis forms)
def psum(x, axis_name):
    return jax.lax.psum(x, axis_name)


def ppermute(x, axis_name, perm):
    return jax.lax.ppermute(x, axis_name, perm)


def axis_index(axis_name):
    return jax.lax.axis_index(axis_name)
