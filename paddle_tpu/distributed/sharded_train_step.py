"""ShardedTrainStep: hybrid-parallel compiled training over a device Mesh.

Reference analog: the whole fleet wrapper stack — DataParallel grad allreduce
(EagerReducer reducer.h:88), TensorParallel (mp_layers NCCL calls), sharding stage 1/2
(GroupShardedOptimizerStage2: slice grads + scatter optimizer state,
group_sharded_optimizer_stage2.py:48) and stage 3 (param sharding,
group_sharded_stage3.py:60) — all of which rewrite the eager program with hooks.

TPU-native: ONE jitted step with NamedShardings:
  - batch sharded over ('dp','sharding') — data parallelism,
  - params/opt-state sharded per layer annotations ('mp' for TP layers),
  - ZeRO: stage>=1 shards optimizer state over the 'sharding' axis, stage 3 also
    shards parameters; XLA inserts reduce-scatter/all-gather exactly where the
    reference's hooks did, but fused and overlapped by the scheduler.
"""
from __future__ import annotations

import os

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..tensor.tensor import Tensor
from ..framework import random as _random
from ..jit._step_impl import build_step_fn, init_scaler_state
from ..observability import goodput as _goodput
from ..observability import metrics as _obs
from ..observability import profiling as _profiling
from ..observability import slo as _slo
from ..observability.spans import span as _span
from .sharding_ctx import mesh_scope, param_sharding

# Per-step training telemetry (names documented in README §Observability;
# tools/metrics_lint.py polices the namespace).
_M_STEPS = _obs.counter(
    "train_steps_total", "Sharded train steps executed")
_M_STEP_SECONDS = _obs.histogram(
    "train_step_duration_seconds",
    "Wall-clock latency of one sharded train step call (dispatch + "
    "donated-buffer backpressure; excludes the first compile call)")
_M_COMPILE_SECONDS = _obs.gauge(
    "train_compile_seconds",
    "Duration of the first train-step call (trace + XLA compile)")
_M_TOKENS = _obs.counter(
    "train_tokens_total",
    "Training tokens consumed (batch x seq for rank-2 inputs, else samples)")
_M_TOKENS_PER_S = _obs.gauge(
    "train_tokens_per_second", "Token throughput of the latest step")
_M_FLOPS_PER_S = _obs.gauge(
    "train_model_flops_per_second",
    "Achieved FLOP/s (HLO-estimated step FLOPs / step wall time); "
    "populated once compiled_stats() has run")
_M_MFU = _obs.gauge(
    "train_mfu_ratio",
    "Model FLOP utilization: achieved FLOP/s over the device peak "
    "(cost_model.peak_flops_per_device); 0 until the peak is known")
_M_COLLECTIVE_BYTES = _obs.gauge(
    "train_collective_bytes",
    "Per-device collective payload bytes per compiled step (census.py)",
    labelnames=("op",))


def _zero_spec(shape, spec, axis_name, mesh):
    """Extend a param spec with ZeRO sharding over `axis_name` on the first
    divisible, unsharded dim; replicate if none divides."""
    n = mesh.shape[axis_name]
    if n == 1:
        return spec
    spec = list(spec) if spec is not None else [None] * len(shape)
    while len(spec) < len(shape):
        spec.append(None)
    for i, d in enumerate(shape):
        if spec[i] is None and d % n == 0:
            spec[i] = axis_name
            break
    return tuple(spec)


class ShardedTrainStep:
    def __init__(self, model, loss_fn, optimizer, mesh: Mesh, batch_spec=None,
                 zero_stage: int = 0, donate: bool = True, accum_steps: int = 1,
                 scaler=None):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh
        if zero_stage == 0:
            # honor a prior group_sharded_parallel(model, opt, level) call —
            # that API records the requested ZeRO stage on the model
            zero_stage = int(getattr(model, "_group_sharded_stage", 0) or 0)
        self.zero_stage = zero_stage
        # batch axis 0 sharded over all data-like mesh axes present
        data_axes = tuple(a for a in ("dp", "sharding") if a in mesh.axis_names and mesh.shape[a] > 1)
        self.batch_spec = batch_spec if batch_spec is not None else P(data_axes if data_axes else None)
        _profiling.install_compile_hooks()
        self._jitted = None
        self._opt_state = None
        self._param_sharding = None
        self._opt_sharding = None
        self._donate = donate
        self.accum_steps = max(1, int(accum_steps))
        self.scaler = scaler
        self._scaler_state = None
        self._est_step_flops = None  # filled by compiled_stats()
        self._peak_flops = None      # device peak, resolved once per process

    def _specs(self):
        named = dict(self.model.named_parameters())
        pshard, oshard = {}, {}
        for k, p in named.items():
            spec = getattr(p, "sharding_spec", None)
            shape = tuple(p._value.shape)
            base = tuple(spec) if spec is not None else tuple([None] * len(shape))
            if self.zero_stage >= 3 and "sharding" in self.mesh.axis_names:
                base = _zero_spec(shape, base, "sharding", self.mesh)
            pshard[k] = NamedSharding(self.mesh, P(*_clean(base, self.mesh)))
            obase = base
            if self.zero_stage >= 1 and self.zero_stage < 3 and "sharding" in self.mesh.axis_names:
                obase = _zero_spec(shape, base, "sharding", self.mesh)
            oshard[k] = NamedSharding(self.mesh, P(*_clean(obase, self.mesh)))
        return pshard, oshard

    def _init(self, batch):
        named = dict(self.model.named_parameters())
        trainable = {k for k, p in named.items() if not p.stop_gradient}
        self._param_names = list(named.keys())
        pshard, oshard = self._specs()
        self._param_sharding = pshard

        # place params according to shardings
        for k, p in named.items():
            p._rebind(jax.device_put(p._value, pshard[k]))
        for k, b in self.model.named_buffers():
            b._rebind(jax.device_put(b._value, NamedSharding(self.mesh, P())))

        # a checkpoint restore may have pre-populated _opt_state — keep it and
        # only (re)place the leaves onto this mesh's shardings
        restored = self._opt_state or {}
        self._opt_state = {
            k: jax.tree.map(lambda v: jax.device_put(v, oshard[k] if hasattr(v, "shape") and v.shape == named[k]._value.shape else NamedSharding(self.mesh, P())),
                            restored.get(k, None) if restored.get(k, None) is not None
                            else self.optimizer._init_state(named[k]))
            for k in trainable
        }

        mesh = self.mesh
        self._scaler_state = init_scaler_state(self.scaler)
        mb_sharding = NamedSharding(mesh, P(None, *tuple(self.batch_spec)))

        def mb_constraint(a):
            return jax.lax.with_sharding_constraint(a, mb_sharding)

        inner = build_step_fn(self.model, self.loss_fn, self.optimizer, named,
                              trainable, accum_steps=self.accum_steps,
                              scaler=self.scaler, cast_loss_f32=True,
                              mb_constraint=mb_constraint)

        rep = NamedSharding(mesh, P())

        def _opt_leaf_sharding(k):
            pshape = tuple(named[k]._value.shape)
            return lambda leaf: (oshard[k] if hasattr(leaf, "shape") and tuple(leaf.shape) == pshape else rep)

        opt_shardings = {k: jax.tree.map(_opt_leaf_sharding(k), self._opt_state[k])
                         for k in self._opt_state}
        scaler_shardings = (jax.tree.map(lambda _: rep, self._scaler_state)
                            if self._scaler_state is not None else None)
        batch_shardings = tuple(NamedSharding(mesh, self.batch_spec) for _ in batch)
        in_shardings = (pshard, rep, opt_shardings, scaler_shardings, rep, rep,
                        *batch_shardings)
        out_shardings = (pshard, rep, opt_shardings, scaler_shardings, rep, rep)

        def traced(*args):
            with mesh_scope(mesh):
                return inner(*args)

        donate = (0, 2) if self._donate else ()
        _profiling.record_compile("train_step")
        self._jitted = jax.jit(traced, in_shardings=in_shardings, out_shardings=out_shardings,
                               donate_argnums=donate)

    def _compile_for_analysis(self, *batch):
        """AOT-compile the step on example inputs for census/per-op
        analysis.  Deliberately NOT cached on self: the executable can hold
        hundreds of MB of host memory, and every analysis entrypoint is a
        startup-time call, not a hot path."""
        raw = tuple(b._value if isinstance(b, Tensor) else jnp.asarray(b) for b in batch)
        if self._jitted is None:
            self._init(raw)
        params, buffers = self.model.functional_state()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        key = _random.get_rng_key()
        return self._jitted.lower(
            params, buffers, self._opt_state, self._scaler_state, lr, key, *raw
        ).compile()

    def compiled_stats(self, *batch):
        """Collective-traffic census of the compiled step (census.py):
        per-device bytes for all-reduce / all-gather / reduce-scatter /
        ppermute / all-to-all plus HLO-estimated FLOPs."""
        from .census import collective_census

        census = collective_census(self._compile_for_analysis(*batch))
        # publish the census so the interconnect traffic of the *current*
        # compiled step is always scrapeable next to the latency series
        self._est_step_flops = census.get("est_step_flops")
        if _obs.enabled():
            for op, key_ in (("all-reduce", "bytes_allreduce"),
                             ("all-gather", "bytes_allgather"),
                             ("reduce-scatter", "bytes_reducescatter"),
                             ("collective-permute", "bytes_ppermute"),
                             ("all-to-all", "bytes_alltoall")):
                _M_COLLECTIVE_BYTES.labels(op=op).set(census[key_])
        return census

    def per_op_stats(self, *batch, json_path=None):
        """Per-op flops/bytes of the compiled step (``census.per_op_census``)
        — the cost half of the census<->timeline join
        ``tools/trace_report.py`` performs against a recorded trace.
        Optionally writes the table as JSON to ``json_path``."""
        from .census import per_op_census

        ops = per_op_census(self._compile_for_analysis(*batch))
        if json_path is not None:
            import json

            with open(json_path, "w") as f:
                json.dump(ops, f)
        return ops

    def _record_step_metrics(self, dt, raw, compiled_call):
        if compiled_call:
            _M_COMPILE_SECONDS.set(dt)
            return
        _M_STEPS.inc()
        _M_STEP_SECONDS.observe(dt)
        _slo.track("train_step", dt)
        if raw and hasattr(raw[0], "shape"):
            shape = raw[0].shape
            # rank-2 inputs are (batch, seq) -> tokens; anything else
            # (vision NCHW etc.) counts samples, not dim products
            tokens = (int(shape[0]) * int(shape[1]) if len(shape) == 2
                      else int(shape[0]) if len(shape) else 1)
            if tokens and dt > 0:
                _M_TOKENS.inc(tokens)
                _M_TOKENS_PER_S.set(tokens / dt)
        if self._est_step_flops and dt > 0:
            achieved = self._est_step_flops / dt
            _M_FLOPS_PER_S.set(achieved)
            if self._peak_flops is None:
                # resolve once: device kind cannot change within the process,
                # and this sits in the per-step instrumentation path
                from ..cost_model import peak_flops_per_device

                self._peak_flops = peak_flops_per_device()
            # est_step_flops comes from the per-device SPMD program, so the
            # ratio is already per-device — no mesh-size factor
            if self._peak_flops > 0:
                _M_MFU.set(achieved / self._peak_flops)

    def __call__(self, *batch):
        if not _obs.enabled():
            return self._step(*batch)
        compiled_call = self._jitted is None
        # goodput ledger: attributes to `step` on the active train ledger
        # (backend-compile seconds inside a first call are carved out to
        # `compile` by the record_compile hook); nested same-bucket under
        # run_with_recovery's own step section — never double-counted
        with _goodput.active_section("train", "step"), \
                _span("sharded_train_step") as sp:
            out = self._step(*batch)
        self._record_step_metrics(sp.duration,
                                  tuple(getattr(b, "_value", b) for b in batch),
                                  compiled_call)
        return out

    def _step(self, *batch):
        raw = tuple(b._value if isinstance(b, Tensor) else jnp.asarray(b) for b in batch)
        if self._jitted is None:
            self._init(raw)
            if _obs.enabled() and os.environ.get(
                    "PADDLE_TPU_OBS_CENSUS", "").lower() in ("1", "true", "on"):
                # opt-in: one extra AOT compile buys per-step MFU/collective
                # gauges without the caller wiring compiled_stats() itself
                try:
                    self.compiled_stats(*batch)
                except Exception:
                    pass
        if self.scaler is not None and getattr(self.scaler, "_host_dirty", False):
            self._scaler_state = init_scaler_state(self.scaler)
            self.scaler._host_dirty = False
        params, buffers = self.model.functional_state()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        key = _random.get_rng_key()
        new_params, new_buffers, new_opt, new_scaler, loss, aux = self._jitted(
            params, buffers, self._opt_state, self._scaler_state, lr, key, *raw
        )
        self._opt_state = new_opt
        self._scaler_state = new_scaler
        if new_scaler is not None:
            self.scaler._attach_device_state(new_scaler)
        self.model.load_functional_state(new_params, new_buffers)
        self.optimizer._step_count += 1
        loss_t = Tensor(loss)
        if aux:
            return (loss_t, *[Tensor(a) for a in aux])
        return loss_t


def _clean(spec, mesh):
    return tuple(s if (s is None or s in mesh.axis_names) else None for s in spec)
