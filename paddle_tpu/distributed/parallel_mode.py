"""ParallelMode + distributed.split + gloo CPU-barrier helpers + PS dataset
stubs (ref: python/paddle/distributed/parallel.py ParallelMode,
collective.py split:?, parallel.py gloo_init_parallel_env; fleet dataset
classes are parameter-server ingestion — an explicit non-goal, SURVEY §7.4).
"""
from __future__ import annotations

import warnings


class ParallelMode:
    """Ref distributed/parallel.py ParallelMode constants."""

    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


_split_layer_cache = {}


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Megatron-style split linear/embedding (ref distributed/collective.py
    split): builds the Column/Row-parallel layer and applies it.

    The created parameters are cached by `name` so repeated forward calls
    train ONE set of weights; pass a unique name per call site (an automatic
    shape-derived key is used otherwise, which collides for two same-shaped
    splits — hence the warning)."""
    from .meta_parallel.mp_layers import (
        ColumnParallelLinear,
        RowParallelLinear,
        VocabParallelEmbedding,
    )

    if operation not in ("linear", "embedding"):
        raise ValueError(f"operation must be 'linear' or 'embedding', got {operation}")
    key = name
    if key is None:
        key = f"{operation}:{tuple(size)}:{axis}:{num_partitions}"
        warnings.warn(
            "distributed.split called without `name`: parameters are cached "
            "by an automatic shape key, which collides if two same-shaped "
            "splits exist — pass a unique name per call site", stacklevel=2)
    layer = _split_layer_cache.get(key)
    if layer is None:
        if operation == "embedding":
            layer = VocabParallelEmbedding(size[0], size[1], weight_attr=weight_attr)
        elif axis == 1:
            layer = RowParallelLinear(size[0], size[1], has_bias=bias_attr is not False,
                                      input_is_parallel=False, weight_attr=weight_attr)
        else:
            layer = ColumnParallelLinear(size[0], size[1],
                                         has_bias=bias_attr is not False,
                                         gather_output=gather_out,
                                         weight_attr=weight_attr)
        _split_layer_cache[key] = layer
    return layer(x)


# ------------------------------------------------------------- gloo helpers

_gloo_store = None
_gloo_rank = None
_gloo_n = None


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """CPU rendezvous over the TCPStore (the reference uses a gloo HTTP
    store; same contract: rank 0 hosts, everyone meets)."""
    global _gloo_store, _gloo_rank, _gloo_n
    from .store import TCPStore

    host, port = server_endpoint.rsplit(":", 1)
    _gloo_store = TCPStore(host, int(port), is_master=(int(rank_id) == 0),
                           world_size=int(rank_num))
    _gloo_rank, _gloo_n = int(rank_id), int(rank_num)
    _gloo_store.add("gloo/init", 1)
    _gloo_store.wait(["gloo/init"])


def gloo_barrier():
    """Block until every rank arrives (counter on the shared store)."""
    if _gloo_store is None:
        raise RuntimeError("call gloo_init_parallel_env first")
    n = _gloo_store.add("gloo/barrier", 1)
    gen = (n - 1) // _gloo_n  # barrier generation this arrival belongs to
    import time

    deadline = time.monotonic() + 300  # NTP slew must not shrink the window
    while _gloo_store.add("gloo/barrier", 0) < (gen + 1) * _gloo_n:
        if time.monotonic() > deadline:
            raise TimeoutError("gloo_barrier timed out")
        time.sleep(0.01)


def gloo_release():
    global _gloo_store
    if _gloo_store is not None:
        close = getattr(_gloo_store, "close", None)
        if close:
            close()
        _gloo_store = None


# ------------------------------------------------- PS dataset stubs (§7.4)

def _ps_stub(cls_name):
    class _Stub:
        def __init__(self, *a, **k):
            raise NotImplementedError(
                f"{cls_name} belongs to the parameter-server ingestion stack, "
                f"an explicit non-goal of the TPU build (SURVEY §7.4); use "
                f"paddle.io.Dataset/DataLoader for input pipelines")

    _Stub.__name__ = cls_name
    return _Stub


QueueDataset = _ps_stub("QueueDataset")
InMemoryDataset = _ps_stub("InMemoryDataset")
ProbabilityEntry = _ps_stub("ProbabilityEntry")
CountFilterEntry = _ps_stub("CountFilterEntry")
ShowClickEntry = _ps_stub("ShowClickEntry")
