"""Hybrid-parallel topology over a jax.sharding.Mesh.

Reference: `CommunicateTopology`/`HybridCommunicateGroup`
(python/paddle/distributed/fleet/base/topology.py:52,134) building the 4-D rank mesh
[dp, pp, sharding, mp] and per-axis comm groups.  TPU-native: the rank mesh IS a
jax.sharding.Mesh whose axes are the parallelism dimensions; "comm groups" become
named mesh axes that collectives reference inside jit/shard_map.  Axis order follows
the reference's hybrid_configs convention plus net-new 'sep' (sequence parallel).
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

from . import env as _env
from .collective import Group, new_group

# canonical axis order (outermost first): pp slowest, mp innermost like the reference
AXIS_ORDER = ("pp", "dp", "sharding", "sep", "mp")


class CommunicateTopology:
    """Ref topology.py:52."""

    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "model"),
                 dims=(1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = None
        self._world = int(np.prod(self._dims))

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    def world_size(self):
        return self._world

    def get_rank(self, **kwargs):
        coords = [kwargs[n] for n in self._parallel_names]
        return int(np.ravel_multi_index(coords, self._dims))

    def get_coord(self, rank):
        return tuple(int(i) for i in np.unravel_index(rank, self._dims))

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        ranks = [r for r in range(self._world) if self.get_coord(r)[axis] == index]
        return ranks

    def get_dim_size(self, axis_name):
        return self.get_dim(axis_name)

    def get_comm_list(self, axis_name):
        axis = self._parallel_names.index(axis_name)
        other_dims = [d for i, d in enumerate(self._dims) if i != axis]
        lists = []
        for flat in range(int(np.prod(other_dims)) if other_dims else 1):
            coords = list(np.unravel_index(flat, other_dims)) if other_dims else []
            group = []
            for k in range(self._dims[axis]):
                full = coords[:axis] + [k] + coords[axis:]
                group.append(self.get_rank(**dict(zip(self._parallel_names, full))))
            lists.append(group)
        return lists


def build_mesh(dp=1, mp=1, pp=1, sharding=1, sep=1, devices=None) -> Mesh:
    """Create the device mesh for a hybrid strategy.  Axis layout puts mp
    innermost so tensor-parallel collectives ride the fastest ICI links
    (scaling-book recipe).

    On real TPU topologies the assignment goes through
    mesh_utils.create_device_mesh (single slice: ICI-nearest-neighbor
    placement per axis) or create_hybrid_device_mesh (multi-host with DCN:
    the outermost data axes span hosts, mp/sep stay inside a slice) instead
    of a naive flat reshape — the reshape order is only correct by accident
    on some topologies."""
    shape = (pp, dp, sharding, sep, mp)
    need = int(np.prod(shape))
    if devices is None:
        all_devs = jax.devices()
        if all_devs[0].platform == "tpu" and len(all_devs) == need:
            from jax.experimental import mesh_utils

            try:
                n_hosts = max(getattr(d, "process_index", 0) for d in all_devs) + 1
                if n_hosts > 1:
                    per_host = len(all_devs) // n_hosts
                    # split each axis into a DCN (cross-host) and ICI part:
                    # data-like axes absorb the host dimension outermost
                    dcn = [1] * len(shape)
                    ici = list(shape)
                    rest = n_hosts
                    for i in (1, 2, 0):        # dp, sharding, then pp over DCN
                        g = int(np.gcd(ici[i], rest))
                        dcn[i] *= g
                        ici[i] //= g
                        rest //= g
                        if rest == 1:
                            break
                    if rest == 1 and per_host == int(np.prod(ici)):
                        dev = mesh_utils.create_hybrid_device_mesh(
                            tuple(ici), tuple(dcn), devices=all_devs)
                        return Mesh(dev, AXIS_ORDER)
                dev = mesh_utils.create_device_mesh(shape, devices=all_devs)
                return Mesh(dev, AXIS_ORDER)
            except Exception as e:
                import warnings

                warnings.warn(
                    f"mesh_utils device assignment failed ({e!r}); falling "
                    "back to flat reshape — axis-to-ICI placement may be "
                    "suboptimal on this topology", stacklevel=2)
        devices = np.array(all_devs)
    if len(devices) < need:
        raise ValueError(f"need {need} devices, have {len(devices)}")
    dev = np.asarray(devices)[:need].reshape(shape)
    return Mesh(dev, AXIS_ORDER)


class HybridCommunicateGroup:
    """Ref topology.py:134.  Wraps a Mesh; exposes the reference's group getters."""

    def __init__(self, topology=None, dp=None, mp=None, pp=None, sharding=None, sep=1):
        if topology is not None and dp is None:
            dims = {n: topology.get_dim(n) for n in topology.get_hybrid_group_names()}
            dp = dims.get("data", 1)
            mp = dims.get("model", 1)
            pp = dims.get("pipe", 1)
            sharding = dims.get("sharding", 1)
        self._dp_degree = dp or 1
        self._mp_degree = mp or 1
        self._pp_degree = pp or 1
        self._sharding_degree = sharding or 1
        self._sep_degree = sep or 1
        self._topo = topology
        total = self._dp_degree * self._mp_degree * self._pp_degree * self._sharding_degree * self._sep_degree
        n_dev = len(jax.devices())
        self.mesh = None
        if total <= n_dev:
            self.mesh = build_mesh(self._dp_degree, self._mp_degree, self._pp_degree,
                                   self._sharding_degree, self._sep_degree)
        self.global_rank = _env.get_rank()
        self._dp_group = new_group(axis_name="dp")
        self._mp_group = new_group(axis_name="mp")
        self._pp_group = new_group(axis_name="pp")
        self._sharding_group = new_group(axis_name="sharding")
        self._sep_group = new_group(axis_name="sep")

    # --- degree / rank getters (ref topology.py get_*_parallel_*)
    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def _coord(self):
        """This process's coordinate in the mesh = coordinate of its first
        addressable device (per-rank coordinates only exist at process
        granularity on TPU; within a process SPMD materializes them inside
        shard_map).  Single-process: (0,0,0,0,0)."""
        if self.mesh is not None and jax.process_count() > 1:
            local_ids = {d.id for d in jax.local_devices()}
            devs = self.mesh.devices
            for idx in np.ndindex(devs.shape):
                if devs[idx].id in local_ids:
                    return tuple(int(i) for i in idx)
        return (0, 0, 0, 0, 0)

    def get_data_parallel_rank(self):
        return self._coord()[1]

    def get_model_parallel_rank(self):
        return self._coord()[4]

    def get_stage_id(self):
        return self._coord()[0]

    def get_sharding_parallel_rank(self):
        return self._coord()[2]

    def get_data_parallel_group(self):
        return self._dp_group

    def get_model_parallel_group(self):
        return self._mp_group

    def get_pipe_parallel_group(self):
        return self._pp_group

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_sep_parallel_group(self):
        return self._sep_group

    def get_check_parallel_group(self, *a, **k):
        return self._mp_group

    def get_rank_from_stage(self, stage_id, **kwargs):
        """PROCESS rank owning pipeline stage `stage_id` at this process's
        other coordinates (overridable via kwargs, ref topology.py).  On a
        multi-device-per-process mesh this is the owning process index, not a
        per-device ordinal."""
        coord = list(self._coord())
        coord[0] = stage_id
        for i, name in enumerate(("pp", "dp", "sharding", "sep", "mp")):
            if name in kwargs:
                coord[i] = kwargs[name]
        if self.mesh is not None:
            dev = self.mesh.devices[tuple(coord)]
            return int(getattr(dev, "process_index", 0))
        dims = (self._pp_degree, self._dp_degree, self._sharding_degree,
                self._sep_degree, self._mp_degree)
        return int(np.ravel_multi_index(coord, dims))

    def topology(self):
        return self._topo


_hcg: HybridCommunicateGroup | None = None


def get_hybrid_communicate_group():
    return _hcg


def set_hybrid_communicate_group(hcg):
    global _hcg
    _hcg = hcg
