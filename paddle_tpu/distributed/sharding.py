"""group_sharded_parallel API (ref: python/paddle/distributed/sharding/group_sharded.py
wrapping GroupShardedStage2/3 + GroupShardedOptimizerStage2).

TPU-native: ZeRO is a sharding-rule decision, not a hook pipeline.  This returns the
model/optimizer unchanged but records the requested stage; ShardedTrainStep reads it
and shards optimizer state (stage 1/2) or parameters too (stage 3) over the
'sharding' mesh axis — XLA emits the reduce-scatter/all-gather the reference's
GroupSharded hooks performed manually.
"""
from __future__ import annotations


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False, buffer_max_size=2**23,
                           segment_size=2**20, sync_comm=False):
    stage = {"os": 1, "os_g": 2, "p_g_os": 3}.get(level, 2)
    model._group_sharded_stage = stage
    optimizer._group_sharded_stage = stage
    if scaler is not None:
        return model, optimizer, scaler
    return model, optimizer


def save_group_sharded_model(model, output, optimizer=None):
    from ..framework.io import save

    save(model.state_dict(), output + ".pdmodel.state")
    if optimizer is not None:
        save(optimizer.state_dict(), output + ".pdopt")
