"""Collective-traffic census of a compiled step (perf evidence for meshes
the attached hardware cannot run).

Ref analog: the reference's cost model + profiler count NCCL bytes per step
(fleet/meta_optimizers' cost models); here the numbers come straight from
the optimized HLO: every cross-device collective op's output bytes, per
device, per step.  Used by the driver dryrun to record
{bytes_allreduce, bytes_ppermute, ...} for the hybrid LLaMA step.
"""
from __future__ import annotations

import re

_DT_BYTES = {"pred": 1, "s8": 1, "u8": 1, "bf16": 2, "f16": 2, "s16": 2,
             "u16": 2, "f32": 4, "s32": 4, "u32": 4, "f64": 8, "s64": 8,
             "u64": 8, "c64": 8, "c128": 16}

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "collective-permute", "all-to-all")


def _shape_bytes(text, reduce="sum"):
    """Bytes of the `dtype[d0,d1,...]` groups in `text`.

    Async `-start` tuples print the aliased operand group(s) alongside the
    result group(s), so per-op conventions recover the payload:
    - 'half_sum' (all-reduce / permute / all-to-all: operand size == result
      size, possibly VARIADIC combined): sum/2 — a max would undercount the
      combined case.
    - 'max' (all-gather / reduce-scatter: operand and result sizes differ):
      the larger group is the full participating buffer, i.e. the payload.
    """
    sizes = []
    for dt, dims in re.findall(r"(\w+)\[([0-9,]*)\]", text):
        if dt not in _DT_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        sizes.append(n * _DT_BYTES[dt])
    if not sizes:
        return 0
    if reduce == "half_sum":
        return sizes[0] if len(sizes) == 1 else sum(sizes) // 2
    if reduce == "max":
        return max(sizes)
    return sum(sizes)


def collective_census(compiled):
    """{op: {"count": n, "bytes": per-device output bytes}} + est_flops.

    `compiled` is a jax Compiled (jitted.lower(*args).compile()).  Bytes are
    the collectives' OUTPUT payloads summed over the program — the per-step,
    per-device traffic the interconnect must carry (a while-loop body is
    counted once; multiply by trip count externally if needed).
    """
    txt = compiled.as_text()
    out = {op: {"count": 0, "bytes": 0} for op in _COLLECTIVES}
    for line in txt.splitlines():
        for op in _COLLECTIVES:
            # match the sync opcode OR the async -start form (XLA's default
            # on TPU); -done carries the same payload and is skipped so each
            # collective is counted once
            m = re.search(rf"=\s*(.*?)\s{re.escape(op)}(-start)?\(", line)
            if m and f"{op}-done" not in line:
                out[op]["count"] += 1
                if m.group(2):  # async form: tuple aliases operands
                    red = ("max" if op in ("all-gather", "reduce-scatter")
                           else "half_sum")
                else:
                    red = "sum"
                out[op]["bytes"] += _shape_bytes(m.group(1), reduce=red)
                break
    flops = None
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        flops = float(ca.get("flops", 0.0))
    except Exception:
        pass
    return {
        "bytes_allreduce": out["all-reduce"]["bytes"],
        "bytes_allgather": out["all-gather"]["bytes"],
        "bytes_reducescatter": out["reduce-scatter"]["bytes"],
        "bytes_ppermute": out["collective-permute"]["bytes"],
        "bytes_alltoall": out["all-to-all"]["bytes"],
        "counts": {op: v["count"] for op, v in out.items()},
        "est_step_flops": flops,
    }
