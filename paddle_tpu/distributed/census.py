"""Collective-traffic census of a compiled step (perf evidence for meshes
the attached hardware cannot run).

Ref analog: the reference's cost model + profiler count NCCL bytes per step
(fleet/meta_optimizers' cost models); here the numbers come straight from
the optimized HLO: every cross-device collective op's output bytes, per
device, per step.  Used by the driver dryrun to record
{bytes_allreduce, bytes_ppermute, ...} for the hybrid LLaMA step.
"""
from __future__ import annotations

import re

_DT_BYTES = {"pred": 1, "s8": 1, "u8": 1, "bf16": 2, "f16": 2, "s16": 2,
             "u16": 2, "f32": 4, "s32": 4, "u32": 4, "f64": 8, "s64": 8,
             "u64": 8, "c64": 8, "c128": 16}

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "collective-permute", "all-to-all")


def _shape_bytes(text, reduce="sum"):
    """Bytes of the `dtype[d0,d1,...]` groups in `text`.

    Async `-start` tuples print the aliased operand group(s) alongside the
    result group(s), so per-op conventions recover the payload:
    - 'half_sum' (all-reduce / permute / all-to-all: operand size == result
      size, possibly VARIADIC combined): sum/2 — a max would undercount the
      combined case.
    - 'max' (all-gather / reduce-scatter: operand and result sizes differ):
      the larger group is the full participating buffer, i.e. the payload.
    """
    sizes = []
    for dt, dims in re.findall(r"(\w+)\[([0-9,]*)\]", text):
        if dt not in _DT_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        sizes.append(n * _DT_BYTES[dt])
    if not sizes:
        return 0
    if reduce == "half_sum":
        return sizes[0] if len(sizes) == 1 else sum(sizes) // 2
    if reduce == "max":
        return max(sizes)
    return sum(sizes)


def collective_census(compiled):
    """{op: {"count": n, "bytes": per-device output bytes}} + est_flops.

    `compiled` is a jax Compiled (jitted.lower(*args).compile()).  Bytes are
    the collectives' OUTPUT payloads summed over the program — the per-step,
    per-device traffic the interconnect must carry (a while-loop body is
    counted once; multiply by trip count externally if needed).
    """
    txt = compiled.as_text()
    out = {op: {"count": 0, "bytes": 0} for op in _COLLECTIVES}
    for line in txt.splitlines():
        for op in _COLLECTIVES:
            # match the sync opcode OR the async -start form (XLA's default
            # on TPU); -done carries the same payload and is skipped so each
            # collective is counted once
            m = re.search(rf"=\s*(.*?)\s{re.escape(op)}(-start)?\(", line)
            if m and f"{op}-done" not in line:
                out[op]["count"] += 1
                if m.group(2):  # async form: tuple aliases operands
                    red = ("max" if op in ("all-gather", "reduce-scatter")
                           else "half_sum")
                else:
                    red = "sum"
                out[op]["bytes"] += _shape_bytes(m.group(1), reduce=red)
                break
    flops = None
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        flops = float(ca.get("flops", 0.0))
    except Exception:
        pass
    return {
        "bytes_allreduce": out["all-reduce"]["bytes"],
        "bytes_allgather": out["all-gather"]["bytes"],
        "bytes_reducescatter": out["reduce-scatter"]["bytes"],
        "bytes_ppermute": out["collective-permute"]["bytes"],
        "bytes_alltoall": out["all-to-all"]["bytes"],
        "counts": {op: v["count"] for op, v in out.items()},
        "est_step_flops": flops,
    }


# ------------------------------------------------------------- per-op census
_NAME_RE = re.compile(r"^\s*(?:ROOT\s+)?%?([^\s=]+)\s*=")
# the opcode is the bare word between the result type (which ends in ']',
# '}' or ')') and its '(' argument list
_OPCODE_RE = re.compile(r"[\])}]\s+([a-z][a-z0-9\-]*)\(")
_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}")

#: Bookkeeping opcodes that carry no compute and clutter attribution.
_TRIVIAL_OPCODES = frozenset({
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "partition-id", "after-all",
})


def _entry_lines(txt):
    """Lines of the ENTRY computation only.  The body ends at the first
    closing ``}`` on its own line — nested braces inside the body occur
    only in same-line attributes (layouts ``{1,0}``, sharding specs), never
    as standalone lines."""
    out, in_entry = [], False
    for line in txt.splitlines():
        if not in_entry:
            if line.lstrip().startswith("ENTRY "):
                in_entry = True
            continue
        if line.strip() == "}":
            break
        out.append(line)
    return out


def _dims(group_text):
    """First `dtype[d0,d1,...]` group in ``group_text`` -> list of dims."""
    m = re.search(r"(\w+)\[([0-9,]*)\]", group_text)
    if not m or m.group(1) not in _DT_BYTES:
        return None
    return [int(d) for d in m.group(2).split(",") if d]


def per_op_census(compiled, include_trivial=False):
    """Per-op cost table of a compiled program: ``[{name, opcode,
    bytes_out, bytes_in, flops}]`` in program order.

    ``compiled`` is a jax Compiled (``jitted.lower(*args).compile()``).
    Bytes come from the printed operand/result shapes; ``flops`` is an
    analytic 2*M*N*K estimate for ``dot`` ops (contracting dims read off
    the HLO attributes) and 0 elsewhere — enough to RANK ops for the
    census<->timeline attribution join (`tools/trace_report.py`), not a
    replacement for the backend cost model.

    Only the ENTRY computation is scanned: fused-computation bodies repeat
    the fusion's internal ops, which would double-count the fusion row's
    bytes and pad the table with names no timeline event carries.
    """
    ops = []
    for line in _entry_lines(compiled.as_text()):
        nm = _NAME_RE.match(line)
        if nm is None:
            continue
        m = _OPCODE_RE.search(line)
        if m is None:
            continue
        opcode = m.group(1)
        if opcode in _TRIVIAL_OPCODES and not include_trivial:
            continue
        result_txt = line[nm.end():m.start() + 1]
        operand_txt = line[m.end():]
        flops = 0
        if opcode == "dot":
            out_dims = _dims(result_txt)
            lhs_dims = _dims(operand_txt)
            cm = _CONTRACT_RE.search(line)
            if out_dims is not None and lhs_dims is not None and cm:
                k = 1
                for i in (int(d) for d in cm.group(1).split(",") if d):
                    if i < len(lhs_dims):
                        k *= lhs_dims[i]
                n = 1
                for d in out_dims:
                    n *= d
                flops = 2 * n * k
        ops.append({
            "name": nm.group(1),
            "opcode": opcode,
            "bytes_out": _shape_bytes(result_txt, reduce="sum"),
            "bytes_in": _shape_bytes(operand_txt, reduce="sum"),
            "flops": flops,
        })
    return ops
