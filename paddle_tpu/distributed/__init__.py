"""paddle.distributed parity surface (ref: python/paddle/distributed/__init__.py).

See SURVEY.md §2.4/§5.8 for the inventory this implements: env bootstrap, collectives
("ProcessGroupXLA" = mesh-axis metadata + lax collectives), topology Mesh,
DataParallel, fleet facade, meta_parallel TP/PP layers, sharded train steps (ZeRO),
MoE alltoall, launch CLI.
"""
from .env import (  # noqa: F401
    init_parallel_env, get_rank, get_world_size, is_initialized, ParallelEnv,
)
from .collective import (  # noqa: F401
    ReduceOp, Group, new_group, get_group, all_reduce, all_gather, all_gather_object,
    broadcast, broadcast_object_list, reduce, reduce_scatter, scatter, alltoall,
    all_to_all, send, recv, isend, irecv, barrier, wait, destroy_process_group,
)
from .parallel import DataParallel  # noqa: F401
from .topology import (  # noqa: F401
    CommunicateTopology, HybridCommunicateGroup, build_mesh,
    get_hybrid_communicate_group, set_hybrid_communicate_group,
)
from .sharded_train_step import ShardedTrainStep  # noqa: F401
from .sharding_ctx import mesh_scope, constraint, annotate  # noqa: F401
from . import fleet  # noqa: F401
from . import sharding  # noqa: F401
from . import auto_parallel  # noqa: F401
from .auto_parallel import ProcessMesh, shard_tensor, shard_op  # noqa: F401
from .store import Store, TCPStore  # noqa: F401
from . import checkpoint  # noqa: F401
from . import fault_tolerance  # noqa: F401
from .fault_tolerance import Preemption, run_with_recovery  # noqa: F401
from . import fleet_executor  # noqa: F401
from . import launch  # noqa: F401
from . import utils  # noqa: F401
from .parallel_mode import (  # noqa: F401
    CountFilterEntry,
    InMemoryDataset,
    ParallelMode,
    ProbabilityEntry,
    QueueDataset,
    ShowClickEntry,
    gloo_barrier,
    gloo_init_parallel_env,
    gloo_release,
    split,
)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Ref: distributed/spawn.py.  Single-host TPU: SPMD over the local mesh makes
    process-spawning unnecessary; run func once in-process for parity."""
    import multiprocessing as mp
    import os

    if nprocs in (-1, 0, 1):
        func(*args)
        return
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        env = dict(os.environ, PADDLE_TRAINER_ID=str(rank), PADDLE_TRAINERS_NUM=str(nprocs))

        def target(r=rank, e=env):
            os.environ.update(e)
            func(*args)

        p = ctx.Process(target=target, daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
