"""LocalSGD and DGC — the two reference meta-optimizers that deliberately
break lockstep data parallelism (ref fleet/meta_optimizers/localsgd_optimizer.py,
dgc_optimizer.py + paddle/fluid/operators/dgc_op.*).

The reference implements both as Program rewrites around NCCL ops.  The
TPU-native design expresses them as ONE jitted shard_map step over the 'dp'
mesh axis, because both need *per-worker* state that plain GSPMD data
parallelism (which keeps replicas bit-identical) cannot represent:

- LocalSGD: each dp shard holds its OWN copy of params + optimizer state
  (stacked on a leading dp-sharded axis), runs k local updates, and every
  k-th step averages params across the axis with lax.pmean inside lax.cond —
  the collective only executes on sync ticks.
- DGC: params stay replicated, but the momentum-corrected velocity `u` and
  the unsent residual `e` are per-worker (stacked, dp-sharded).  Each step:
  u = m*u + g;  e += u;  send the top-(1-sparsity) fraction of |e| via psum;
  clear sent coordinates from u and e (momentum-factor masking).  With
  sparsity=0 every coordinate is sent each step and the schedule reduces to
  dense synchronous SGD — the parity oracle the tests use.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...autograd import tape
from ...framework import random as _random
from ...tensor.tensor import Tensor

__all__ = ["LocalSGDTrainStep", "DGCTrainStep"]


def _make_forward(model, loss_fn):
    """(all_params, buffers, key, batch) -> (loss_f32, (new_buffers, aux))."""

    def forward_loss(allp, buffers, key, batch):
        with _random.rng_key_scope(key):
            restore = model.bind_functional_state(allp, buffers)
            try:
                with tape.no_grad():
                    args = tuple(Tensor(b, stop_gradient=True) for b in batch)
                    out = loss_fn(*args)
                loss_t = out[0] if isinstance(out, (tuple, list)) else out
                new_buffers = {k: b._value for k, b in model.named_buffers()}
            finally:
                restore()
        return loss_t._value.astype(jnp.float32), new_buffers

    return forward_loss


def _named_state(step_obj):
    named = dict(step_obj.model.named_parameters())
    trainable = {k for k, p in named.items() if not p.stop_gradient}
    return named, trainable


class LocalSGDTrainStep:
    """k local optimizer steps per worker, then a param average over `axis`.

    Ref: fleet/meta_optimizers/localsgd_optimizer.py (k_steps program rewrite).
    Between sync ticks the model object holds worker-0's view; `sync_params()`
    (also called automatically on every k-th step) writes the cross-worker
    average back into the model.
    """

    def __init__(self, model, loss_fn, optimizer, mesh, k_steps=4, axis="dp",
                 batch_spec=None):
        if axis not in mesh.axis_names or mesh.shape[axis] < 2:
            raise ValueError(f"LocalSGD needs a >=2-way mesh axis {axis!r}; "
                             f"mesh has {dict(mesh.shape)}")
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh
        self.axis = axis
        self.k_steps = max(1, int(k_steps))
        self.n = int(mesh.shape[axis])
        self.batch_spec = batch_spec if batch_spec is not None else P(axis)
        self._jitted = None
        self._step = 0

    # ------------------------------------------------------------------ setup
    def _init(self):
        model, opt, mesh, axis, n = self.model, self.optimizer, self.mesh, self.axis, self.n
        named, trainable = _named_state(self)
        self._named, self._trainable = named, trainable
        stk_sh = NamedSharding(mesh, P(axis))
        rep = NamedSharding(mesh, P())

        def stack(v):
            return jax.device_put(jnp.broadcast_to(v, (n,) + tuple(v.shape)), stk_sh)

        self._pstk = {k: stack(named[k]._value) for k in trainable}
        self._frozen = {k: jax.device_put(named[k]._value, rep)
                        for k in named if k not in trainable}
        self._ostk = {k: jax.tree.map(stack, opt._init_state(named[k]))
                      for k in trainable}
        forward = _make_forward(model, self.loss_fn)
        k_steps = self.k_steps

        def body(pstk, frozen, buffers, ostk, lr, key, step, *batch):
            local_p = jax.tree.map(lambda v: v[0], pstk)
            local_o = jax.tree.map(lambda v: v[0], ostk)
            key = jax.random.fold_in(key, jax.lax.axis_index(axis))

            def pure_loss(tp, bufs, kk, mb):
                loss, nb = forward({**tp, **frozen}, bufs, kk, mb)
                return loss, nb

            (loss, new_buffers), grads = jax.value_and_grad(
                pure_loss, has_aux=True)(local_p, buffers, key, batch)

            clipped = opt._clipped_grads(list(grads.items()))
            new_p, new_o = {}, {}
            for k, g in clipped:
                new_p[k], new_o[k] = opt._apply_update(
                    local_p[k], g, local_o[k], lr, opt._param_decay_coeff(named[k]))

            do_sync = ((step + 1) % k_steps) == 0
            new_p = jax.lax.cond(
                do_sync,
                lambda p: jax.tree.map(lambda v: jax.lax.pmean(v, axis), p),
                lambda p: p,
                new_p)
            new_buffers = jax.tree.map(lambda v: jax.lax.pmean(v, axis), new_buffers)
            loss = jax.lax.pmean(loss, axis)
            return (jax.tree.map(lambda v: v[None], new_p), new_buffers,
                    jax.tree.map(lambda v: v[None], new_o), loss)

        spec_stk = P(axis)
        spec_rep = P()
        in_specs = (spec_stk, spec_rep, spec_rep, spec_stk, spec_rep, spec_rep,
                    spec_rep) + tuple(self.batch_spec for _ in range(self._n_batch))
        out_specs = (spec_stk, spec_rep, spec_stk, spec_rep)
        self._jitted = jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False))

    def __call__(self, *batch):
        raw = tuple(b._value if isinstance(b, Tensor) else jnp.asarray(b) for b in batch)
        if self._jitted is None:
            self._n_batch = len(raw)
            self._init()
        _, buffers = self.model.functional_state()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        key = _random.get_rng_key()
        step = jnp.asarray(self._step, jnp.int32)
        self._pstk, new_buffers, self._ostk, loss = self._jitted(
            self._pstk, self._frozen, buffers, self._ostk, lr, key, step, *raw)
        self._step += 1
        self.optimizer._step_count += 1
        for k, b in self.model.named_buffers():
            b._rebind(new_buffers[k])
        if self._step % self.k_steps == 0:
            self._write_back()
        return Tensor(loss)

    def _write_back(self):
        """Load worker-0's row into the model (rows are equal right after a
        sync tick)."""
        for k in self._trainable:
            self._named[k]._rebind(self._pstk[k][0])

    def sync_params(self):
        """Force a cross-worker average now (e.g. before eval mid-interval)."""
        if self._jitted is None:
            return
        self._pstk = {k: jax.tree.map(
            lambda v: jnp.broadcast_to(jnp.mean(v, axis=0), v.shape), v)
            for k, v in self._pstk.items()}
        self._write_back()


class DGCTrainStep:
    """Deep Gradient Compression data parallelism (ref dgc_optimizer.py).

    Per worker and per parameter: velocity u (momentum correction) and
    residual e (unsent gradient mass).  Each step sends only the
    top-(1-sparsity) fraction of |e| (per tensor) through the psum; sent
    coordinates are cleared from u and e.  Steps before `rampup_begin_step`
    sync densely.  Pair with SGD — DGC's velocity IS the momentum.
    """

    def __init__(self, model, loss_fn, optimizer, mesh, sparsity=0.999,
                 momentum=0.9, rampup_begin_step=0, axis="dp", batch_spec=None):
        if axis not in mesh.axis_names or mesh.shape[axis] < 2:
            raise ValueError(f"DGC needs a >=2-way mesh axis {axis!r}; "
                             f"mesh has {dict(mesh.shape)}")
        if not 0.0 <= sparsity < 1.0:
            raise ValueError(f"sparsity must be in [0, 1), got {sparsity}")
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh
        self.axis = axis
        self.sparsity = float(sparsity)
        self.momentum = float(momentum)
        self.rampup_begin_step = int(rampup_begin_step)
        self.n = int(mesh.shape[axis])
        self.batch_spec = batch_spec if batch_spec is not None else P(axis)
        self._jitted = None
        self._step = 0

    def _init(self):
        model, opt, mesh, axis, n = self.model, self.optimizer, self.mesh, self.axis, self.n
        named, trainable = _named_state(self)
        self._named, self._trainable = named, trainable
        stk_sh = NamedSharding(mesh, P(axis))
        rep = NamedSharding(mesh, P())

        def zstack(v):
            return jax.device_put(jnp.zeros((n,) + tuple(v.shape), v.dtype), stk_sh)

        self._u = {k: zstack(named[k]._value) for k in trainable}
        self._e = {k: zstack(named[k]._value) for k in trainable}
        self._opt_state = {k: jax.tree.map(lambda v: jax.device_put(v, rep),
                                           opt._init_state(named[k]))
                           for k in trainable}
        forward = _make_forward(model, self.loss_fn)
        m_coef, sparsity, rampup = self.momentum, self.sparsity, self.rampup_begin_step

        def body(params, frozen, buffers, u_stk, e_stk, opt_state, lr, key, step, *batch):
            key = jax.random.fold_in(key, jax.lax.axis_index(axis))

            def pure_loss(tp, bufs, kk, mb):
                loss, nb = forward({**tp, **frozen}, bufs, kk, mb)
                return loss, nb

            (loss, new_buffers), grads = jax.value_and_grad(
                pure_loss, has_aux=True)(params, buffers, key, batch)

            sparse_on = step >= rampup
            synced, new_u, new_e = {}, {}, {}
            for k, g in grads.items():
                u = u_stk[k][0]
                e = e_stk[k][0]
                g = g.astype(u.dtype)
                u2 = m_coef * u + g
                e2 = e + u2
                flat = jnp.abs(e2.astype(jnp.float32)).reshape(-1)
                keep = max(1, int(math.ceil(flat.shape[0] * (1.0 - sparsity))))
                if keep >= flat.shape[0]:
                    mask = jnp.ones_like(e2, jnp.float32)
                else:
                    thr = jax.lax.top_k(flat, keep)[0][-1]
                    mask = (jnp.abs(e2.astype(jnp.float32)) >= thr).astype(jnp.float32)
                mask = jnp.where(sparse_on, mask, jnp.ones_like(mask))
                send = e2 * mask.astype(e2.dtype)
                synced[k] = jax.lax.pmean(send, axis)
                inv = (1.0 - mask).astype(e2.dtype)
                new_e[k] = (e2 * inv)[None]
                new_u[k] = (u2 * inv)[None]

            clipped = opt._clipped_grads(list(synced.items()))
            new_params = dict(frozen)
            new_opt = {}
            for k, g in clipped:
                new_params[k], new_opt[k] = opt._apply_update(
                    params[k], g, opt_state[k], lr, opt._param_decay_coeff(named[k]))

            new_buffers = jax.tree.map(lambda v: jax.lax.pmean(v, axis), new_buffers)
            loss = jax.lax.pmean(loss, axis)
            return new_params, new_buffers, new_u, new_e, new_opt, loss

        spec_stk = P(axis)
        spec_rep = P()
        in_specs = (spec_rep, spec_rep, spec_rep, spec_stk, spec_stk, spec_rep,
                    spec_rep, spec_rep, spec_rep) \
            + tuple(self.batch_spec for _ in range(self._n_batch))
        out_specs = (spec_rep, spec_rep, spec_stk, spec_stk, spec_rep, spec_rep)
        self._jitted = jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False))

    def __call__(self, *batch):
        raw = tuple(b._value if isinstance(b, Tensor) else jnp.asarray(b) for b in batch)
        if self._jitted is None:
            self._n_batch = len(raw)
            self._init()
        params = {k: self._named[k]._value for k in self._trainable}
        frozen = {k: self._named[k]._value for k in self._named
                  if k not in self._trainable}
        _, buffers = self.model.functional_state()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        key = _random.get_rng_key()
        step = jnp.asarray(self._step, jnp.int32)
        new_params, new_buffers, self._u, self._e, self._opt_state, loss = \
            self._jitted(params, frozen, buffers, self._u, self._e,
                         self._opt_state, lr, key, step, *raw)
        self._step += 1
        self.optimizer._step_count += 1
        for k in self._trainable:
            self._named[k]._rebind(new_params[k])
        for k, b in self.model.named_buffers():
            b._rebind(new_buffers[k])
        return Tensor(loss)
