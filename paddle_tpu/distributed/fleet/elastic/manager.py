"""ElasticManager (ref: fleet/elastic/manager.py:131).

The reference registers each node under an etcd prefix with a TTL heartbeat
(manager.py:217-239); a watcher detects scale-in/out, rewrites
PADDLE_TRAINER_ENDPOINTS and relaunches local trainers.

TPU-native redesign: TPU pods don't rebuild NCCL communicators — recovery is
checkpoint-restore (SURVEY.md §7.3 item 8).  Membership lives in the control-plane KV
store (distributed.store.TCPStore or any dict-like store for tests); each node
heartbeats `{prefix}/nodes/{host}` with a timestamp; the watcher thread flags nodes
whose heartbeat is older than 3 intervals (scale-in: a preempted host) or new keys
(scale-out).  On membership change the manager calls the registered callback —
typically "save checkpoint and re-exec under the new world size" — instead of
hot-patching communicators.
"""
from __future__ import annotations

import os
import threading
import time


ELASTIC_EXIT_CODE = 101
ELASTIC_AUTO_PARALLEL_EXIT_CODE = 102


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class _DictStore:
    """In-memory store for tests (reference precedent: mocked etcd in
    test_fleet_elastic_manager.py)."""

    def __init__(self):
        self._d = {}
        self._lock = threading.Lock()

    def set(self, k, v):
        with self._lock:
            self._d[k] = v if isinstance(v, bytes) else str(v).encode()

    def get(self, k):
        with self._lock:
            return self._d.get(k)

    def add(self, k, amount: int) -> int:
        with self._lock:
            cur = int(self._d.get(k, b"0").decode()) + int(amount)
            self._d[k] = str(cur).encode()
            return cur

    def delete_key(self, k):
        with self._lock:
            self._d.pop(k, None)

    def keys_with_prefix(self, prefix):
        with self._lock:
            return [k for k in self._d if k.startswith(prefix)]


class ElasticManager:
    """Membership + heartbeat + scale detection.

    `np` may be "N" or "MIN:MAX" (ref manager.py parses PADDLE_ELASTIC_NP the same
    way).  `on_change(event, hosts)` fires with event in {"scale_in", "scale_out"}.

    Alerting plane (ISSUE 7): `alert_policy` (an
    `observability.alerts.AlertPolicy`) lets scraped telemetry drive the
    manager's decisions — `poll_alerts()` runs sense->decide->act and maps
    the policy's decisions onto the manager: `restart` marks a pending
    restart (`check()` then returns `ElasticStatus.RESTART` until
    `consume_restart()`), `quarantine` removes the named host from
    membership (the alert instance's `host`/`target` label names it), and
    `widen_deadline` grants `wait_for_np` extra slack — a fleet that is
    slow because it is restarting should not be declared dead by its own
    supervisor.
    """

    def __init__(self, store=None, job_id=None, np=None, host=None,
                 heartbeat_interval=1.0, on_change=None, alert_policy=None,
                 max_wait_slack=300.0, target_to_host=None):
        self.store = store if store is not None else _DictStore()
        self.job_id = job_id or os.environ.get("PADDLE_JOB_ID", "default")
        np = str(np or os.environ.get("PADDLE_ELASTIC_NP", "1"))
        self.min_np = int(np.split(":")[0])
        self.max_np = int(np.split(":")[-1])
        self.host = host or os.environ.get("PADDLE_CURRENT_ENDPOINT",
                                           f"127.0.0.1:{os.getpid()}")
        self.interval = heartbeat_interval
        self.on_change = on_change
        self.prefix = f"/paddle_tpu/elastic/{self.job_id}"
        self.enabled = self.max_np > self.min_np or os.environ.get(
            "PADDLE_ELASTIC_ENABLE", "0") in ("1", "true", "True")
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._known_hosts: set[str] = set()
        self.alert_policy = alert_policy
        self._quarantined: set[str] = set()
        self._wait_slack = 0.0
        self.max_wait_slack = float(max_wait_slack)
        self._pending_restart = None  # AlertDecision awaiting consume
        # scrape-target name (host:metrics_port) -> membership host name;
        # the metrics port is rarely the trainer endpoint, so a quarantine
        # decision needs this mapping to land on the right heartbeat key
        self.target_to_host = dict(target_to_host or {})

    # ------------------------------------------------------------- membership
    def _node_key(self, host=None):
        return f"{self.prefix}/nodes/{host or self.host}"

    def register(self):
        """Ref manager.py:217 — register + start heartbeat + watcher."""
        self.store.set(self._node_key(), str(time.time()))
        self._known_hosts = set(self.hosts())
        t_hb = threading.Thread(target=self._heartbeat_loop, daemon=True)
        t_w = threading.Thread(target=self._watch_loop, daemon=True)
        self._threads = [t_hb, t_w]
        for t in self._threads:
            t.start()

    def _heartbeat_loop(self):
        while not self._stop.wait(self.interval):
            self.store.set(self._node_key(), str(time.time()))

    def hosts(self) -> list[str]:
        pre = f"{self.prefix}/nodes/"
        out = []
        now = time.time()
        # use the store's non-blocking get where available: a node deregistering
        # between the prefix scan and the read must not stall the watcher on a
        # blocking-G wait (TCPStore.get blocks until the key exists)
        getter = getattr(self.store, "get_nb", None) or self.store.get
        for k in self.store.keys_with_prefix(pre):
            try:
                v = getter(k)
            except Exception:
                continue
            if v is None:
                continue
            ts = float(v.decode() if isinstance(v, bytes) else v)
            name = k[len(pre):]
            if now - ts <= 3 * self.interval and name not in self._quarantined:
                out.append(name)
        return sorted(out)

    def _watch_loop(self):
        while not self._stop.wait(self.interval):
            live = set(self.hosts())
            gone = self._known_hosts - live
            new = live - self._known_hosts
            if gone or new:
                self._known_hosts = live
                if self.on_change is not None:
                    if gone:
                        self.on_change("scale_in", sorted(live))
                    if new:
                        self.on_change("scale_out", sorted(live))

    # ------------------------------------------------------------- decisions
    def check(self) -> str:
        """Map current membership to an action (ref manager.py exit/restart
        logic).  A telemetry-driven restart decision (`poll_alerts`)
        dominates membership: the ranks may all be heartbeating while one
        of them is wedged — exactly the failure mode heartbeats cannot
        see and scraped healthchecks can."""
        if self._pending_restart is not None:
            return ElasticStatus.RESTART
        n = len(self.hosts())
        if n >= self.min_np:
            return ElasticStatus.COMPLETED if n <= self.max_np else ElasticStatus.ERROR
        return ElasticStatus.HOLD  # wait for nodes to (re)join

    def wait_for_np(self, timeout=60) -> bool:
        # local wait window: monotonic (the heartbeat VALUES stay wall-clock —
        # they are compared across hosts, which share NTP, not a boot clock).
        # widen_wait() slack (a widen_deadline alert action) extends it.
        deadline = time.monotonic() + timeout + self._wait_slack
        while time.monotonic() < deadline:
            if self.min_np <= len(self.hosts()) <= self.max_np:
                return True
            time.sleep(self.interval / 2)
        return False

    # ------------------------------------------------- telemetry-driven act
    def quarantine(self, host):
        """Exclude ``host`` from membership until ``unquarantine`` — the
        actuation for a node whose telemetry says it is lying about being
        alive (heartbeats fresh, healthchecks failing)."""
        self._quarantined.add(str(host))

    def unquarantine(self, host):
        self._quarantined.discard(str(host))

    @property
    def quarantined(self):
        return sorted(self._quarantined)

    def widen_wait(self, extra_s):
        """Grant ``wait_for_np`` additional slack — cumulative but capped
        at ``max_wait_slack``: a flapping widen_deadline alert (each
        re-fire is a fresh episode past the policy's per-episode gate) must
        not grow the deadline until the supervisor can never declare a
        dead fleet."""
        self._wait_slack = min(self._wait_slack + float(extra_s),
                               self.max_wait_slack)

    def consume_restart(self):
        """Pop the pending restart decision (``check()`` stops returning
        RESTART).  Returns the AlertDecision, or None."""
        d, self._pending_restart = self._pending_restart, None
        return d

    def poll_alerts(self, samples=None, now=None, widen_step_s=None):
        """One sense->decide->act turn of the attached ``alert_policy``.

        Maps decisions onto the manager: ``restart`` arms ``check()``,
        ``quarantine`` quarantines the host named by the alert instance's
        ``host`` label — or its ``target`` label routed through
        ``target_to_host`` (a scrape-target name is host:METRICS_port, not
        the trainer endpoint membership is keyed by) — ``widen_deadline``
        adds ``widen_step_s`` (default: one full heartbeat-timeout window,
        ``3 * interval``) of ``wait_for_np`` slack.  A quarantine that
        names no current membership entry still registers (it excludes a
        future join) but leaves a ``quarantine_unknown_host`` flight event
        so a mis-mapped actuation is never silent.  Returns the decisions.
        """
        if self.alert_policy is None:
            return []
        decisions = self.alert_policy.poll(samples=samples, now=now)
        for d in decisions:
            if d.action == "restart":
                self._pending_restart = d
            elif d.action == "quarantine":
                target = d.labels.get("target")
                host = d.labels.get("host") \
                    or self.target_to_host.get(target, target)
                if host:
                    known = {k[len(f"{self.prefix}/nodes/"):] for k in
                             self.store.keys_with_prefix(
                                 f"{self.prefix}/nodes/")}
                    if host not in known:
                        from ....observability import flight_recorder
                        flight_recorder.record_event(
                            "quarantine_unknown_host", host=host,
                            alert=d.alert, known=sorted(known))
                    self.quarantine(host)
            elif d.action == "widen_deadline":
                self.widen_wait(widen_step_s if widen_step_s is not None
                                else 3 * self.interval)
        return decisions

    def run(self, step_fn, num_steps, manager, get_state, set_state, *,
            check_every=1, samples_fn=None, widen_step_s=None,
            **recovery_kwargs):
        """Run a training loop with THIS manager as the restart authority
        — ``run_with_recovery`` is the restart body (the PR-1 leftover).

        After every ``check_every``-th completed step the alert plane is
        polled (``poll_alerts(samples_fn())``) and ``check()`` consulted;
        a pending telemetry-driven restart (``check()==RESTART``) is
        consumed and raised as ``AlertRestart``, which
        ``run_with_recovery`` heals by restoring the NEWEST valid
        checkpoint from ``manager`` and replaying from there — the
        telemetry-driven restart replays instead of diverging.
        ``recovery_kwargs`` pass through (max_restarts, on_event,
        telemetry_port, ...).  Returns run_with_recovery's summary dict.
        """
        from ...fault_tolerance import (AlertRestart, Preemption,
                                        run_with_recovery)

        every = max(1, int(check_every))

        def wrapped(step):
            step_fn(step)
            if (step + 1) % every:
                return
            if self.alert_policy is not None:
                samples = samples_fn() if samples_fn is not None else None
                self.poll_alerts(samples=samples,
                                 widen_step_s=widen_step_s)
            if self.check() == ElasticStatus.RESTART:
                d = self.consume_restart()
                if d is not None:
                    raise AlertRestart(d)
                raise Preemption("elastic manager requested restart")

        return run_with_recovery(wrapped, num_steps, manager, get_state,
                                 set_state, **recovery_kwargs)

    def exit(self, completed=True):
        self._stop.set()
        self.store.delete_key(self._node_key())
        for t in self._threads:
            t.join(timeout=2 * self.interval)


def enable_elastic(args=None, etcd_client=None) -> bool:
    np = str(getattr(args, "np", None) or os.environ.get("PADDLE_ELASTIC_NP", "1"))
    return ":" in np or os.environ.get("PADDLE_ELASTIC_ENABLE", "0") in ("1", "true")


def launch_elastic(args, store=None):
    """Ref elastic/__init__.py:48 — run the launcher under elastic supervision."""
    from ...launch.main import CollectiveController

    mgr = ElasticManager(store=store, np=getattr(args, "nnodes", "1"))
    mgr.register()
    ctl = CollectiveController(args)
    ctl.start()
    try:
        return ctl.watch()
    finally:
        mgr.exit()
