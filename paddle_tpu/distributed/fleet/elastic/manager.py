"""ElasticManager (ref: fleet/elastic/manager.py:131).

The reference registers each node under an etcd prefix with a TTL heartbeat
(manager.py:217-239); a watcher detects scale-in/out, rewrites
PADDLE_TRAINER_ENDPOINTS and relaunches local trainers.

TPU-native redesign: TPU pods don't rebuild NCCL communicators — recovery is
checkpoint-restore (SURVEY.md §7.3 item 8).  Membership lives in the control-plane KV
store (distributed.store.TCPStore or any dict-like store for tests); each node
heartbeats `{prefix}/nodes/{host}` with a timestamp; the watcher thread flags nodes
whose heartbeat is older than 3 intervals (scale-in: a preempted host) or new keys
(scale-out).  On membership change the manager calls the registered callback —
typically "save checkpoint and re-exec under the new world size" — instead of
hot-patching communicators.
"""
from __future__ import annotations

import os
import threading
import time


ELASTIC_EXIT_CODE = 101
ELASTIC_AUTO_PARALLEL_EXIT_CODE = 102


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class _DictStore:
    """In-memory store for tests (reference precedent: mocked etcd in
    test_fleet_elastic_manager.py)."""

    def __init__(self):
        self._d = {}
        self._lock = threading.Lock()

    def set(self, k, v):
        with self._lock:
            self._d[k] = v if isinstance(v, bytes) else str(v).encode()

    def get(self, k):
        with self._lock:
            return self._d.get(k)

    def add(self, k, amount: int) -> int:
        with self._lock:
            cur = int(self._d.get(k, b"0").decode()) + int(amount)
            self._d[k] = str(cur).encode()
            return cur

    def delete_key(self, k):
        with self._lock:
            self._d.pop(k, None)

    def keys_with_prefix(self, prefix):
        with self._lock:
            return [k for k in self._d if k.startswith(prefix)]


class ElasticManager:
    """Membership + heartbeat + scale detection.

    `np` may be "N" or "MIN:MAX" (ref manager.py parses PADDLE_ELASTIC_NP the same
    way).  `on_change(event, hosts)` fires with event in {"scale_in", "scale_out"}.
    """

    def __init__(self, store=None, job_id=None, np=None, host=None,
                 heartbeat_interval=1.0, on_change=None):
        self.store = store if store is not None else _DictStore()
        self.job_id = job_id or os.environ.get("PADDLE_JOB_ID", "default")
        np = str(np or os.environ.get("PADDLE_ELASTIC_NP", "1"))
        self.min_np = int(np.split(":")[0])
        self.max_np = int(np.split(":")[-1])
        self.host = host or os.environ.get("PADDLE_CURRENT_ENDPOINT",
                                           f"127.0.0.1:{os.getpid()}")
        self.interval = heartbeat_interval
        self.on_change = on_change
        self.prefix = f"/paddle_tpu/elastic/{self.job_id}"
        self.enabled = self.max_np > self.min_np or os.environ.get(
            "PADDLE_ELASTIC_ENABLE", "0") in ("1", "true", "True")
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._known_hosts: set[str] = set()

    # ------------------------------------------------------------- membership
    def _node_key(self, host=None):
        return f"{self.prefix}/nodes/{host or self.host}"

    def register(self):
        """Ref manager.py:217 — register + start heartbeat + watcher."""
        self.store.set(self._node_key(), str(time.time()))
        self._known_hosts = set(self.hosts())
        t_hb = threading.Thread(target=self._heartbeat_loop, daemon=True)
        t_w = threading.Thread(target=self._watch_loop, daemon=True)
        self._threads = [t_hb, t_w]
        for t in self._threads:
            t.start()

    def _heartbeat_loop(self):
        while not self._stop.wait(self.interval):
            self.store.set(self._node_key(), str(time.time()))

    def hosts(self) -> list[str]:
        pre = f"{self.prefix}/nodes/"
        out = []
        now = time.time()
        # use the store's non-blocking get where available: a node deregistering
        # between the prefix scan and the read must not stall the watcher on a
        # blocking-G wait (TCPStore.get blocks until the key exists)
        getter = getattr(self.store, "get_nb", None) or self.store.get
        for k in self.store.keys_with_prefix(pre):
            try:
                v = getter(k)
            except Exception:
                continue
            if v is None:
                continue
            ts = float(v.decode() if isinstance(v, bytes) else v)
            if now - ts <= 3 * self.interval:
                out.append(k[len(pre):])
        return sorted(out)

    def _watch_loop(self):
        while not self._stop.wait(self.interval):
            live = set(self.hosts())
            gone = self._known_hosts - live
            new = live - self._known_hosts
            if gone or new:
                self._known_hosts = live
                if self.on_change is not None:
                    if gone:
                        self.on_change("scale_in", sorted(live))
                    if new:
                        self.on_change("scale_out", sorted(live))

    # ------------------------------------------------------------- decisions
    def check(self) -> str:
        """Map current membership to an action (ref manager.py exit/restart logic)."""
        n = len(self.hosts())
        if n >= self.min_np:
            return ElasticStatus.COMPLETED if n <= self.max_np else ElasticStatus.ERROR
        return ElasticStatus.HOLD  # wait for nodes to (re)join

    def wait_for_np(self, timeout=60) -> bool:
        # local wait window: monotonic (the heartbeat VALUES stay wall-clock —
        # they are compared across hosts, which share NTP, not a boot clock)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.min_np <= len(self.hosts()) <= self.max_np:
                return True
            time.sleep(self.interval / 2)
        return False

    def exit(self, completed=True):
        self._stop.set()
        self.store.delete_key(self._node_key())
        for t in self._threads:
            t.join(timeout=2 * self.interval)


def enable_elastic(args=None, etcd_client=None) -> bool:
    np = str(getattr(args, "np", None) or os.environ.get("PADDLE_ELASTIC_NP", "1"))
    return ":" in np or os.environ.get("PADDLE_ELASTIC_ENABLE", "0") in ("1", "true")


def launch_elastic(args, store=None):
    """Ref elastic/__init__.py:48 — run the launcher under elastic supervision."""
    from ...launch.main import CollectiveController

    mgr = ElasticManager(store=store, np=getattr(args, "nnodes", "1"))
    mgr.register()
    ctl = CollectiveController(args)
    ctl.start()
    try:
        return ctl.watch()
    finally:
        mgr.exit()
