"""fleet facade (ref: python/paddle/distributed/fleet/base/fleet_base.py:144,211,890,947
and DistributedStrategy fleet/base/distributed_strategy.py:110 over
framework/distributed_strategy.proto's 28 messages).

fleet.init builds the HybridCommunicateGroup Mesh from strategy.hybrid_configs;
distributed_model/distributed_optimizer return wrappers whose compiled path is
ShardedTrainStep (dp/mp/sharding via NamedSharding, pp via the compiled pipeline).
"""
from __future__ import annotations

from ..topology import (
    CommunicateTopology,
    HybridCommunicateGroup,
    get_hybrid_communicate_group,
    set_hybrid_communicate_group,
)
from ..env import init_parallel_env, get_rank, get_world_size
from ..parallel import DataParallel
from .. import collective as _collective
from ...optimizer.optimizer import Optimizer
from .. import meta_parallel  # noqa: F401
from . import utils  # noqa: F401
from . import elastic  # noqa: F401
from ..meta_parallel import mp_layers  # noqa: F401
from ..meta_parallel.mp_layers import (  # noqa: F401 (fleet.meta_parallel re-exports)
    VocabParallelEmbedding, ColumnParallelLinear, RowParallelLinear, ParallelCrossEntropy,
    get_rng_state_tracker,
)


class DistributedStrategy:
    """Ref distributed_strategy.py:110 over distributed_strategy.proto's 28
    messages — the single knob surface.

    Every config dict validates its keys (a typo'd knob raises instead of
    being silently dropped), and every *accepted* knob is either consumed by
    the compiled train step (`fleet.distributed_train_step` /
    `PipelineParallel`) or documented inert below:

    consumed: amp{level, init_loss_scaling, incr_every_n_steps,
              decr_every_n_nan_or_inf, incr_ratio, decr_ratio},
              recompute{checkpoints}, sharding{stage/sharding_degree},
              gradient_merge{k_steps, avg}, pipeline{accumulate_steps,
              micro_batch_size}, hybrid_configs (mesh axes),
              gradient_scale_configs{scale_strategy}, tensor_parallel degree.
    inert on TPU (GPU/NCCL mechanics XLA owns; accepted for script parity):
              fuse_all_reduce_ops, fuse_grad_size_in_MB, nccl_comm_num,
              find_unused_parameters, heter_ccl_mode,
              without_graph_optimization.
    localsgd{k_steps} / dgc{rampup_begin_step, sparsity} select the
              shard_map meta-optimizer steps in meta_optimizers.py (per-worker
              param copies / compressed gradient sync over the dp axis).
    """

    _CONFIG_KEYS = {
        "amp_configs": {"init_loss_scaling", "incr_every_n_steps",
                        "decr_every_n_nan_or_inf", "incr_ratio", "decr_ratio",
                        "use_dynamic_loss_scaling", "custom_white_list",
                        "custom_black_list", "use_pure_fp16", "level",
                        "use_fp16_guard", "dtype"},
        "recompute_configs": {"checkpoints", "enable_offload",
                              "checkpoint_shape"},
        "sharding_configs": {"stage", "sharding_degree", "segment_broadcast_MB",
                             "mp_degree", "dp_degree", "offload",
                             "segment_anchors", "gradient_merge_acc_step",
                             "optimize_offload"},
        "pipeline_configs": {"accumulate_steps", "micro_batch_size",
                             "schedule_mode", "enable_partial_send_recv"},
        "tensor_parallel_configs": {"tensor_parallel_degree", "tensor_init_seed"},
        "gradient_merge_configs": {"k_steps", "avg"},
        "localsgd_configs": {"k_steps", "begin_step"},
        "dgc_configs": {"rampup_begin_step", "rampup_step", "sparsity"},
        "gradient_scale_configs": {"scale_strategy"},
        "hybrid_configs": {"dp_degree", "mp_degree", "pp_degree",
                           "sharding_degree", "sep_degree"},
    }

    def __init__(self):
        self.__dict__["_cfg"] = {
            "amp": False,
            "amp_configs": {},
            "recompute": False,
            "recompute_configs": {},
            "sharding": False,
            "sharding_configs": {},
            "pipeline": False,
            "pipeline_configs": {"accumulate_steps": 1, "micro_batch_size": 1},
            "tensor_parallel": False,
            "tensor_parallel_configs": {},
            "hybrid_configs": {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                               "sharding_degree": 1, "sep_degree": 1},
            "gradient_merge": False,
            "gradient_merge_configs": {"k_steps": 1, "avg": True},
            "lamb": False,
            "lars": False,
            "dgc": False,
            "dgc_configs": {"rampup_begin_step": 0, "sparsity": 0.999},
            "localsgd": False,
            "localsgd_configs": {"k_steps": 4, "begin_step": 1},
            "gradient_scale_configs": {"scale_strategy": "avg"},
            "find_unused_parameters": False,
            "fuse_all_reduce_ops": True,
            "fuse_grad_size_in_MB": 32,
            "nccl_comm_num": 1,
            "heter_ccl_mode": False,
            "without_graph_optimization": False,
        }

    def __getattr__(self, name):
        cfg = self.__dict__.get("_cfg", {})
        if name in cfg:
            return cfg[name]
        raise AttributeError(name)

    def __setattr__(self, name, value):
        cfg = self.__dict__["_cfg"]
        if name not in cfg:
            raise AttributeError(
                f"DistributedStrategy has no knob {name!r} "
                f"(known: {sorted(cfg)})")
        if name in ("dgc", "localsgd") and value:
            other = "localsgd" if name == "dgc" else "dgc"
            if cfg.get(other):
                raise ValueError("dgc and localsgd are mutually exclusive")
        allowed = self._CONFIG_KEYS.get(name)
        if allowed is not None:
            unknown = set(value) - allowed
            if unknown:
                raise ValueError(
                    f"unknown key(s) {sorted(unknown)} in "
                    f"DistributedStrategy.{name}; allowed: {sorted(allowed)}")
            merged = dict(cfg[name])
            merged.update(value)
            value = merged
        cfg[name] = value

    def __repr__(self):
        on = [k for k, v in self._cfg.items()
              if isinstance(v, bool) and v]
        return f"DistributedStrategy(hybrid={self.hybrid_configs}, enabled={on})"


class _Fleet:
    def __init__(self):
        self._strategy = None
        self._hcg = None
        self._is_initialized = False
        self.worker_num_ = 1

    def init(self, role_maker=None, is_collective=False, strategy=None, log_level="INFO"):
        """Ref fleet_base.py:211."""
        init_parallel_env()
        self._strategy = strategy or DistributedStrategy()
        hc = self._strategy.hybrid_configs
        self._hcg = HybridCommunicateGroup(
            dp=hc.get("dp_degree", 1), mp=hc.get("mp_degree", 1),
            pp=hc.get("pp_degree", 1), sharding=hc.get("sharding_degree", 1),
            sep=hc.get("sep_degree", 1),
        )
        set_hybrid_communicate_group(self._hcg)
        self._is_initialized = True
        return self

    def is_first_worker(self):
        return get_rank() == 0

    def worker_index(self):
        return get_rank()

    def worker_num(self):
        return get_world_size()

    def is_worker(self):
        return True

    def worker_endpoints(self, to_string=False):
        import os

        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
        return ",".join(eps) if to_string else eps

    def barrier_worker(self):
        _collective.barrier()

    def get_hybrid_communicate_group(self):
        return self._hcg

    @property
    def _hcg_prop(self):
        return self._hcg

    def distributed_model(self, model):
        """Ref fleet_base.py:947,1052-1077 — wrap per strategy.  With SPMD shardings
        the tp/sharding wrappers are no-ops (annotations live on the layers); pp wraps
        into the compiled PipelineParallel; pure-dp wraps in DataParallel."""
        if self._hcg is not None and self._hcg.get_pipe_parallel_world_size() > 1:
            from ..meta_parallel.pipeline_parallel import PipelineParallel

            if not isinstance(model, PipelineParallel):
                model = PipelineParallel(model, self._hcg, self._strategy)
            return model
        if self._hcg is not None and self._hcg.get_model_parallel_world_size() > 1:
            from ..meta_parallel.tensor_parallel import TensorParallel

            return TensorParallel(model, self._hcg, strategy=self._strategy)
        return DataParallel(model)

    def distributed_optimizer(self, optimizer, strategy=None):
        """Ref fleet_base.py:890 → HybridParallelOptimizer."""
        from .hybrid_optimizer import HybridParallelOptimizer

        return HybridParallelOptimizer(optimizer, self._hcg, self._strategy)

    def distributed_train_step(self, model, loss_fn, optimizer):
        """TPU-native entry: ONE compiled step consuming every enabled
        strategy knob (the reference spread these across meta-optimizers that
        each rewrote the Program; here they are parameters of the jitted step).

        amp -> in-graph GradScaler; gradient_merge -> accum_steps;
        sharding -> ZeRO stage; recompute -> jax.checkpoint on the listed
        layers; hybrid_configs -> the mesh ShardedTrainStep runs on.
        """
        if not self._is_initialized:
            raise RuntimeError("call fleet.init(strategy=...) first")
        s = self._strategy
        inner_opt = getattr(optimizer, "_inner_opt", optimizer)

        if s.recompute:
            from .utils.recompute import apply_recompute

            model = apply_recompute(model, s.recompute_configs.get("checkpoints"))

        scaler = None
        if s.amp:
            from ...amp import GradScaler

            c = s.amp_configs
            scaler = GradScaler(
                init_loss_scaling=c.get("init_loss_scaling", 2.0 ** 15),
                incr_every_n_steps=c.get("incr_every_n_steps", 1000),
                decr_every_n_nan_or_inf=c.get("decr_every_n_nan_or_inf", 2),
                incr_ratio=c.get("incr_ratio", 2.0),
                decr_ratio=c.get("decr_ratio", 0.5),
                use_dynamic_loss_scaling=c.get("use_dynamic_loss_scaling", True))

        accum = 1
        if s.gradient_merge:
            accum = int(s.gradient_merge_configs.get("k_steps", 1))
        elif s.pipeline and self._hcg.get_pipe_parallel_world_size() <= 1:
            accum = int(s.pipeline_configs.get("accumulate_steps", 1))

        zero_stage = 0
        if s.sharding:
            zero_stage = int(s.sharding_configs.get("stage", 2))

        if s.localsgd or s.dgc:
            bad = [k for k, on in (("amp", s.amp), ("sharding", s.sharding),
                                   ("gradient_merge", s.gradient_merge),
                                   ("pipeline", self._hcg.get_pipe_parallel_world_size() > 1))
                   if on]
            if bad:
                raise NotImplementedError(
                    f"localsgd/dgc cannot be combined with {bad} — they own the "
                    f"dp-axis gradient schedule")
            from .meta_optimizers import DGCTrainStep, LocalSGDTrainStep

            if s.localsgd:
                return LocalSGDTrainStep(
                    model, loss_fn, inner_opt, self._hcg.mesh,
                    k_steps=int(s.localsgd_configs.get("k_steps", 4)))
            c = s.dgc_configs
            sparsity = c.get("sparsity", 0.999)
            if isinstance(sparsity, (list, tuple)):
                sparsity = sparsity[-1]
            return DGCTrainStep(
                model, loss_fn, inner_opt, self._hcg.mesh,
                sparsity=float(sparsity),
                rampup_begin_step=int(c.get("rampup_begin_step", 0)))

        if self._hcg.get_pipe_parallel_world_size() > 1:
            if scaler is not None or (s.gradient_merge and accum > 1):
                # don't silently drop enabled knobs: the compiled pipeline has
                # its own microbatching and no loss-scaling hook yet
                raise NotImplementedError(
                    "amp / gradient_merge are not supported together with "
                    "pipeline parallelism yet — pipeline microbatching "
                    "(pipeline_configs.accumulate_steps) already accumulates, "
                    "and bf16 needs no loss scaling on TPU")
            from ..meta_parallel.pipeline_schedule import PipelineTrainStep

            return PipelineTrainStep(
                model, loss_fn, inner_opt, self._hcg.mesh,
                n_microbatch=int(s.pipeline_configs.get("accumulate_steps", 1)))

        from ..sharded_train_step import ShardedTrainStep

        return ShardedTrainStep(model, loss_fn, inner_opt, self._hcg.mesh,
                                zero_stage=zero_stage, accum_steps=accum,
                                scaler=scaler)

    # PS-mode stubs (SURVEY.md §7.4: parameter-server stack is an explicit non-goal)
    def is_server(self):
        return False

    def init_worker(self):
        pass

    def init_server(self, *args, **kwargs):
        raise NotImplementedError("parameter-server mode is out of scope for the TPU build")

    def run_server(self):
        raise NotImplementedError("parameter-server mode is out of scope for the TPU build")

    def stop_worker(self):
        pass

    def save_inference_model(self, *args, **kwargs):
        pass

    def save_persistables(self, *args, **kwargs):
        pass


fleet = _Fleet()

# module-level function aliases (paddle.distributed.fleet.init style)
init = fleet.init
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
worker_index = fleet.worker_index
worker_num = fleet.worker_num
is_first_worker = fleet.is_first_worker
get_hybrid_communicate_group_fn = fleet.get_hybrid_communicate_group


class UserDefinedRoleMaker:
    def __init__(self, *args, **kwargs):
        pass


class PaddleCloudRoleMaker:
    def __init__(self, is_collective=False, **kwargs):
        self._is_collective = is_collective
