"""Filesystem abstraction for checkpoint storage.

Ref: python/paddle/distributed/fleet/utils/fs.py (FS base, LocalFS,
HDFSClient over the hadoop CLI).  The checkpoint saver (SURVEY §5.4) writes
through this interface so HDFS-backed clusters and local disks share a code
path; this build implements LocalFS fully and keeps HDFSClient's surface
with an actionable error (no hadoop binary in the TPU image).
"""
from __future__ import annotations

import os
import shutil

__all__ = ["FS", "LocalFS", "HDFSClient", "FSFileExistsError", "FSFileNotExistsError"]


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class FS:
    def ls_dir(self, fs_path):
        raise NotImplementedError

    def is_file(self, fs_path):
        raise NotImplementedError

    def is_dir(self, fs_path):
        raise NotImplementedError

    def is_exist(self, fs_path):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def need_upload_download(self):
        raise NotImplementedError

    def rename(self, fs_src_path, fs_dst_path):
        raise NotImplementedError

    def mv(self, fs_src_path, fs_dst_path, overwrite=False):
        raise NotImplementedError

    def upload_dir(self, local_dir, dest_dir):
        raise NotImplementedError

    def list_dirs(self, fs_path):
        raise NotImplementedError

    def touch(self, fs_path, exist_ok=True):
        raise NotImplementedError

    def cat(self, fs_path=None):
        raise NotImplementedError


class LocalFS(FS):
    """Local-disk implementation (ref fs.py LocalFS)."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for name in sorted(os.listdir(fs_path)):
            if os.path.isdir(os.path.join(fs_path, name)):
                dirs.append(name)
            else:
                files.append(name)
        return dirs, files

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def delete(self, fs_path):
        if self.is_file(fs_path):
            os.remove(fs_path)
        elif self.is_dir(fs_path):
            shutil.rmtree(fs_path)

    def need_upload_download(self):
        return False

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if exist_ok:
                return
            raise FSFileExistsError(fs_path)
        with open(fs_path, "a"):
            pass

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if not self.is_exist(src_path):
            raise FSFileNotExistsError(src_path)
        if not overwrite and self.is_exist(dst_path):
            raise FSFileExistsError(dst_path)
        shutil.move(src_path, dst_path)

    def list_dirs(self, fs_path):
        if not self.is_exist(fs_path):
            return []
        return [d for d in sorted(os.listdir(fs_path))
                if os.path.isdir(os.path.join(fs_path, d))]

    def cat(self, fs_path=None):
        with open(fs_path) as f:
            return f.read()


class HDFSClient(FS):
    """Surface parity for the hadoop-CLI client (ref fs.py HDFSClient).
    The TPU image ships no hadoop binary; construction works (so configs
    that instantiate it still import) but any operation raises with
    guidance to use LocalFS or a mounted path."""

    def __init__(self, hadoop_home=None, configs=None, time_out=300000, sleep_inter=1000):
        self._hadoop_home = hadoop_home

    def _unavailable(self):
        raise RuntimeError(
            "HDFSClient: no hadoop CLI in this environment. Point the "
            "checkpoint dir at a mounted/network filesystem and use LocalFS "
            "instead — the saver only needs the FS interface.")

    def __getattribute__(self, name):
        if name.startswith("_") or name in ("need_upload_download",):
            return object.__getattribute__(self, name)
        if name in ("ls_dir", "is_file", "is_dir", "is_exist", "upload",
                    "download", "mkdirs", "delete", "rename", "mv",
                    "upload_dir", "list_dirs", "touch", "cat"):
            self._unavailable()
        return object.__getattribute__(self, name)

    def need_upload_download(self):
        return True
