"""Activation recomputation (gradient checkpointing).

Reference: `python/paddle/distributed/fleet/utils/recompute.py` — a PyLayer whose
forward runs under no_grad saving only inputs + RNG state, and whose backward re-runs
the forward to rebuild activations before backprop.

TPU-native: the recomputed region becomes ONE taped op whose primal is wrapped in
`jax.checkpoint` (remat).  Eagerly this gives the same save-inputs-only semantics;
under `to_static`/jit the XLA scheduler rematerializes the region in the backward
pass, trading FLOPs for HBM exactly like the reference — but fused and overlapped by
the compiler instead of a Python-driven re-forward.
"""
from __future__ import annotations

import jax

from ....tensor.tensor import Tensor, apply_op
from ....autograd import tape
from ....framework import random as _random
from ....nn.layer.layers import Layer


def _owning_layer(function):
    if isinstance(function, Layer):
        return function
    owner = getattr(function, "__self__", None)
    return owner if isinstance(owner, Layer) else None


def recompute(function, *args, preserve_rng_state: bool = True, use_reentrant: bool = True,
              **kwargs):
    """Run `function(*args)` but save only its inputs for backward; activations are
    rebuilt (XLA remat) when gradients flow.  `function` may be an `nn.Layer` (its
    parameters are captured as differentiable inputs) or any callable of Tensors."""
    layer = _owning_layer(function)
    param_items = list(layer.named_parameters()) if layer is not None else []
    buffers = {k: b for k, b in layer.named_buffers()} if layer is not None else {}

    n_args = len(args)
    key = _random.get_rng_key() if preserve_rng_state else None

    def primal(*flat):
        call_args = [
            Tensor(v, stop_gradient=True) if isinstance(args[i], Tensor) else args[i]
            for i, v in enumerate(flat[:n_args])
        ]
        params = {k: v for (k, _), v in zip(param_items, flat[n_args:])}
        scope = _random.rng_key_scope(key) if key is not None else _nullcontext()
        with scope, tape.no_grad():
            if layer is not None:
                restore = layer.bind_functional_state(
                    params, {k: b._value for k, b in buffers.items()})
                try:
                    out = function(*call_args, **kwargs)
                finally:
                    restore()
            else:
                out = function(*call_args, **kwargs)
        if isinstance(out, (tuple, list)):
            return tuple(o._value if isinstance(o, Tensor) else o for o in out)
        return out._value if isinstance(out, Tensor) else out

    flat_inputs = (*args, *[p for _, p in param_items])
    static = tuple(i for i, a in enumerate(flat_inputs)
                   if not isinstance(a, Tensor) and not hasattr(a, "shape"))
    return apply_op(jax.checkpoint(primal, static_argnums=static), flat_inputs,
                    name="recompute")


class _nullcontext:
    def __enter__(self):
        return None

    def __exit__(self, *a):
        return False


class _Chunk(Layer):
    """A registered container for one recomputed segment so `recompute` can capture
    the segment's parameters as differentiable inputs (not closure constants)."""

    def __init__(self, layers):
        super().__init__()
        self._n = len(layers)
        for i, l in enumerate(layers):
            setattr(self, f"seg{i}", l)

    def forward(self, *xs):
        y = xs
        for i in range(self._n):
            l = getattr(self, f"seg{i}")
            y = l(*y) if isinstance(y, tuple) else l(y)
            if not isinstance(y, tuple):
                y = (y,)
        return y[0] if len(y) == 1 else y


def recompute_sequential(ctx, functions, *args, **kwargs):
    """Ref fleet/utils/recompute.py `recompute_sequential`: chunk a Sequential and
    recompute each segment."""
    segments = int(ctx.get("segments", 1)) if isinstance(ctx, dict) else int(ctx or 1)
    if isinstance(functions, Layer):
        layers = list(functions.children()) or [functions]
    else:
        layers = list(functions)
    n = len(layers)
    seg = max(1, n // max(1, segments))
    out = args
    for start in range(0, n, seg):
        chunk = _Chunk(layers[start:start + seg])
        out = recompute(chunk, *out, **kwargs)
        if not isinstance(out, tuple):
            out = (out,)
    return out[0] if len(out) == 1 else out
