"""Activation recomputation (gradient checkpointing).

Reference: `python/paddle/distributed/fleet/utils/recompute.py` — a PyLayer whose
forward runs under no_grad saving only inputs + RNG state, and whose backward re-runs
the forward to rebuild activations before backprop.

TPU-native: the recomputed region becomes ONE taped op whose primal is wrapped in
`jax.checkpoint` (remat).  Eagerly this gives the same save-inputs-only semantics;
under `to_static`/jit the XLA scheduler rematerializes the region in the backward
pass, trading FLOPs for HBM exactly like the reference — but fused and overlapped by
the compiler instead of a Python-driven re-forward.
"""
from __future__ import annotations

import contextlib
import functools

import jax

from ....tensor.tensor import Tensor, apply_op
from ....autograd import tape
from ....framework import random as _random
from ....nn.layer.layers import Layer
from ....nn.layer.container import LayerList


def _collect_layers(function) -> list[Layer]:
    """Find every Layer whose parameters `function` can reach: the function itself,
    a bound method's owner, functools.partial payloads, and closure cells.  These
    params must enter the checkpointed primal as differentiable inputs — anything
    reached only as a closure constant would silently get no gradient."""
    seen: dict[int, Layer] = {}

    def visit(obj, depth=0):
        if depth > 3:
            return
        if isinstance(obj, Layer):
            seen.setdefault(id(obj), obj)
            return
        owner = getattr(obj, "__self__", None)
        if isinstance(owner, Layer):
            seen.setdefault(id(owner), owner)
        if isinstance(obj, functools.partial):
            visit(obj.func, depth + 1)
            for a in obj.args:
                visit(a, depth + 1)
            for v in obj.keywords.values():
                visit(v, depth + 1)
        closure = getattr(obj, "__closure__", None)
        if closure:
            for cell in closure:
                try:
                    visit(cell.cell_contents, depth + 1)
                except ValueError:
                    pass
        if isinstance(obj, (list, tuple)):
            for it in obj:
                visit(it, depth + 1)

    visit(function)
    return list(seen.values())


def recompute(function, *args, preserve_rng_state: bool = True, use_reentrant: bool = True,
              **kwargs):
    """Run `function(*args)` but save only its inputs for backward; activations are
    rebuilt (XLA remat) when gradients flow.  `function` may be an `nn.Layer`, a bound
    method, a closure/partial over Layers (their parameters are discovered and
    captured as differentiable inputs), or any pure callable of Tensors."""
    layers = _collect_layers(function)
    param_items = []   # (layer_idx, name, Parameter); dedup shared Parameter objects
    buffer_state = []  # (layer_idx, {name: raw})
    seen_params: set[int] = set()
    for li, layer in enumerate(layers):
        for k, p in layer.named_parameters():
            if id(p) not in seen_params:
                seen_params.add(id(p))
                param_items.append((li, k, p))
        buffer_state.append({k: b._value for k, b in layer.named_buffers()})

    n_args = len(args)
    key = _random.get_rng_key() if preserve_rng_state else None

    def primal(*flat):
        call_args = [
            Tensor(v, stop_gradient=True) if isinstance(args[i], Tensor) else args[i]
            for i, v in enumerate(flat[:n_args])
        ]
        per_layer: list[dict] = [{} for _ in layers]
        for (li, k, _), v in zip(param_items, flat[n_args:]):
            per_layer[li][k] = v
        scope = _random.rng_key_scope(key) if key is not None else contextlib.nullcontext()
        restores = []
        with scope, tape.no_grad():
            try:
                for li, layer in enumerate(layers):
                    restores.append(layer.bind_functional_state(per_layer[li],
                                                                buffer_state[li]))
                out = function(*call_args, **kwargs)
            finally:
                for r in reversed(restores):
                    r()
        if isinstance(out, (tuple, list)):
            return tuple(o._value if isinstance(o, Tensor) else o for o in out)
        return out._value if isinstance(out, Tensor) else out

    flat_inputs = (*args, *[p for _, _, p in param_items])
    static = tuple(i for i, a in enumerate(flat_inputs)
                   if not isinstance(a, Tensor) and not hasattr(a, "shape"))
    return apply_op(jax.checkpoint(primal, static_argnums=static), flat_inputs,
                    name="recompute")


class _Chunk(Layer):
    """A registered container for one recomputed segment (params discoverable by
    `_collect_layers` via the Layer itself)."""

    def __init__(self, layers):
        super().__init__()
        self.segs = LayerList(layers)

    def forward(self, *xs):
        y = xs
        for l in self.segs:
            y = l(*y) if isinstance(y, tuple) else l(y)
            if not isinstance(y, tuple):
                y = (y,)
        return y[0] if len(y) == 1 else y


def recompute_sequential(ctx, functions, *args, **kwargs):
    """Ref fleet/utils/recompute.py `recompute_sequential`: chunk a Sequential and
    recompute each segment."""
    segments = int(ctx.get("segments", 1)) if isinstance(ctx, dict) else int(ctx or 1)
    if isinstance(functions, Layer):
        layers = list(functions.children()) or [functions]
    else:
        layers = list(functions)
    n = len(layers)
    seg = max(1, n // max(1, segments))
    out = args
    for start in range(0, n, seg):
        chunk = _Chunk(layers[start:start + seg])
        out = recompute(chunk, *out, **kwargs)
        if not isinstance(out, tuple):
            out = (out,)
    return out[0] if len(out) == 1 else out


def apply_recompute(model, checkpoints=None):
    """Wrap sublayers of `model` so their forward runs under activation
    recompute (ref meta_optimizers/recompute_optimizer.py: the static twin
    rewrote the Program; here we wrap Layer.forward with `recompute`).

    `checkpoints`: sublayer names from named_sublayers() to wrap; None wraps
    every direct child that owns parameters.  Returns `model` (mutated).
    """
    named = dict(model.named_sublayers())
    if checkpoints:
        targets = []
        for name in checkpoints:
            if name not in named:
                raise ValueError(
                    f"recompute checkpoint {name!r} is not a sublayer of "
                    f"{type(model).__name__}; known: {sorted(named)[:20]}...")
            targets.append(named[name])
    else:
        targets = [l for _, l in model.named_children()
                   if any(True for _ in l.parameters())]
    for layer in targets:
        if getattr(layer, "_recompute_wrapped", False):
            continue
        inner_forward = layer.forward

        def wrapped(*args, _f=inner_forward, **kwargs):
            return recompute(_f, *args, **kwargs)

        layer.forward = wrapped
        layer._recompute_wrapped = True
    return model
