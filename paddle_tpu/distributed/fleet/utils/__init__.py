"""fleet.utils (ref: python/paddle/distributed/fleet/utils/) — recompute +
hybrid-parallel helpers."""
from .recompute import recompute, recompute_sequential  # noqa: F401
from . import hybrid_parallel_util  # noqa: F401
from .hybrid_parallel_util import (  # noqa: F401
    broadcast_input_data, broadcast_mp_parameters, broadcast_dp_parameters,
    broadcast_sharding_parameters, fused_allreduce_gradients,
)
from . import fs  # noqa: F401
