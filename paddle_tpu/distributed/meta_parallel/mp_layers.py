"""Tensor (model) parallel layers.

Reference: fleet/meta_parallel/parallel_layers/mp_layers.py —
VocabParallelEmbedding:30, ColumnParallelLinear:95, RowParallelLinear:171,
ParallelCrossEntropy:251, where each layer holds a weight SHARD and calls NCCL
collectives by hand.

TPU-native: each layer holds the FULL logical weight annotated with a PartitionSpec
(`sharding_spec`), the forward is ordinary math plus `constraint` hints, and the XLA
SPMD partitioner materializes the per-device shards and inserts the identical
collectives (allgather for column gather_output, psum for row) over ICI.  Numerics are
bit-identical to the single-device layer — the reference needed parity tests for this
(hybrid_parallel_mp_layers.py); here it is true by construction and the tests verify
the compiled sharded run against the dense one.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...nn.layer.layers import Layer
from ...nn import functional as F
from ...nn.initializer import XavierNormal, Normal, Constant
from ...tensor.tensor import Tensor
from ..sharding_ctx import annotate, constraint


class VocabParallelEmbedding(Layer):
    """Ref mp_layers.py:30 — embedding table sharded over the vocab dim on 'mp'."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None, mp_group=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=Normal(0.0, 1.0) if weight_attr is None else None,
        )
        annotate(self.weight, "mp", None)

    def forward(self, x):
        out = F.embedding(x, self.weight)
        out._value = constraint(out._value, None, None, None) if out.ndim == 3 else out._value
        return out


class ColumnParallelLinear(Layer):
    """Ref mp_layers.py:95 — weight [in, out] sharded on out ('mp' columns)."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=None,
                 gather_output=True, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr, default_initializer=XavierNormal()
        )
        annotate(self.weight, None, "mp")
        if has_bias or has_bias is None:
            self.bias = self.create_parameter([out_features], is_bias=True)
            annotate(self.bias, "mp")
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            out._value = constraint(out._value, *([None] * out.ndim))
        else:
            out._value = constraint(out._value, *([None] * (out.ndim - 1)), "mp")
        return out


class RowParallelLinear(Layer):
    """Ref mp_layers.py:171 — weight [in, out] sharded on in ('mp' rows); the psum the
    reference issues by hand is inserted by the partitioner at the contraction."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr, default_initializer=XavierNormal()
        )
        annotate(self.weight, "mp", None)
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            annotate(self.bias, None)
        else:
            self.bias = None

    def forward(self, x):
        if self.input_is_parallel:
            x._value = constraint(x._value, *([None] * (x.ndim - 1)), "mp")
        out = F.linear(x, self.weight, self.bias)
        out._value = constraint(out._value, *([None] * out.ndim))
        return out


class ParallelCrossEntropy(Layer):
    """Ref mp_layers.py:251 — CE over vocab-sharded logits; GSPMD handles the
    sharded logsumexp reduction."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none", ignore_index=self.ignore_index)


class RNGStatesTracker:
    """Ref parallel_layers/random.py RNGStatesTracker (dropout determinism across TP).
    With functional threefry keys every device derives the same key stream, so local
    (non-replicated) dropout uses a fold_in on the mp axis index inside shard_map."""

    def __init__(self):
        self.states_ = {}

    def add(self, name, seed):
        import jax

        from ...framework.random import make_key
        self.states_[name] = make_key(seed)

    def rng_state(self, name="model_parallel_rng"):
        import contextlib

        @contextlib.contextmanager
        def scope():
            from ...framework import random as _random

            if name in self.states_:
                with _random.rng_key_scope(self.states_[name]) as gen:
                    yield
                    self.states_[name] = gen._key
            else:
                yield

        return scope()


_RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _RNG_STATE_TRACKER


def model_parallel_random_seed(seed=None):
    import numpy as np

    _RNG_STATE_TRACKER.add("model_parallel_rng", seed or np.random.randint(1 << 30))
