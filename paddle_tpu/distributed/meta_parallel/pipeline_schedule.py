"""Compiled pipeline-parallel training step.

Reference analog: `PipelineParallel.forward_backward_pipeline` (ref
fleet/meta_parallel/pipeline_parallel.py:82) — a HOST-DRIVEN 1F1B loop issuing NCCL
p2p sends/recvs per microbatch (p2p_communication.py:232).

TPU-native: the whole schedule is ONE XLA program.  `jax.shard_map` is manual only
over the 'pp' mesh axis; stage-to-stage transfer is `lax.ppermute` and the
fill/steady/drain schedule is a `lax.scan` over ticks.  Autodiff through the scan +
ppermute yields the reverse (backward) pipeline automatically — the transpose of a
ppermute is the reverse ppermute, so XLA schedules forward and backward waves without
any Python in the loop.  All other mesh axes (dp/sharding/mp) stay "auto": the SPMD
partitioner shards the batch and inserts dp gradient all-reduces around the manual
pp core, which is how dp×pp composition falls out for free.

Stage partitioning: the layer list is split into
  prologue  — leading layers that change the activation shape (e.g. embedding);
              run on ALL microbatches before the pipeline (cheap, one fused kernel);
  body      — the maximal shape-preserving run of layers (transformer blocks);
              split contiguously into `pp` stages, dispatched by `lax.switch` on
              the device's stage index;
  epilogue  — trailing shape-changing layers (final norm / lm head) + loss, folded
              into the LAST stage so the carried activation keeps one shape.

Correctness of bubble ticks: stage k's tick t computes microbatch (t-k), which is
out-of-range during fill/drain; those values are real-but-unused (clamped indices on
finite inputs, zero-init carry), and the last stage masks their loss with a `where`,
so neither the loss nor its gradient sees them.

Memory layout (v2): when the body chunks are HOMOGENEOUS (every stage runs the same
layer structure — true for L % pp == 0 transformer stacks), each body parameter is
stacked across stages into one [pp, ...] array sharded over the 'pp' mesh axis
(NamedSharding P('pp')), so per-device body-parameter bytes = total/pp — the memory
contract of the reference's 1F1B pipeline (ref fleet/meta_parallel/
pipeline_parallel.py:82) without its host-driven p2p loop.  Every device then runs
the SAME stage program with its own weight slice (no lax.switch), and the per-tick
work is wrapped in jax.checkpoint so peak activation memory scales with the
microbatch count × carry size (the 1F1B memory shape), not batch × depth.
Non-homogeneous models fall back to the v1 replicated layout.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...tensor.tensor import Tensor
from ...autograd import tape
from ...framework import random as _random


def _apply_item(pair, t):
    layer, ffunc = pair
    if ffunc == "__callable__":
        return layer(t)
    if ffunc is not None:
        return ffunc(layer, t)
    return layer(t)


class PipelineTrainStep:
    """One-program GPipe schedule over the 'pp' mesh axis.

    step = PipelineTrainStep(pipeline_layer, loss_fn, optimizer, mesh, n_microbatch)
    loss = step(x, y)
    """

    def __init__(self, layers, loss_fn, optimizer, mesh, n_microbatch: int,
                 donate: bool = True, remat: bool = True):
        if "pp" not in mesh.axis_names:
            raise ValueError("mesh has no 'pp' axis")
        self.model = layers
        self.loss_fn = loss_fn if loss_fn is not None else getattr(layers, "_loss_fn", None)
        if self.loss_fn is None:
            raise ValueError("pipeline needs a loss_fn (PipelineLayer(loss_fn=...))")
        self.optimizer = optimizer
        self.mesh = mesh
        self.n_stages = mesh.shape["pp"]
        self.n_microbatch = max(int(n_microbatch), self.n_stages)
        self._donate = donate
        self._remat = remat
        self._jitted = None
        self._opt_state = None
        self._stacked = None       # {rel: [pp, ...] array} when homogeneous
        self._stack_info = None    # per-stage [(rel, flat_name)] lists
        self.stacked_mode = False

    # ------------------------------------------------------------------ probing
    def _probe_shapes(self, params, buffers, x_mb):
        """Per-item output ShapeDtypeStructs for one microbatch-shaped input."""
        items = self.model.run_function
        model = self.model

        def run(params, buffers, x):
            restore = model.bind_functional_state(params, buffers)
            try:
                with tape.no_grad():
                    t = Tensor(x, stop_gradient=True)
                    outs = []
                    for item in items:
                        t = _apply_item(item, t)
                        outs.append(t._value)
            finally:
                restore()
            return outs

        return jax.eval_shape(run, params, buffers,
                              jax.ShapeDtypeStruct(x_mb.shape, x_mb.dtype))

    def _partition(self, in_shape, out_shapes):
        """prologue / body(chunked into stages) / epilogue item index ranges."""
        n = len(out_shapes)
        ins = [in_shape] + [(s.shape, s.dtype) for s in out_shapes[:-1]]
        outs = [(s.shape, s.dtype) for s in out_shapes]
        preserve = [ins[i] == outs[i] for i in range(n)]
        body_end = -1
        for i in range(n - 1, -1, -1):
            if preserve[i]:
                body_end = i
                break
        if body_end < 0:
            raise ValueError("no shape-preserving layers to pipeline")
        body_start = body_end
        while body_start > 0 and preserve[body_start - 1]:
            body_start -= 1
        body = list(range(body_start, body_end + 1))
        if len(body) < self.n_stages:
            raise ValueError(
                f"{len(body)} pipelineable layers < {self.n_stages} pipeline stages")
        chunks = [list(c) for c in np.array_split(body, self.n_stages)]
        return list(range(body_start)), chunks, list(range(body_end + 1, n))

    # ---------------------------------------------------------------- stacking
    def _try_stack_info(self, chunks, items, named):
        """(per_stage_params, per_stage_buffers, None) if every stage chunk
        has the same layer structure (param/buffer names, shapes, dtypes,
        per-slot trainability); otherwise (None, None, reason).

        Frozen body params ARE stackable (they ride along without grads);
        body-layer buffers ARE stackable read-only (in-trace buffer writes
        are dropped, matching the replicated pipeline's semantics).  Tied
        params ACROSS body stages are the one true fallback — stacking would
        un-tie them (tying prologue<->epilogue, e.g. embedding<->lm_head,
        lives outside the body and stacks fine: the shared leaf stays
        replicated and its shard_map cotangent is psum'd over 'pp', the
        compiled analog of allreduce_shared_weight_gradients, ref
        pp_layers.py:162 SharedLayerDesc)."""
        id2flat = {id(p): k for k, p in named.items()}
        buf_named = dict(self.model.named_buffers())
        id2buf = {id(b): k for k, b in buf_named.items()}
        per_stage, per_stage_buf = [], []
        for c in chunks:
            plist, blist = [], []
            for j, i in enumerate(c):
                layer = items[i][0]
                if not callable(layer) or not hasattr(layer, "named_parameters"):
                    return None, None, (
                        f"body item {i} is not a Layer with parameters")
                for pn, p in layer.named_parameters():
                    if id(p) not in id2flat:
                        return None, None, (
                            f"body param {pn} not registered on the model")
                    plist.append((f"{j}.{pn}", id2flat[id(p)]))
                for bn, b in layer.named_buffers():
                    if id(b) not in id2buf:
                        return None, None, (
                            f"body buffer {bn} not registered on the model")
                    blist.append((f"{j}.{bn}", id2buf[id(b)]))
            per_stage.append(plist)
            per_stage_buf.append(blist)
        all_flats = [f for plist in per_stage for _, f in plist]
        if len(set(all_flats)) != len(all_flats):
            return None, None, (
                "a parameter is shared across body stages (intra-body tied "
                "weights): stacking would un-tie it")
        rels0 = [r for r, _ in per_stage[0]]
        brels0 = [r for r, _ in per_stage_buf[0]]
        for plist, blist in zip(per_stage[1:], per_stage_buf[1:]):
            if [r for r, _ in plist] != rels0 or [r for r, _ in blist] != brels0:
                return None, None, "stage chunks have different layer structures"
        for i in range(len(rels0)):
            p0 = named[per_stage[0][i][1]]
            for plist in per_stage[1:]:
                p = named[plist[i][1]]
                if (p._value.shape != p0._value.shape
                        or p._value.dtype != p0._value.dtype
                        or p.stop_gradient != p0.stop_gradient):
                    return None, None, (
                        f"param slot {rels0[i]} differs across stages "
                        "(shape/dtype/trainability)")
        for i in range(len(brels0)):
            b0 = buf_named[per_stage_buf[0][i][1]]
            for blist in per_stage_buf[1:]:
                b = buf_named[blist[i][1]]
                if (b._value.shape != b0._value.shape
                        or b._value.dtype != b0._value.dtype):
                    return None, None, (
                        f"buffer slot {brels0[i]} differs across stages")
        return per_stage, per_stage_buf, None

    def sync_model(self):
        """Write the stacked [pp, ...] body weights back into the model's Tensors
        (needed before state_dict()/save; the train loop itself never unstacks)."""
        if not self.stacked_mode or self._stacked is None:
            return
        named = dict(self.model.named_parameters())
        for idx, (rel, _) in enumerate(self._stack_info[0]):
            full = np.asarray(self._stacked[rel])
            for s, plist in enumerate(self._stack_info):
                named[plist[idx][1]]._rebind(jnp.asarray(full[s]))

    # ------------------------------------------------------------------ build
    def _init(self, x, y):
        model = self.model
        mesh = self.mesh
        S = self.n_stages
        M = self.n_microbatch
        loss_fn = self.loss_fn
        opt = self.optimizer
        items = model.run_function

        B = x.shape[0]
        if B % M != 0:
            raise ValueError(f"batch {B} not divisible by {M} microbatches")
        mb = B // M
        x_mb1 = jax.ShapeDtypeStruct((mb,) + x.shape[1:], x.dtype)

        params, buffers = model.functional_state()
        out_shapes = self._probe_shapes(params, buffers, x_mb1)
        prologue, chunks, epilogue = self._partition((x_mb1.shape, x_mb1.dtype), out_shapes)
        hid = out_shapes[chunks[-1][-1]]  # [mb, *hidden]

        named = dict(model.named_parameters())
        self._stack_info, self._stack_buf_info, reason = \
            self._try_stack_info(chunks, items, named)
        if self._stack_info is not None:
            return self._init_stacked(items, prologue, chunks, epilogue, hid,
                                      named, mb, M, S)
        import warnings

        warnings.warn(
            "pipeline: falling back to FULLY REPLICATED body weights "
            f"(per-device bytes = total, not total/pp): {reason}. "
            "Make the stage chunks homogeneous to restore the stacked "
            "memory contract.", stacklevel=3)
        trainable = {k for k, p in named.items() if not p.stop_gradient}
        self._opt_state = {k: opt._init_state(named[k]) for k in trainable}

        # params + opt state replicated over the mesh (v1 fallback; see docstring)
        rep = NamedSharding(mesh, P())
        for k, p in named.items():
            p._rebind(jax.device_put(p._value, rep))
        for k, b in model.named_buffers():
            b._rebind(jax.device_put(b._value, rep))
        self._opt_state = jax.device_put(self._opt_state, rep)

        # batch sharded over the data axes (auto axes of the shard_map)
        data_axes = tuple(a for a in ("dp", "sharding") if a in mesh.axis_names
                          and mesh.shape[a] > 1)
        self._batch_sharding = NamedSharding(mesh, P(data_axes if data_axes else None))

        T = M + S - 1

        def pipeline_loss(allp, buffers, xv, yv, key):
            """Runs on every device; manual over 'pp' only."""
            restore = model.bind_functional_state(allp, buffers)
            try:
                with _random.rng_key_scope(key), tape.no_grad():
                    # prologue on all microbatches at once
                    t = Tensor(xv, stop_gradient=True)
                    for i in prologue:
                        t = _apply_item(items[i], t)
                    emb = t._value
                    emb = emb.reshape((M, emb.shape[0] // M) + emb.shape[1:])
                    y_mb = yv.reshape((M, yv.shape[0] // M) + yv.shape[1:])
                    stage = lax.axis_index("pp")

                    def make_branch(k):
                        chunk = chunks[k]

                        def branch(x_in, t_idx):
                            h = Tensor(x_in, stop_gradient=True)
                            for i in chunk:
                                h = _apply_item(items[i], h)
                            if k == S - 1:
                                e = h
                                for i in epilogue:
                                    e = _apply_item(items[i], e)
                                mb_idx = jnp.clip(t_idx - (S - 1), 0, M - 1)
                                lbl = lax.dynamic_index_in_dim(y_mb, mb_idx, 0,
                                                               keepdims=False)
                                lt = loss_fn(e, Tensor(lbl, stop_gradient=True))
                                raw = (lt._value if isinstance(lt, Tensor) else lt)
                                raw = raw.astype(jnp.float32)
                                l = jnp.where(t_idx >= S - 1, raw, 0.0)
                            else:
                                l = jnp.zeros((), jnp.float32)
                            return h._value, l
                        return branch

                    branches = [make_branch(k) for k in range(S)]
                    perm = [(i, (i + 1) % S) for i in range(S)]
                    buf0 = jnp.zeros((emb.shape[1],) + hid.shape[1:], hid.dtype)

                    def tick(carry, t_idx):
                        buf, loss_acc = carry
                        inj = lax.dynamic_index_in_dim(
                            emb, jnp.clip(t_idx, 0, M - 1), 0, keepdims=False)
                        x_in = jnp.where(stage == 0, inj.astype(buf.dtype), buf)
                        h, l = lax.switch(stage, branches, x_in, t_idx)
                        nxt = lax.ppermute(h, "pp", perm)
                        return (nxt, loss_acc + l), None

                    (_, loss_acc), _ = lax.scan(tick, (buf0, jnp.zeros((), jnp.float32)),
                                                jnp.arange(T))
                    loss = lax.psum(loss_acc, "pp") / M
            finally:
                restore()
            return loss

        sharded_loss = jax.shard_map(
            pipeline_loss, mesh=mesh,
            in_specs=(P(), P(), P(), P(), P()),
            out_specs=P(),
            axis_names={"pp"},
            check_vma=False,
        )

        def step(params, buffers, opt_state, lr, key, xv, yv):
            t_params = {k: v for k, v in params.items() if k in trainable}
            frozen = {k: v for k, v in params.items() if k not in trainable}

            def pure_loss(tp):
                return sharded_loss({**tp, **frozen}, buffers, xv, yv, key)

            loss, grads = jax.value_and_grad(pure_loss)(t_params)
            clipped = opt._clipped_grads(list(grads.items()))
            new_params = dict(frozen)
            new_opt = {}
            for k, g in clipped:
                new_params[k], new_opt[k] = opt._apply_update(
                    params[k], g, opt_state[k], lr, opt._param_decay_coeff(named[k]))
            return new_params, new_opt, loss

        donate = (0, 2) if self._donate else ()
        self._jitted = jax.jit(step, donate_argnums=donate)

    def _init_stacked(self, items, prologue, chunks, epilogue, hid, named, mb, M, S):
        """v2: homogeneous stages — body weights stacked [pp, ...], sharded P('pp')."""
        model = self.model
        mesh = self.mesh
        loss_fn = self.loss_fn
        opt = self.optimizer
        remat = self._remat
        self.stacked_mode = True
        info = self._stack_info
        buf_info = self._stack_buf_info
        flat0 = {rel: flat for rel, flat in info[0]}   # template (chunk-0) names
        bflat0 = {rel: flat for rel, flat in buf_info[0]}
        body_flats = {flat for plist in info for _, flat in plist}
        body_buf_flats = {flat for blist in buf_info for _, flat in blist}
        self._body_flats = body_flats
        self._body_buf_flats = body_buf_flats
        buf_named = dict(model.named_buffers())

        pp_shard = NamedSharding(mesh, P("pp"))
        rep = NamedSharding(mesh, P())
        stacked = {}
        # rels whose slot is frozen ride along stacked but take no grads/updates
        self._frozen_rels = {rel for rel, flat in info[0]
                             if named[flat].stop_gradient}
        for idx, (rel, _) in enumerate(info[0]):
            # stack on host, then place sharded: the full [pp, ...] array never
            # materializes in one device's HBM
            arrs = [np.asarray(named[info[s][idx][1]]._value) for s in range(S)]
            stacked[rel] = jax.device_put(np.stack(arrs), pp_shard)
            # free the originals: rebind each stage's Tensor to its host copy so
            # device 0 doesn't keep the full body-param set alive alongside the
            # stacked shards (sync_model restores device arrays on demand)
            for s in range(S):
                named[info[s][idx][1]]._rebind(arrs[s])
        self._stacked = stacked
        stacked_buf = {}
        for idx, (rel, _) in enumerate(buf_info[0]):
            arrs = [np.asarray(buf_named[buf_info[s][idx][1]]._value)
                    for s in range(S)]
            stacked_buf[rel] = jax.device_put(np.stack(arrs), pp_shard)
            for s in range(S):
                buf_named[buf_info[s][idx][1]]._rebind(arrs[s])
        self._stacked_buf = stacked_buf

        rep_keys = [k for k in named if k not in body_flats]
        trainable = {k for k in rep_keys if not named[k].stop_gradient}
        for k in rep_keys:
            named[k]._rebind(jax.device_put(named[k]._value, rep))
        for bk, b in buf_named.items():
            if bk not in body_buf_flats:
                b._rebind(jax.device_put(b._value, rep))

        class _Shim:  # _init_state only reads ._value
            def __init__(self, v):
                self._value = v

        def _place_stacked_state(state):
            # moments share the stacked [pp, ...] shape -> shard over pp; 0-d
            # leaves (Adam beta1_pow/beta2_pow etc.) must stay replicated
            return jax.tree.map(
                lambda leaf: jax.device_put(
                    leaf, pp_shard if getattr(leaf, "ndim", 0) >= 1
                    and leaf.shape[0] == S else rep),
                state)

        self._opt_state = {
            **{k: jax.device_put(opt._init_state(named[k]), rep) for k in trainable},
            **{"·stack·" + rel: _place_stacked_state(opt._init_state(_Shim(v)))
               for rel, v in stacked.items() if rel not in self._frozen_rels},
        }

        data_axes = tuple(a for a in ("dp", "sharding") if a in mesh.axis_names
                          and mesh.shape[a] > 1)
        self._batch_sharding = NamedSharding(mesh, P(data_axes if data_axes else None))

        T = M + S - 1
        body = chunks[0]  # every stage runs the template chunk's program

        def pipeline_loss(rep_params, stk, stk_buf, buffers, xv, yv, key):
            local = {flat0[rel]: v[0] for rel, v in stk.items()}  # local [1,...] slice
            local_buf = {bflat0[rel]: v[0] for rel, v in stk_buf.items()}
            restore = model.bind_functional_state({**rep_params, **local},
                                                  {**buffers, **local_buf})
            try:
                with _random.rng_key_scope(key), tape.no_grad():
                    t = Tensor(xv, stop_gradient=True)
                    for i in prologue:
                        t = _apply_item(items[i], t)
                    emb = t._value
                    emb = emb.reshape((M, emb.shape[0] // M) + emb.shape[1:])
                    y_mb = yv.reshape((M, yv.shape[0] // M) + yv.shape[1:])
                    stage = lax.axis_index("pp")

                    def run_tick(x_in, t_idx):
                        h = Tensor(x_in, stop_gradient=True)
                        for i in body:
                            h = _apply_item(items[i], h)
                        hv = h._value

                        def last_fn(ev):
                            e = Tensor(ev, stop_gradient=True)
                            for i in epilogue:
                                e = _apply_item(items[i], e)
                            mb_idx = jnp.clip(t_idx - (S - 1), 0, M - 1)
                            lbl = lax.dynamic_index_in_dim(y_mb, mb_idx, 0,
                                                           keepdims=False)
                            lt = loss_fn(e, Tensor(lbl, stop_gradient=True))
                            raw = (lt._value if isinstance(lt, Tensor) else lt)
                            return jnp.where(t_idx >= S - 1,
                                             raw.astype(jnp.float32), 0.0)

                        l = lax.cond(stage == S - 1, last_fn,
                                     lambda ev: jnp.zeros((), jnp.float32), hv)
                        return hv, l

                    tick_body = jax.checkpoint(run_tick) if remat else run_tick
                    perm = [(i, (i + 1) % S) for i in range(S)]
                    buf0 = jnp.zeros((emb.shape[1],) + hid.shape[1:], hid.dtype)

                    def tick(carry, t_idx):
                        buf, loss_acc = carry
                        inj = lax.dynamic_index_in_dim(
                            emb, jnp.clip(t_idx, 0, M - 1), 0, keepdims=False)
                        x_in = jnp.where(stage == 0, inj.astype(buf.dtype), buf)
                        h, l = tick_body(x_in, t_idx)
                        nxt = lax.ppermute(h, "pp", perm)
                        return (nxt, loss_acc + l), None

                    (_, loss_acc), _ = lax.scan(
                        tick, (buf0, jnp.zeros((), jnp.float32)), jnp.arange(T))
                    loss = lax.psum(loss_acc, "pp") / M
            finally:
                restore()
            return loss

        sharded_loss = jax.shard_map(
            pipeline_loss, mesh=mesh,
            in_specs=(P(), P("pp"), P("pp"), P(), P(), P(), P()),
            out_specs=P(),
            axis_names={"pp"},
            check_vma=False,
        )
        frozen_rels = self._frozen_rels

        def step(rep_params, stk, stk_buf, buffers, opt_state, lr, key, xv, yv):
            t_rep = {k: v for k, v in rep_params.items() if k in trainable}
            frozen = {k: v for k, v in rep_params.items() if k not in trainable}
            stk_t = {r: v for r, v in stk.items() if r not in frozen_rels}
            stk_f = {r: v for r, v in stk.items() if r in frozen_rels}

            def pure_loss(tp, tstk):
                return sharded_loss({**tp, **frozen}, {**tstk, **stk_f},
                                    stk_buf, buffers, xv, yv, key)

            loss, (g_rep, g_stk) = jax.value_and_grad(pure_loss, argnums=(0, 1))(
                t_rep, stk_t)
            pairs = list(g_rep.items()) + [("·stack·" + rel, g)
                                           for rel, g in g_stk.items()]
            clipped = dict(opt._clipped_grads(pairs))
            new_rep = dict(frozen)
            new_stk = dict(stk_f)
            new_opt = {}
            for k in trainable:
                new_rep[k], new_opt[k] = opt._apply_update(
                    rep_params[k], clipped[k], opt_state[k], lr,
                    opt._param_decay_coeff(named[k]))
            for rel in stk_t:
                sk = "·stack·" + rel
                new_stk[rel], new_opt[sk] = opt._apply_update(
                    stk[rel], clipped[sk], opt_state[sk], lr,
                    opt._param_decay_coeff(named[flat0[rel]]))
            return new_rep, new_stk, new_opt, loss

        donate = (0, 1, 4) if self._donate else ()
        self._jitted = jax.jit(step, donate_argnums=donate)
        # any external state read (state_dict / functional_state / checkpoint save)
        # transparently writes the trained stacked weights back first
        model._pre_state_hook = self.sync_model

    # ------------------------------------------------------------------ call
    def compiled_stats(self, x, y):
        """Collective census of the compiled pipeline step (census.py) —
        the ppermute bytes are the stage-boundary activations crossing ICI
        per step (while-body counted once; x T ticks for totals)."""
        from ..census import collective_census

        xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
        if self._jitted is None:
            self._init(xv, yv)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        key = _random.get_rng_key()
        if self.stacked_mode:
            params, buffers = self.model.functional_state(_sync=False)
            rep_params = {k: v for k, v in params.items()
                          if k not in self._body_flats}
            buffers = {k: v for k, v in buffers.items()
                       if k not in self._body_buf_flats}
            compiled = self._jitted.lower(
                rep_params, self._stacked, self._stacked_buf, buffers,
                self._opt_state, lr, key, xv, yv).compile()
        else:
            params, buffers = self.model.functional_state()
            compiled = self._jitted.lower(
                params, buffers, self._opt_state, lr, key, xv, yv).compile()
        return collective_census(compiled)

    def __call__(self, x, y):
        xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
        if self._jitted is None:
            self._init(xv, yv)
        xv = jax.device_put(xv, self._batch_sharding)
        yv = jax.device_put(yv, self._batch_sharding)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        key = _random.get_rng_key()
        if self.stacked_mode:
            params, buffers = self.model.functional_state(_sync=False)
            rep_params = {k: v for k, v in params.items()
                          if k not in self._body_flats}
            buffers = {k: v for k, v in buffers.items()
                       if k not in self._body_buf_flats}
            new_rep, new_stk, new_opt, loss = self._jitted(
                rep_params, self._stacked, self._stacked_buf, buffers,
                self._opt_state, lr, key, xv, yv)
            self._stacked = new_stk
            self._opt_state = new_opt
            self.model.load_functional_state(new_rep)
        else:
            params, buffers = self.model.functional_state()
            new_params, new_opt, loss = self._jitted(
                params, buffers, self._opt_state, lr, key, xv, yv)
            self._opt_state = new_opt
            self.model.load_functional_state(new_params)
        self.optimizer._step_count += 1
        return Tensor(loss)
