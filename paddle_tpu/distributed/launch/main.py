"""Launcher CLI (ref: python/paddle/distributed/launch/main.py:18 + controllers/
collective.py:87-97 which sets PADDLE_MASTER / PADDLE_TRAINER_ID /
PADDLE_TRAINER_ENDPOINTS for every spawned trainer).

The CollectiveController spawns `nproc_per_node` local trainer processes with the
reference env contract plus JAX multi-host env (coordinator address/process id), logs
each rank to `--log_dir`, watches exits (ref controllers/watcher.py) and restarts
failed ranks up to `--max_restart` times (elastic level >= 1).
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
import zlib


def _detect_host(master_host: str) -> str:
    """Local address as seen on the route toward the master (no traffic sent)."""
    import socket

    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect((master_host, 9))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="paddle_tpu.distributed.launch",
                                description="TPU distributed launcher")
    p.add_argument("--master", default=None,
                   help="rendezvous server host:port (jax coordinator)")
    p.add_argument("--rank", type=int, default=-1, help="node rank (-1: auto)")
    p.add_argument("--nnodes", default="1", help="number of nodes, or MIN:MAX for elastic")
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--log_dir", default="log")
    p.add_argument("--log_level", default="INFO")
    p.add_argument("--run_mode", default="collective", choices=["collective"])
    p.add_argument("--job_id", default="default")
    p.add_argument("--devices", default=None, help="visible device ids, e.g. 0,1,2,3")
    p.add_argument("--max_restart", type=int, default=3)
    p.add_argument("--elastic_level", type=int, default=-1)
    p.add_argument("--elastic_timeout", type=int, default=30)
    p.add_argument("--metrics_port", type=int, default=None,
                   help="serve /metrics,/healthz,/varz from the launcher "
                        "(0 = ephemeral); healthz reports per-rank liveness")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


class CollectiveController:
    """Ref controllers/collective.py — build env per rank, spawn, watch."""

    def __init__(self, args):
        self.args = args
        self.procs: list[subprocess.Popen] = []
        self.restarts = 0
        self._host_list = None
        self._rdzv_rank = None
        self.telemetry = None
        nn = str(args.nnodes)
        self.min_nodes = int(nn.split(":")[0])
        self.max_nodes = int(nn.split(":")[-1])
        if getattr(args, "metrics_port", None) is not None:
            self._start_telemetry(args.metrics_port)

    def _start_telemetry(self, port):
        """Launcher-side telemetry plane (README "Endpoints & flight
        recorder"): /metrics + /varz over the process-global registry, and
        a /healthz `ranks` check that fails while any spawned trainer has
        exited nonzero (a restart-looping rank shows up as unhealthy, not
        as silent churn)."""
        from ...observability import metrics as _obs
        from ...observability.exporter import TelemetryServer

        self._m_restarts = _obs.gauge(
            "launch_rank_restarts_count",
            "Trainer ranks restarted by the launcher watcher")
        self._m_alive = _obs.gauge(
            "launch_ranks_alive_count", "Spawned trainer ranks still running")

        def _check_ranks():
            failed, alive, n = self._update_rank_gauges()
            if failed:
                return False, f"ranks {failed} exited nonzero"
            return True, f"{alive}/{n} ranks running"

        self.telemetry = TelemetryServer(port=port)
        self.telemetry.register_healthcheck("ranks", _check_ranks)
        self.telemetry.start()

    def _update_rank_gauges(self):
        """Refresh the launch_* gauges (called from BOTH the watch loop and
        the /healthz check, so plain /metrics scrapes never read stale
        values) -> (failed_ranks, alive, total)."""
        states = [p.poll() for p in self.procs]
        alive = sum(s is None for s in states)
        self._m_alive.set(alive)
        self._m_restarts.set(self.restarts)
        failed = [i for i, s in enumerate(states) if s not in (None, 0)]
        return failed, alive, len(states)

    def _endpoints(self, n):
        # deterministic port base: hash() is randomized per process (PYTHONHASHSEED),
        # which would give every launcher invocation/node a different endpoint list
        # for the same job_id; crc32 is stable across processes and hosts
        base = 61000 + (zlib.crc32(self.args.job_id.encode()) % 1000)
        nproc = self.args.nproc_per_node
        hosts = self._hosts()
        # ports stay globally unique so multi-node-on-localhost tests don't collide
        return ",".join(f"{hosts[min(i // nproc, len(hosts) - 1)]}:{base + i}"
                        for i in range(n))

    def _multi_node(self):
        return self.max_nodes > 1 and self.args.master

    def _hosts(self):
        """One agreed host list, one entry per node (see _rendezvous).
        Single-node: loopback."""
        if self._multi_node():
            self._rendezvous()
            return self._host_list
        n_nodes = min(max(self.min_nodes, max(self.args.rank, 0) + 1), self.max_nodes)
        return ["127.0.0.1"] * max(n_nodes, 1)

    def node_rank(self):
        if self._multi_node():
            self._rendezvous()
            return self._rdzv_rank
        return max(self.args.rank, 0)

    def _rendezvous(self):
        """Agree on (node_rank, host list) across all launchers (ref: the KV
        rendezvous in launch/controllers/master.py).

        Mastership: explicit --rank 0 hosts the store; --rank>0 connects; with
        --rank -1 (auto) the node that wins the bind race on the master port hosts
        it.  Auto ranks come from an atomic counter; node 0 then publishes the
        final host list under {job}/world so every node sees the SAME world size
        and endpoints (late joiners beyond that list get a clear error)."""
        if self._host_list is not None:
            return
        from ..store import TCPStore

        a = self.args
        master_host, master_port = a.master.rsplit(":", 1)
        local = os.environ.get("PADDLE_LOCAL_HOST") or _detect_host(master_host)
        if a.rank == 0:
            store = TCPStore(master_host, int(master_port), is_master=True)
        elif a.rank > 0:
            store = TCPStore(master_host, int(master_port), is_master=False)
        else:
            try:
                store = TCPStore(master_host, int(master_port), is_master=True,
                                 use_native=False)
            except OSError:
                store = TCPStore(master_host, int(master_port), is_master=False)
        node_rank = a.rank if a.rank >= 0 else store.add(f"{a.job_id}/nrank", 1) - 1
        store.set(f"{a.job_id}/host/{node_rank}", local.encode())
        if node_rank == 0:
            # barrier on the minimum quorum, then fold in any extra early joiners
            hosts = [store.get(f"{a.job_id}/host/{r}").decode()
                     for r in range(self.min_nodes)]
            if a.rank < 0:
                n_reg = store.add(f"{a.job_id}/nrank", 0)
            else:
                # explicit ranks: count contiguously registered hosts above the
                # quorum so an initial gang of min..max nodes isn't sealed out
                n_reg = self.min_nodes
                while n_reg < self.max_nodes and \
                        store.get_nb(f"{a.job_id}/host/{n_reg}") is not None:
                    n_reg += 1
            n_use = min(max(int(n_reg), self.min_nodes), self.max_nodes)
            hosts += [store.get(f"{a.job_id}/host/{r}").decode()
                      for r in range(self.min_nodes, n_use)]
            store.set(f"{a.job_id}/world", ",".join(hosts).encode())
        else:
            hosts = store.get(f"{a.job_id}/world").decode().split(",")
        if node_rank >= len(hosts):
            raise RuntimeError(
                f"node rank {node_rank} joined after the job world of "
                f"{len(hosts)} nodes was sealed; scale-up of a running job goes "
                "through fleet.elastic, not the launcher")
        self._rdzv_rank = node_rank
        self._host_list = hosts
        self._store = store  # keep the master server thread alive

    def build_env(self, local_rank: int) -> dict:
        a = self.args
        n = a.nproc_per_node
        node_rank = self.node_rank()
        global_rank = node_rank * n + local_rank
        world = len(self._hosts()) * n
        eps = self._endpoints(world)
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(global_rank),
            "PADDLE_LOCAL_RANK": str(local_rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_TRAINER_ENDPOINTS": eps,
            "PADDLE_CURRENT_ENDPOINT": eps.split(",")[global_rank],
            "PADDLE_JOB_ID": a.job_id,
        })
        if a.master:
            env["PADDLE_MASTER"] = a.master
        if a.devices is not None:
            env["PADDLE_VISIBLE_DEVICES"] = a.devices
        return env

    def spawn_one(self, local_rank: int) -> subprocess.Popen:
        a = self.args
        os.makedirs(a.log_dir, exist_ok=True)
        log_path = os.path.join(a.log_dir, f"workerlog.{local_rank}")
        logf = open(log_path, "ab")
        cmd = [sys.executable, a.training_script] + list(a.training_script_args)
        return subprocess.Popen(cmd, env=self.build_env(local_rank),
                                stdout=logf, stderr=subprocess.STDOUT)

    def start(self):
        self.procs = [self.spawn_one(i) for i in range(self.args.nproc_per_node)]

    def watch(self) -> int:
        """Ref controllers/watcher.py: poll children; on failure either restart the
        failed ranks (elastic_level >= 1, up to max_restart) or tear down."""
        while True:
            time.sleep(0.5)
            states = [p.poll() for p in self.procs]
            if self.telemetry is not None:
                self._update_rank_gauges()
            if all(s == 0 for s in states):
                return 0
            failed = [i for i, s in enumerate(states) if s not in (None, 0)]
            if failed:
                if self.args.elastic_level >= 1 and self.restarts < self.args.max_restart:
                    self.restarts += 1
                    for i in failed:
                        self.procs[i] = self.spawn_one(i)
                    continue
                self.stop()
                return next(s for s in states if s not in (None, 0))

    def stop(self):
        if self.telemetry is not None:
            self.telemetry.stop()
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + 10
        for p in self.procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()


def launch(argv=None):
    args = parse_args(argv)
    ctl = CollectiveController(args)
    ctl.start()
    try:
        rc = ctl.watch()
    except KeyboardInterrupt:
        ctl.stop()
        rc = 130
    sys.exit(rc)
