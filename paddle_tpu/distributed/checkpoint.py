"""Distributed sharded checkpoint with reshard-on-load.

Reference analog: `distributed/auto_parallel/dist_saver.py` (per-rank sharded
save), `distributed/auto_parallel/converter.py` (merge + re-slice when the
parallel config changes between save and load), and
`fluid/incubate/checkpoint/auto_checkpoint.py:267` (periodic auto-checkpoint
keyed for job restart).

TPU-native design: every leaf of the state pytree is a (possibly sharded)
jax.Array.  Each process writes only the addressable shards it uniquely owns
(``replica_id == 0``) into its own ``volume_p{proc}.npz``; process 0 also
writes ``index.json`` mapping each leaf to its global shape/dtype and chunk
table (offset, shape, volume, key) plus a pickled pytree skeleton.  Loading
rebuilds each leaf with ``jax.make_array_from_callback`` under the *new*
mesh/sharding: every device slice requested by the new sharding is assembled
from whatever stored chunks overlap it.  A tp=2 checkpoint therefore restores
under tp=4 (or pp=2, or a single chip) with no separate converter pass — the
chunk table plays the role of the reference's Converter merge/slice machinery.

Integrity & commit protocol (fault-tolerance layer):

- every volume records a CRC32 + SHA-256 in ``index.json`` (or its process
  sidecar) and all files are written tmp + ``os.replace`` — a torn write
  leaves only an orphaned ``*.tmp`` file;
- a save is visible only once its ``COMMITTED`` marker lands (written last
  by process 0): ``latest_step`` scans for committed, unquarantined steps,
  so a save killed mid-write simply does not exist;
- ``load_state`` verifies volume checksums; a corrupt step is quarantined
  (a ``QUARANTINED`` marker records the reason) and, when the step was not
  explicitly requested, the loader falls back to the newest valid step;
- ``CheckpointManager`` retries transient I/O errors (ENOSPC/EIO…) with
  exponential backoff and its keep-last-k GC only ever deletes steps older
  than the k newest *valid* ones — it can never remove the only good
  checkpoint.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import shutil
import time
import zlib

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..observability import goodput as _goodput
from ..observability import metrics as _obs
from ..observability.spans import span as _span

# Checkpoint-protocol telemetry (README §Observability; every save/load/
# quarantine/GC decision leaves a countable trace for the operator).
_M_SAVES = _obs.counter(
    "checkpoint_saves_total", "Committed checkpoint saves (this process)")
_M_SAVE_FAILURES = _obs.counter(
    "checkpoint_save_failures_total",
    "Checkpoint saves that failed after exhausting retries")
_M_SAVE_SECONDS = _obs.histogram(
    "checkpoint_save_duration_seconds",
    "save_state wall time (serialize + digest + atomic publish)")
_M_SAVED_BYTES = _obs.counter(
    "checkpoint_saved_bytes_total",
    "Bytes of checkpoint volume data written by this process")
_M_LOADS = _obs.counter(
    "checkpoint_loads_total", "Successful checkpoint loads")
_M_LOAD_SECONDS = _obs.histogram(
    "checkpoint_load_duration_seconds",
    "load_state wall time (verify + assemble + reshard)")
_M_LOAD_FALLBACKS = _obs.counter(
    "checkpoint_load_fallbacks_total",
    "Loads that fell back past a corrupt/incomplete newest step")
_M_QUARANTINES = _obs.counter(
    "checkpoint_quarantines_total",
    "Checkpoint steps quarantined after failing verification")
_M_GC_DELETED = _obs.counter(
    "checkpoint_gc_deleted_total",
    "Checkpoint step dirs removed by the retention GC")

__all__ = [
    "save_state", "load_state", "latest_step", "valid_steps",
    "CheckpointManager", "CheckpointCorruptError",
    "save_train_state", "load_train_state",
]

_INDEX = "index.json"
_SKELETON = "skeleton.pkl"
_COMMITTED = "COMMITTED"
_QUARANTINED = "QUARANTINED"


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed integrity verification (bad checksum, missing or
    unreadable volume/index/skeleton, or a chunk-coverage gap).

    ``quarantinable`` distinguishes definite corruption (checksum mismatch,
    garbled files — safe to mark QUARANTINED forever) from findings that can
    also be a transient multi-host race (a volume/chunk another process is
    still writing): the loader falls back either way but only writes the
    permanent marker for the former."""

    def __init__(self, *args, quarantinable=True):
        super().__init__(*args)
        self.quarantinable = quarantinable


# ------------------------------------------------------------------ integrity
def _file_digests(path):
    crc = 0
    h = hashlib.sha256()
    size = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(1 << 20)
            if not block:
                break
            crc = zlib.crc32(block, crc)
            h.update(block)
            size += len(block)
    return {"crc32": f"{crc & 0xFFFFFFFF:08x}", "sha256": h.hexdigest(),
            "bytes": size}


def _atomic_write(path, data: bytes):
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def is_committed(ckpt):
    """True when the COMMITTED marker is present and not a de-commit
    tombstone (a re-save in progress rewrites the marker to
    ``{"resaving": true}`` instead of deleting it, so a kill mid-rewrite
    can never be mistaken for a committed — or legacy pre-marker — dir)."""
    p = os.path.join(ckpt, _COMMITTED)
    try:
        with open(p) as f:
            return not json.load(f).get("resaving")
    except FileNotFoundError:
        return False
    except (OSError, ValueError):
        return False  # unreadable marker: be conservative


def is_quarantined(ckpt):
    return os.path.exists(os.path.join(ckpt, _QUARANTINED))


def quarantine(ckpt, reason=""):
    """Mark a checkpoint dir as corrupt; discovery (`latest_step`,
    `valid_steps`, fallback loading) skips it from now on.  The data is kept
    on disk for forensics; GC removes it once enough newer valid steps
    exist."""
    try:
        _atomic_write(os.path.join(ckpt, _QUARANTINED),
                      json.dumps({"reason": str(reason),
                                  "time": time.time()}).encode())
        _M_QUARANTINES.inc()
    except OSError:
        pass  # quarantine is advisory; checksum verification still protects


# --------------------------------------------------------------------- pytree
class _Leaf:
    __slots__ = ("key",)

    def __init__(self, key):
        self.key = key


def _flatten(obj, prefix, out):
    """Flatten nested dict/list/tuple into {path: array-leaf}; returns skeleton."""
    if isinstance(obj, dict):
        return {k: _flatten(v, f"{prefix}/{k}" if prefix else str(k), out)
                for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        seq = [_flatten(v, f"{prefix}/{i}" if prefix else str(i), out)
               for i, v in enumerate(obj)]
        return type(obj)(seq)
    if hasattr(obj, "shape") and hasattr(obj, "dtype"):
        out[prefix] = obj
        return _Leaf(prefix)
    return obj  # plain scalar/str — lives in the skeleton


def _unflatten(skel, leaves):
    if isinstance(skel, _Leaf):
        return leaves[skel.key]
    if isinstance(skel, dict):
        return {k: _unflatten(v, leaves) for k, v in skel.items()}
    if isinstance(skel, (list, tuple)):
        return type(skel)(_unflatten(v, leaves) for v in skel)
    return skel


def _to_storable(data):
    """npz can't round-trip ml_dtypes (bfloat16/float8 come back as raw void):
    store such chunks as flat uint8 bytes; _from_storable reinterprets."""
    if data.dtype.kind == "V" or data.dtype.name.startswith(("bfloat", "float8")):
        return np.ascontiguousarray(data).reshape(-1).view(np.uint8)
    return data


def _from_storable(data, dtype, sizes):
    dtype = np.dtype(dtype)
    if data.dtype == np.uint8 and dtype != np.uint8:
        return data.view(dtype).reshape(sizes)
    return data


def _norm_index(index, shape):
    """Normalize a shard index (tuple of slices) to (starts, sizes)."""
    starts, sizes = [], []
    for sl, dim in zip(index, shape):
        lo, hi, _ = sl.indices(dim)
        starts.append(lo)
        sizes.append(hi - lo)
    return starts, sizes


# ----------------------------------------------------------------------- save
def _step_dir(path, step):
    return os.path.join(path, f"step_{int(step):010d}") if step is not None else path


def save_state(path, state, step=None, process_index=None,
               process_count=None, trace=None):
    """Write `state` (a pytree of arrays) as a sharded checkpoint
    (instrumented: `checkpoint_save_duration_seconds` + a span in the
    chrome trace; the body is `_save_state_impl`).  ``trace`` (a
    ``observability.tracing.Trace``) additionally lands the save as a
    span in that request/run trace and attaches the trace id to the
    duration histogram as an OpenMetrics exemplar."""
    with _span("checkpoint_save", _M_SAVE_SECONDS, trace=trace,
               attrs={"step": step} if step is not None else None):
        ckpt = _save_state_impl(path, state, step=step,
                                process_index=process_index,
                                process_count=process_count)
    _M_SAVES.inc()
    return ckpt


def _save_state_impl(path, state, step=None, process_index=None,
                     process_count=None):
    """Write `state` (a pytree of arrays) as a sharded checkpoint.

    Each process saves only shards it owns; callers on multi-host must call this
    on every process (the volumes are disjoint).  Returns the checkpoint dir.

    No cross-host barrier is taken: process 0's COMMITTED marker may land
    before a peer's volume/sidecar (a reader then hits a chunk-coverage
    gap, which is a non-quarantinable fallback, and the sidecar merge
    skips step-mismatched leftovers).  Multi-host callers wanting a hard
    guarantee should barrier (e.g. TCPStore.barrier) after save_state
    before relying on the step.
    """
    proc = jax.process_index() if process_index is None else process_index
    nprocs = jax.process_count() if process_count is None else process_count
    if step is None and (nprocs > 1 or proc > 0):
        # without a step there is no generation marker to tell a fresh sidecar
        # from a stale one left by a previous, wider save
        raise ValueError(
            "save_state(step=None) is single-process only; multi-host saves "
            "must pass a step so each save generation is distinguishable")
    ckpt = _step_dir(path, step)
    os.makedirs(ckpt, exist_ok=True)
    if proc == 0:
        # de-commit before touching any content: a save killed mid-write must
        # leave the dir invisible to discovery (and a re-save into a
        # quarantined dir rehabilitates it only by completing).  The
        # tombstone is written UNCONDITIONALLY (not just over an existing
        # marker): a marker-less dir with an index — committed v2, legacy,
        # or half-written — would otherwise pass for a legacy (pre-marker)
        # checkpoint if this save dies partway
        _atomic_write(os.path.join(ckpt, _COMMITTED),
                      json.dumps({"resaving": True}).encode())
        try:
            os.remove(os.path.join(ckpt, _QUARANTINED))
        except FileNotFoundError:
            pass

    leaves: dict = {}
    skel = _flatten(state, "", leaves)

    chunks = {}      # key -> np array to store in this process's volume
    index = {}       # leaf path -> {shape, dtype, chunks: [...]}
    vol_name = f"volume_p{proc:05d}.npz"
    for key, arr in leaves.items():
        if isinstance(arr, jax.Array):
            shards = [s for s in arr.addressable_shards if s.replica_id == 0]
            global_shape = arr.shape
        else:
            shards = None
            global_shape = tuple(np.asarray(arr).shape)

        entry = {"shape": list(global_shape),
                 "dtype": str(np.dtype(arr.dtype) if hasattr(arr, "dtype") else np.asarray(arr).dtype),
                 "chunks": []}
        if shards is None:
            if proc == 0:
                ck = f"{key}#0"
                chunks[ck] = _to_storable(np.asarray(arr))
                entry["chunks"].append({"volume": vol_name, "key": ck,
                                        "offset": [0] * len(global_shape),
                                        "sizes": list(global_shape)})
        else:
            seen = set()
            for i, sh in enumerate(shards):
                starts, sizes = _norm_index(sh.index, global_shape)
                sig = tuple(starts)
                if sig in seen:   # same slice on several local devices (replicated axis)
                    continue
                seen.add(sig)
                ck = f"{key}#{i}"
                chunks[ck] = _to_storable(np.asarray(sh.data))
                entry["chunks"].append({"volume": vol_name, "key": ck,
                                        "offset": starts, "sizes": sizes})
        index[key] = entry

    volumes = {}
    if chunks:
        vol_path = os.path.join(ckpt, vol_name)
        tmp_vol = vol_path + ".tmp.npz"  # np.savez appends .npz if absent
        np.savez(tmp_vol, **chunks)
        volumes[vol_name] = _file_digests(tmp_vol)
        os.replace(tmp_vol, vol_path)
        _M_SAVED_BYTES.inc(volumes[vol_name]["bytes"])

    if proc == 0:
        idx_path = os.path.join(ckpt, _INDEX)
        # drop stale artifacts from previous save generations.  A sidecar/
        # volume from a process index >= the CURRENT world size can only be
        # a leftover from a prior, wider generation (a replay after scale-
        # down, or a step=None re-save where nprocs==1 makes every foreign
        # file stale) — deleting by process index is race-free, unlike a
        # blanket purge, which could delete files current-generation peers
        # already published (no cross-host barrier orders us).  Sidecars
        # from procs < nprocs with a mismatched recorded step are likewise
        # stale; a same-step same-width prior generation is overwritten by
        # each peer's own atomic re-publish instead.
        def _proc_of(name, prefix, suffix):
            try:
                return int(name[len(prefix):-len(suffix)])
            except ValueError:
                return None

        for name in os.listdir(ckpt):
            full = os.path.join(ckpt, name)
            if ".tmp" in name:
                continue  # a peer's in-flight atomic write — never touch
            if name.startswith("index_p") and name.endswith(".json"):
                p = _proc_of(name, "index_p", ".json")
                if p is not None and p >= nprocs:
                    os.remove(full)
                    continue
                try:
                    with open(full) as f:
                        if json.load(f).get("step") != step:
                            os.remove(full)
                except (OSError, ValueError):
                    # unreadable != stale: sidecars are written atomically
                    # (tmp + rename), so this is a transient read race — leave
                    # it; _read_index skips mismatched/garbled sidecars anyway
                    pass
            elif name.startswith("volume_p") and name != vol_name and \
                    name.endswith(".npz"):
                p = _proc_of(name, "volume_p", ".npz")
                if p is not None and p >= nprocs:
                    os.remove(full)
        _atomic_write(idx_path, json.dumps(
            {"version": 2, "step": step, "leaves": index,
             "volumes": volumes}).encode())
        _atomic_write(os.path.join(ckpt, _SKELETON), pickle.dumps(skel))
        # commit marker LAST: only now does the checkpoint exist for
        # discovery (latest_step / valid_steps / fallback loading).  It
        # carries digests of the index/skeleton — the volumes' digests live
        # in the index, so every file in the protocol ends up verifiable
        _atomic_write(os.path.join(ckpt, _COMMITTED), json.dumps(
            {"step": step,
             "files": {_INDEX: _file_digests(idx_path),
                       _SKELETON: _file_digests(
                           os.path.join(ckpt, _SKELETON))}}).encode())
    elif chunks:
        # non-zero process: publish our chunk table so proc 0 can merge it, or —
        # shared-filesystem case — just append via a sidecar the loader also reads.
        side = os.path.join(ckpt, f"index_p{proc:05d}.json")
        _atomic_write(side, json.dumps(   # atomic: readers never see a partial
            {"step": step, "leaves": index, "volumes": volumes}).encode())
    return ckpt


# ----------------------------------------------------------------------- load
def _discoverable(d):
    """A dir counts for discovery/retention when it is a committed v2 step
    OR a legacy (pre-marker) checkpoint: new-code saves write the de-commit
    tombstone before any content, so a marker-less dir with an index can
    only have been written whole by the old format."""
    if is_quarantined(d):
        return False
    if os.path.exists(os.path.join(d, _COMMITTED)):
        return is_committed(d)  # tombstone (resaving) -> False
    return os.path.exists(os.path.join(d, _INDEX))


def valid_steps(path):
    """Sorted steps whose dirs completed their commit protocol (or predate
    it) and are not quarantined."""
    try:
        names = os.listdir(path)
    except OSError:
        return []
    out = []
    for name in names:
        if not name.startswith("step_"):
            continue
        try:
            s = int(name[5:])
        except ValueError:
            continue
        if _discoverable(os.path.join(path, name)):
            out.append(s)
    return sorted(out)


def latest_step(path):
    """Newest step that completed its commit protocol (a save killed
    mid-write never committed, so it is invisible here)."""
    steps = valid_steps(path)
    return steps[-1] if steps else None


class _VolumeCache:
    def __init__(self, ckpt, volmeta=None, verify=True):
        self.ckpt = ckpt
        self.volmeta = volmeta or {}
        self.verify = verify
        self._open = {}

    def get(self, volume, key):
        if volume not in self._open:
            path = os.path.join(self.ckpt, volume)
            meta = self.volmeta.get(volume)
            try:
                if self.verify and meta and "crc32" in meta:
                    got = _file_digests(path)  # one streaming pass, no slurp
                    for name in ("crc32", "sha256"):
                        if name in meta and got[name] != meta[name]:
                            raise CheckpointCorruptError(
                                f"checkpoint volume {volume} failed {name} "
                                f"verification (stored {meta[name]}, "
                                f"got {got[name]})")
                self._open[volume] = np.load(path)  # lazy per-chunk zip read
            except FileNotFoundError as e:
                # possibly another host still writing its volume — fall
                # back, but do not permanently quarantine
                raise CheckpointCorruptError(
                    f"checkpoint volume {volume} is missing",
                    quarantinable=False) from e
            except CheckpointCorruptError:
                raise
            except OSError as e:
                # transient media error (EIO and friends): fall back without
                # condemning data that may read fine on retry
                raise CheckpointCorruptError(
                    f"checkpoint volume {volume} could not be read: {e}",
                    quarantinable=False) from e
            except Exception as e:
                # the bytes were readable but are not a valid npz archive
                raise CheckpointCorruptError(
                    f"checkpoint volume {volume} is unreadable: {e}") from e
        try:
            return self._open[volume][key]
        except KeyError as e:
            raise CheckpointCorruptError(
                f"chunk {key} missing from volume {volume}") from e


def _read_index(ckpt):
    try:
        with open(os.path.join(ckpt, _INDEX)) as f:
            index = json.load(f)
    except FileNotFoundError:
        if not os.path.isdir(ckpt):
            raise
        # non-quarantinable: a dir without its index can be a first save
        # still in flight on another host — a stale QUARANTINED marker
        # written now could outlive the commit and hide a valid checkpoint
        raise CheckpointCorruptError(
            f"checkpoint dir {ckpt} has no {_INDEX}",
            quarantinable=False) from None
    except ValueError as e:
        raise CheckpointCorruptError(
            f"checkpoint index in {ckpt} is unreadable: {e}") from e
    leaves = index["leaves"]
    index.setdefault("volumes", {})
    # merge sidecar indices from other processes (shared filesystem); a sidecar
    # from a different save generation (mismatched step) is stale — skip it
    for name in sorted(os.listdir(ckpt)):
        if name.startswith("index_p") and name.endswith(".json"):
            try:
                with open(os.path.join(ckpt, name)) as f:
                    side_doc = json.load(f)
            except (OSError, ValueError):
                continue  # transient write race; chunk coverage check catches real gaps
            if side_doc.get("step") != index.get("step"):
                continue
            index["volumes"].update(side_doc.get("volumes", {}))
            side = side_doc["leaves"]
            for k, e in side.items():
                if k not in leaves:
                    leaves[k] = e
                    continue
                have = {tuple(c["offset"]) for c in leaves[k]["chunks"]}
                leaves[k]["chunks"] += [c for c in e["chunks"]
                                        if tuple(c["offset"]) not in have]
    return index


def _assemble(entry, req_slices, vols):
    """Assemble the requested slice of a leaf from overlapping stored chunks."""
    shape = entry["shape"]
    starts, sizes = _norm_index(req_slices, shape)
    out = np.empty(sizes, dtype=np.dtype(entry["dtype"]))
    covered = 0
    for ch in entry["chunks"]:
        off, csz = ch["offset"], ch["sizes"]
        lo = [max(s, o) for s, o in zip(starts, off)]
        hi = [min(s + z, o + c) for s, z, o, c in zip(starts, sizes, off, csz)]
        if any(h <= l for l, h in zip(lo, hi)):
            continue
        src = tuple(slice(l - o, h - o) for l, h, o in zip(lo, hi, off))
        dst = tuple(slice(l - s, h - s) for l, h, s in zip(lo, hi, starts))
        data = _from_storable(vols.get(ch["volume"], ch["key"]),
                              entry["dtype"], csz)
        out[dst] = data[src]
        covered += int(np.prod([h - l for l, h in zip(lo, hi)]))
    want = int(np.prod(sizes)) if sizes else 1
    if covered < want:
        # a gap can mean corruption OR a multi-host save whose sidecars are
        # still landing — fall back, but leave no permanent quarantine
        raise CheckpointCorruptError(
            f"checkpoint chunk table does not cover the requested slice "
            f"({covered}/{want} elements) — was the checkpoint written by "
            f"all hosts?", quarantinable=False)
    return out


def load_state(path, step=None, shardings=None, template=None, verify=True,
               return_step=False, trace=None):
    """Load a checkpoint, resharding each leaf onto a new mesh if asked.

    ``shardings`` may be: None (leaves come back as host jnp arrays), a pytree
    matching the saved structure whose leaves are ``jax.sharding.Sharding`` or
    None, or a callable ``(leaf_path, shape) -> Sharding | None``.

    Volume checksums are verified (``verify=False`` skips).  A corrupt step
    is quarantined; when ``step`` was not explicitly requested the loader
    falls back to the next-newest valid step instead of failing.  With
    ``return_step=True`` the result is ``(state, loaded_step)`` — callers
    resuming a step counter MUST use the returned step, not a prior
    ``latest_step()`` read: fallback may have loaded an older one.
    """
    explicit = step is not None
    if explicit:
        candidates = [step]
    else:
        vs = valid_steps(path)
        # no step dirs: a direct (step-less) checkpoint dir
        candidates = vs[::-1] if vs else [None]
    last_err = None
    for s in candidates:
        ckpt = _step_dir(path, s)
        try:
            with _span("checkpoint_load", _M_LOAD_SECONDS, trace=trace,
                       attrs={"step": s} if s is not None else None):
                state = _load_from_dir(ckpt, shardings, verify)
            _M_LOADS.inc()
            return (state, s) if return_step else state
        except FileNotFoundError as e:
            # the candidate dir vanished (e.g. concurrent GC): try the next
            last_err = e
            if explicit:
                raise
            _M_LOAD_FALLBACKS.inc()
        except CheckpointCorruptError as e:
            last_err = e
            if s is not None and e.quarantinable and os.path.isdir(ckpt):
                quarantine(ckpt, str(e))
            if explicit:
                raise
            _M_LOAD_FALLBACKS.inc()
    raise CheckpointCorruptError(
        f"no loadable checkpoint under {path}: {last_err}") from last_err


def _verify_metadata(ckpt):
    """Check index/skeleton digests recorded in the COMMITTED marker.
    Legacy dirs and in-flight saves carry none — nothing to check there;
    the marker itself needs no digest (it is tiny, atomic, and a garbled
    one already reads as not-committed)."""
    try:
        with open(os.path.join(ckpt, _COMMITTED)) as f:
            marker = json.load(f)
    except (OSError, ValueError):
        return
    for name, meta in (marker.get("files") or {}).items():
        path = os.path.join(ckpt, name)
        try:
            got = _file_digests(path)
        except FileNotFoundError:
            raise CheckpointCorruptError(
                f"checkpoint file {name} is missing from committed "
                f"dir {ckpt}") from None
        except OSError as e:
            raise CheckpointCorruptError(
                f"checkpoint file {name} could not be read: {e}",
                quarantinable=False) from e
        for dig in ("crc32", "sha256"):
            if dig in meta and got[dig] != meta[dig]:
                raise CheckpointCorruptError(
                    f"checkpoint file {name} failed {dig} verification "
                    f"(stored {meta[dig]}, got {got[dig]})")


def _load_from_dir(ckpt, shardings, verify):
    # a de-commit tombstone means an interrupted re-save left mixed-
    # generation files behind: refuse even explicit loads — discovery
    # already reports this dir as nonexistent, and its index/skeleton may
    # disagree.  (Non-quarantinable: completing the re-save heals it.)
    if os.path.exists(os.path.join(ckpt, _COMMITTED)) \
            and not is_committed(ckpt):
        raise CheckpointCorruptError(
            f"checkpoint dir {ckpt} is de-committed (a re-save was "
            f"interrupted); re-save it or restore another step",
            quarantinable=False)
    if verify:
        _verify_metadata(ckpt)
    index = _read_index(ckpt)
    try:
        with open(os.path.join(ckpt, _SKELETON), "rb") as f:
            skel = pickle.load(f)
    except FileNotFoundError:
        raise CheckpointCorruptError(
            f"checkpoint dir {ckpt} has no {_SKELETON}",
            quarantinable=False) from None  # may still be landing (see index)
    except Exception as e:
        raise CheckpointCorruptError(
            f"checkpoint skeleton in {ckpt} is unreadable: {e}") from e

    shard_leaves = {}
    if shardings is not None and not callable(shardings):
        def _walk(obj, prefix):
            if isinstance(obj, jax.sharding.Sharding):
                shard_leaves[prefix] = obj
            elif isinstance(obj, dict):
                for k, v in obj.items():
                    _walk(v, f"{prefix}/{k}" if prefix else str(k))
            elif isinstance(obj, (list, tuple)):
                for i, v in enumerate(obj):
                    _walk(v, f"{prefix}/{i}" if prefix else str(i))
        _walk(shardings, "")

    vols = _VolumeCache(ckpt, volmeta=index.get("volumes"), verify=verify)
    leaves = {}
    for key, entry in index["leaves"].items():
        shape = tuple(entry["shape"])
        dtype = np.dtype(entry["dtype"])
        if callable(shardings):
            sh = shardings(key, shape)
        else:
            sh = shard_leaves.get(key)
        if isinstance(sh, _Leaf):   # sharding pytree had a plain array here
            sh = None
        if sh is None:
            full = _assemble(entry, tuple(slice(0, d) for d in shape), vols)
            leaves[key] = jnp.asarray(full)
        else:
            leaves[key] = jax.make_array_from_callback(
                shape, sh, lambda idx, e=entry: _assemble(e, idx, vols))
    return _unflatten(skel, leaves)


# ------------------------------------------------------------------- manager
class CheckpointManager:
    """Step-indexed checkpoint dir with retention (ref auto_checkpoint.py:267
    TrainEpochRange: periodic snapshot + restore-latest on job restart).

    Saves retry transient I/O errors (ENOSPC/EIO/EAGAIN…) with exponential
    backoff (``retry`` is a ``fault_tolerance.RetryPolicy``; the atomic
    commit protocol makes a failed attempt invisible, so retries are safe).
    GC keeps the last ``keep`` *valid* steps: uncommitted or quarantined
    dirs never count toward retention, and the only good checkpoint is
    never deleted.
    """

    def __init__(self, path, keep=3, save_interval=1, retry=None):
        import threading

        from .fault_tolerance import RetryPolicy

        self.path = path
        self.keep = keep
        self.save_interval = max(1, int(save_interval))
        self.retry = retry if retry is not None else RetryPolicy()
        os.makedirs(path, exist_ok=True)
        # async-save machinery: ONE worker thread drains a FIFO queue, so
        # overlapping async saves serialize in submission order (a second
        # save queues behind the first — they can never interleave their
        # tmp+rename commits)
        self._async_cv = threading.Condition()
        self._async_queue = []
        self._async_pending = 0
        self._async_thread = None
        self._async_errors = []

    def should_save(self, step):
        return step % self.save_interval == 0

    def save(self, step, state, force=False, trace=None, async_=False):
        """Save ``state`` at ``step`` (subject to ``save_interval`` unless
        ``force``).

        ``async_=True`` moves the serialize + tmp-write + commit onto a
        background worker and returns a ``concurrent.futures.Future`` of
        the checkpoint path immediately — training continues while the
        bytes land.  The atomic tmp+rename/COMMITTED protocol is
        unchanged (it runs verbatim on the worker), so a process killed
        mid-async-save leaves an uncommitted dir that ``latest_step`` /
        ``restore`` never see.  Overlapping async saves queue FIFO behind
        each other; ``wait()`` joins them all and surfaces the first
        failure.  The ``state`` pytree is captured by reference — jax
        arrays are immutable so this is safe, but host numpy buffers must
        not be mutated in place before the save completes.
        """
        if not force and not self.should_save(step):
            return None
        if not async_:
            # goodput ledger: a sync save blocks the train loop for its
            # whole write — the full span is checkpoint_save time
            with _goodput.active_section("train", "checkpoint_save"):
                return self._save_sync(step, state, trace)
        import threading
        from concurrent.futures import Future

        # goodput ledger: of an async save only this enqueue (and a later
        # wait()) blocks the caller; the worker's write overlaps training
        with _goodput.active_section("train", "checkpoint_save"):
            fut = Future()
            with self._async_cv:
                self._async_queue.append((step, state, trace, fut))
                self._async_pending += 1
                # the worker unregisters itself (sets _async_thread=None)
                # UNDER the condition before exiting, so this check can
                # never race a dying worker into dropping the job
                if self._async_thread is None:
                    self._async_thread = threading.Thread(
                        target=self._async_worker, daemon=True,
                        name="paddle-tpu-ckpt-save")
                    self._async_thread.start()
        return fut

    def _save_sync(self, step, state, trace=None):
        from .fault_tolerance import retry_call

        try:
            ckpt = retry_call(save_state, self.path, state, step=step,
                              policy=self.retry, trace=trace)
        except Exception:
            _M_SAVE_FAILURES.inc()
            raise
        if jax.process_index() == 0:
            self._gc()
        return ckpt

    def _async_worker(self):
        while True:
            with self._async_cv:
                if not self._async_queue:
                    self._async_thread = None
                    return
                step, state, trace, fut = self._async_queue.pop(0)
            try:
                ckpt = self._save_sync(step, state, trace)
            except BaseException as e:
                fut.set_exception(e)
                with self._async_cv:
                    self._async_errors.append(e)
                    self._async_pending -= 1
                    self._async_cv.notify_all()
            else:
                fut.set_result(ckpt)
                with self._async_cv:
                    self._async_pending -= 1
                    self._async_cv.notify_all()

    def wait(self, timeout=None):
        """Join every outstanding async save.  Raises the FIRST async
        failure (then forgets it — the next wait() starts clean) and
        returns True; returns False when ``timeout`` elapses with saves
        still in flight."""
        # goodput ledger: the join is the async save's other blocking slice
        with _goodput.active_section("train", "checkpoint_save"):
            with self._async_cv:
                done = self._async_cv.wait_for(
                    lambda: self._async_pending == 0, timeout=timeout)
                if not done:
                    return False
                if self._async_errors:
                    err, self._async_errors = self._async_errors[0], []
                    raise err
        return True

    def _gc(self):
        """Delete steps older than the ``keep`` newest VALID ones.  Partial
        (uncommitted) and quarantined dirs older than the retention window go
        too; anything newer than the oldest kept valid step is left alone
        (it may be a concurrent save in flight)."""
        if not self.keep:
            return
        valid = self.valid_steps()
        if not valid:
            return  # nothing provably good: delete nothing
        cutoff = valid[-self.keep:][0]
        for s in self.all_steps():
            if s < cutoff:
                shutil.rmtree(os.path.join(self.path, f"step_{s:010d}"),
                              ignore_errors=True)
                _M_GC_DELETED.inc()

    def all_steps(self):
        out = []
        for name in os.listdir(self.path):
            if name.startswith("step_"):
                try:
                    out.append(int(name[5:]))
                except ValueError:
                    pass
        return sorted(out)

    def valid_steps(self):
        return valid_steps(self.path)

    def latest_step(self):
        return latest_step(self.path)

    def restore(self, step=None, shardings=None, return_step=False,
                trace=None):
        return load_state(self.path, step=step, shardings=shardings,
                          return_step=return_step, trace=trace)


# --------------------------------------------------- train-state convenience
def _model_state(model, optimizer=None, train_step=None, step=None):
    params, buffers = model.functional_state()
    state = {"params": dict(params), "buffers": dict(buffers),
             "meta": {"step": step}}
    if train_step is not None and getattr(train_step, "_opt_state", None) is not None:
        state["opt_state"] = train_step._opt_state
        state["meta"]["step_count"] = train_step.optimizer._step_count
    elif optimizer is not None:
        named = {id(p): k for k, p in model.named_parameters()}
        state["opt_state"] = {
            named[pid]: st for pid, st in optimizer._accumulators.items()
            if pid in named
        }
        state["meta"]["step_count"] = optimizer._step_count
    return state


def save_train_state(path, model, optimizer=None, train_step=None, step=None):
    """Sharded save of model params/buffers + optimizer state.

    Works for the eager optimizer (`_accumulators`) and for
    ShardedTrainStep-managed state (arrays stay sharded; each process writes
    its own shards).
    """
    return save_state(path, _model_state(model, optimizer, train_step, step),
                      step=step)


def load_train_state(path, model, optimizer=None, train_step=None, step=None):
    """Restore params/buffers (+optimizer state) into `model`, resharding onto
    `train_step`'s mesh if given (the tp=2 → tp=4 path)."""
    shardings = None
    if train_step is not None:
        pshard, oshard = train_step._specs()
        rep = NamedSharding(train_step.mesh, P())

        def shardings(key, shape):
            if key.startswith("params/"):
                return pshard.get(key[len("params/"):], rep)
            if key.startswith("buffers/"):
                return rep
            if key.startswith("opt_state/"):
                rest = key[len("opt_state/"):]
                name = rest.split("/")[0]
                sh = oshard.get(name)
                named = dict(model.named_parameters())
                if sh is not None and name in named and \
                        tuple(shape) == tuple(named[name]._value.shape):
                    return sh
                return rep
            return None

    state = load_state(path, step=step, shardings=shardings)
    model.load_functional_state(state.get("params"), state.get("buffers"))
    meta = state.get("meta", {})
    if train_step is not None and "opt_state" in state:
        train_step._opt_state = state["opt_state"]
        if train_step._jitted is None:
            # params were just rebound host-side; _init will re-place them
            pass
        train_step.optimizer._step_count = int(meta.get("step_count", 0) or 0)
    elif optimizer is not None and "opt_state" in state:
        named = dict(model.named_parameters())
        for name, st in state["opt_state"].items():
            if name in named:
                optimizer._accumulators[id(named[name])] = st
        optimizer._step_count = int(meta.get("step_count", 0) or 0)
    return meta
