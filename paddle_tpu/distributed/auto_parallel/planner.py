"""Parallel-plan search (ref: distributed/auto_parallel/planner.py + tuner/ —
the reference searches dist-attr assignments over profiled costs; here the
search space is the (dp, mp, pp, sharding, microbatches) factorization of the
device count, ranked by the cost_model roofline and filtered by HBM).
"""
from __future__ import annotations

import numpy as np

from .cost_model import ClusterSpec, CostEstimate, ModelSpec, ParallelConfig, estimate

__all__ = ["Planner", "plan", "model_spec_from_layer"]


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


class Planner:
    """Enumerate feasible configs, rank by estimated step time
    (ref planner.py Planner.plan)."""

    def __init__(self, model: ModelSpec, cluster: ClusterSpec | None = None,
                 max_mp=8, max_pp=None, microbatch_options=(1, 2, 4, 8, 16, 32, 64)):
        self.model = model
        self.cluster = cluster or ClusterSpec()
        self.max_mp = max_mp
        self.max_pp = max_pp or model.n_layers
        self.microbatch_options = microbatch_options

    def candidates(self, n_devices: int):
        out = []
        for mp in _divisors(n_devices):
            if mp > self.max_mp or self.model.hidden % mp:
                continue
            for pp in _divisors(n_devices // mp):
                if pp > self.max_pp or self.model.n_layers % pp:
                    continue
                rest = n_devices // (mp * pp)
                for sharding in _divisors(rest):
                    dp = rest // sharding
                    stages = (2, 3) if sharding > 1 else (0,)
                    for m in self.microbatch_options:
                        if self.model.global_batch % (dp * sharding * m):
                            continue
                        if pp == 1 and m > 1:
                            continue  # microbatching only matters under pp here
                        for stage in stages:
                            out.append(ParallelConfig(dp=dp, mp=mp, pp=pp,
                                                      sharding=sharding,
                                                      microbatches=m,
                                                      zero_stage=stage))
        return out

    def plan(self, n_devices: int, top_k: int = 1):
        """Best config(s) by estimated step time; raises if nothing fits HBM."""
        ests = [estimate(self.model, self.cluster, c)
                for c in self.candidates(n_devices)]
        feasible = [e for e in ests if e.feasible]
        if not feasible:
            tight = min(ests, key=lambda e: e.mem_bytes) if ests else None
            raise RuntimeError(
                "no parallel config fits in device memory for "
                f"{n_devices} devices"
                + (f" (closest: {tight.config} at {tight.mem_bytes/1e9:.1f} GB)"
                   if tight else ""))
        feasible.sort(key=lambda e: e.t_step)
        return feasible[0] if top_k == 1 else feasible[:top_k]

    def plan_measured(self, n_devices: int, top_k: int = 3, measure_fn=None,
                      steps: int = 2):
        """Analytic shortlist -> compile + TIME each candidate on the
        attached devices, pick the measured winner (ref
        auto_parallel/tuner/: the reference profiles candidate dist-attrs
        instead of trusting the cost model).  `measure_fn(config) -> fn()`
        returns a zero-arg callable running ONE real step under `config`'s
        mesh; the default builds a scaled-down proxy transformer via
        ShardedTrainStep (pp==1 configs — supply measure_fn for pipelines).
        Returns the winning CostEstimate with `.t_measured` attached;
        every candidate carries its measured time in `.t_measured` too."""
        from ...incubate.autotune import measure_callable

        cands = self.plan(n_devices, top_k=top_k)
        if not isinstance(cands, list):
            cands = [cands]
        if measure_fn is None:
            measure_fn = _default_proxy_measure(self.model, n_devices)
        for est in cands:
            try:
                fn = measure_fn(est.config)
                est.t_measured = measure_callable(fn, steps=steps)
            except Exception as e:  # unmeasurable candidate: analytic time stands
                est.t_measured = float("inf")
                est.measure_error = repr(e)[:200]
        measured = [e for e in cands if np.isfinite(e.t_measured)]
        if not measured:
            # nothing measurable: the analytic winner stands, with no
            # fabricated wall time on it
            cands[0].t_measured = None
            return cands[0]
        return min(measured, key=lambda e: e.t_measured)


def _default_proxy_measure(model: ModelSpec, n_devices: int):
    """Build a measure_fn running a real ShardedTrainStep on a scaled-down
    transformer with the model's shape ratios (pp==1 configs)."""

    def make(config):
        if config.pp != 1:
            raise ValueError("default proxy measures pp==1 configs only")
        import paddle_tpu as paddle
        from .. import build_mesh
        from ..sharded_train_step import ShardedTrainStep
        from ...models import LlamaConfig, LlamaForCausalLM

        mesh = build_mesh(dp=config.dp, mp=config.mp, sharding=config.sharding)
        hidden = max(64, min(256, model.hidden // 16)) // config.mp * config.mp
        cfg = LlamaConfig.tiny(
            tensor_parallel=(config.mp > 1), hidden_size=hidden,
            intermediate_size=hidden * 2, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=4, vocab_size=512,
            max_position_embeddings=64, use_flash_attention=False)
        paddle.seed(0)
        m = LlamaForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=m.parameters())

        def loss_fn(ids, labels):
            loss, _ = m(ids, labels=labels)
            return loss

        step = ShardedTrainStep(m, loss_fn, opt, mesh,
                                zero_stage=config.zero_stage or 0)
        batch = max(config.dp * config.sharding * 2, 2)
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 512, (batch, 32)).astype(np.int32))

        def run():
            loss = step(ids, ids)
            float(loss.item())

        return run

    return make


def model_spec_from_layer(model, seq_len, global_batch, vocab=32000,
                          n_layers=None, hidden=None):
    """Derive a ModelSpec from an nn.Layer (params counted exactly; layer
    count/hidden taken from kwargs or guessed from the parameter shapes)."""
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    if hidden is None:
        # most common square weight dim is a good hidden-size proxy
        from collections import Counter

        dims = Counter()
        for p in model.parameters():
            if len(p.shape) == 2 and p.shape[0] == p.shape[1]:
                dims[int(p.shape[0])] += 1
        hidden = dims.most_common(1)[0][0] if dims else max(
            (int(s) for p in model.parameters() for s in p.shape), default=1024)
    if n_layers is None:
        names = [n for n, _ in model.named_parameters()]
        idx = set()
        for n in names:
            for part in n.split("."):
                if part.isdigit():
                    idx.add(int(part))
        n_layers = (max(idx) + 1) if idx else 1
    return ModelSpec(n_params=float(n_params), n_layers=int(n_layers),
                     hidden=int(hidden), seq_len=int(seq_len),
                     global_batch=int(global_batch), vocab=vocab)


def plan(model_spec: ModelSpec, n_devices: int, cluster: ClusterSpec | None = None,
         top_k: int = 1):
    """One-call entry: best ParallelConfig for `model_spec` on `n_devices`."""
    return Planner(model_spec, cluster).plan(n_devices, top_k=top_k)
