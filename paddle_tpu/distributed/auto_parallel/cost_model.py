"""Analytical cost model for parallel-plan search (ref:
distributed/auto_parallel/cost_model.py + cost/ — the reference estimates
per-op costs from profiled tables; here a TPU roofline over FLOPs, HBM and ICI
traffic, which is how plans are actually chosen on pods: compute time vs
collective time vs the pipeline bubble).

All sizes are per training step.  The model is deliberately coarse — its job
is to RANK (dp, mp, pp, sharding) configs and reject infeasible ones, not to
predict milliseconds; measured MFU on one v5e chip (bench.py) calibrates the
`mxu_efficiency` default.
"""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class ClusterSpec:
    """One accelerator generation (defaults: TPU v5e)."""

    peak_flops: float = 197e12        # bf16 per chip
    hbm_bytes: float = 16e9
    hbm_bw: float = 819e9
    ici_bw: float = 90e9              # per-direction per-link, bytes/s
    dcn_bw: float = 6.25e9            # inter-slice
    mxu_efficiency: float = 0.6       # measured: 0.6 MFU on v5e (bench.py)


@dataclasses.dataclass
class ModelSpec:
    """A decoder-style transformer training job."""

    n_params: float
    n_layers: int
    hidden: int
    seq_len: int
    global_batch: int
    vocab: int = 32000
    dtype_bytes: int = 2              # bf16 weights/activations
    optimizer_state_bytes_per_param: int = 8   # AdamW: 2 moments in f32
    remat: bool = True                # activation recompute (strategy.recompute)

    @property
    def tokens(self):
        return self.global_batch * self.seq_len

    @property
    def flops_per_step(self):
        # 6N per token (fwd 2N + bwd 4N) + causal attention matmuls
        attn = 3 * 2 * self.global_batch * self.seq_len ** 2 * self.hidden \
            * self.n_layers / 2
        return 6.0 * self.n_params * self.tokens + attn


@dataclasses.dataclass
class ParallelConfig:
    dp: int = 1
    mp: int = 1
    pp: int = 1
    sharding: int = 1
    microbatches: int = 1
    zero_stage: int = 2               # ZeRO stage applied over the sharding axis

    @property
    def n_devices(self):
        return self.dp * self.mp * self.pp * self.sharding

    def as_dict(self):
        return {"dp_degree": self.dp, "mp_degree": self.mp,
                "pp_degree": self.pp, "sharding_degree": self.sharding}


@dataclasses.dataclass
class CostEstimate:
    config: ParallelConfig
    t_compute: float
    t_dp_comm: float
    t_mp_comm: float
    t_pp_bubble: float
    t_pp_p2p: float
    mem_bytes: float
    feasible: bool
    reason: str = ""
    # filled by Planner.plan_measured: wall time of one real step (seconds)
    t_measured: float | None = None
    measure_error: str = ""

    @property
    def t_step(self):
        # mp comm serializes with compute; dp grad sync overlaps the backward
        # (count the non-overlappable half); bubble scales the whole pipe
        overlapped_dp = max(self.t_dp_comm - 0.5 * self.t_compute, 0.0)
        base = self.t_compute + self.t_mp_comm + self.t_pp_p2p + overlapped_dp
        return base * (1.0 + self.t_pp_bubble)


def estimate(model: ModelSpec, cluster: ClusterSpec, cfg: ParallelConfig) -> CostEstimate:
    """Roofline the step time of `cfg` and check it fits in HBM."""
    d = cfg
    B = model.dtype_bytes
    data_ways = d.dp * d.sharding

    # ---- compute: model flops split over every axis (+1/3 recompute pass
    # when remat is on)
    recompute_mult = 4.0 / 3.0 if model.remat else 1.0
    t_compute = model.flops_per_step * recompute_mult / d.n_devices / (
        cluster.peak_flops * cluster.mxu_efficiency)

    # ---- dp/sharding gradient sync: ring all-reduce (or reduce-scatter+
    # all-gather under ZeRO — same bytes) of this shard's gradients over ICI
    shard_params = model.n_params / (d.mp * d.pp)
    w = data_ways
    t_dp = (2.0 * shard_params * B * (w - 1) / w / cluster.ici_bw) if w > 1 else 0.0

    # ---- tensor parallel: 2 all-reduces of activations per layer fwd, 2 bwd
    # (Megatron pattern), on this device's microbatch tokens
    if d.mp > 1:
        local_tokens = model.tokens / data_ways / max(d.microbatches, 1)
        act_bytes = local_tokens * model.hidden * B
        per_layer = 4.0 * 2.0 * act_bytes * (d.mp - 1) / d.mp / cluster.ici_bw
        layers_per_stage = model.n_layers / d.pp
        t_mp = per_layer * layers_per_stage * max(d.microbatches, 1)
    else:
        t_mp = 0.0

    # ---- pipeline: bubble fraction (pp-1)/m and per-tick boundary transfers
    if d.pp > 1:
        m = max(d.microbatches, 1)
        bubble = (d.pp - 1) / m
        local_tokens = model.tokens / data_ways / m
        t_p2p = 2.0 * (d.pp - 1) * local_tokens * model.hidden * B \
            * m / d.pp / cluster.ici_bw
    else:
        bubble, t_p2p = 0.0, 0.0

    # ---- memory per device: what's sharded depends on the ZeRO STAGE, not
    # the sharding degree (stage 1: opt state; 2: +grads; 3: +params)
    params_dev = model.n_params / (d.mp * d.pp)
    shard_ways = d.sharding if d.sharding > 1 else 1
    stage = d.zero_stage if shard_ways > 1 else 0
    params_mem = params_dev * B / (shard_ways if stage >= 3 else 1)
    grads_mem = params_dev * B / (shard_ways if stage >= 2 else 1)
    opt_mem = params_dev * model.optimizer_state_bytes_per_param / (
        shard_ways if stage >= 1 else 1)
    # activation footprint per token per layer: ~14*hidden bytes without
    # remat; with remat only the layer-boundary activations (~2*hidden) are
    # kept and the rest is recomputed in backward
    local_tokens_mb = model.tokens / data_ways / max(d.microbatches, 1)
    act_factor = 2.0 if model.remat else 14.0
    act_mem = act_factor * model.hidden * B * local_tokens_mb \
        * (model.n_layers / d.pp)
    inflight = min(d.pp, max(d.microbatches, 1)) if d.pp > 1 else 1
    mem = params_mem + opt_mem + grads_mem + act_mem * inflight

    feasible = mem <= cluster.hbm_bytes
    reason = "" if feasible else (
        f"needs {mem/1e9:.1f} GB/device > {cluster.hbm_bytes/1e9:.0f} GB HBM")
    return CostEstimate(cfg, t_compute, t_dp, t_mp, bubble, t_p2p, mem,
                        feasible, reason)
