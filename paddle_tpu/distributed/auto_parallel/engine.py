"""auto_parallel Engine (ref: distributed/auto_parallel/engine.py:53,95,378).

The reference Engine takes a serial model + loss + optimizer and a DistributedStrategy,
runs completion/partition/reshard passes, and executes per-rank programs.  TPU-native:
the Engine compiles ONE SPMD training/eval step over the ProcessMesh's jax Mesh —
parameter shardings come from layer annotations + shard_tensor markers, batch sharding
from `data_spec`, and XLA GSPMD does completion/partition/reshard.
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...tensor.tensor import Tensor
from ...autograd import tape
from ...framework import random as _random
from ..sharding_ctx import mesh_scope
from ..sharded_train_step import ShardedTrainStep
from .process_mesh import ProcessMesh, get_current_process_mesh


class Engine:
    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 strategy=None, process_mesh=None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.metrics = (metrics if isinstance(metrics, (list, tuple))
                        else [metrics]) if metrics is not None else []
        self.strategy = strategy
        self._process_mesh = process_mesh or get_current_process_mesh()
        self._train_step = None
        self._eval_fn = None
        self.history = {"loss": []}

    # ------------------------------------------------------------------ mesh
    def _jax_mesh(self) -> Mesh:
        tuned = getattr(self, "_tuned_mesh", None)
        if tuned is not None:
            return tuned
        if self._process_mesh is not None:
            return self._process_mesh.to_jax_mesh()
        hc = getattr(self.strategy, "hybrid_configs", None) if self.strategy else None
        if hc:
            from ..topology import build_mesh

            return build_mesh(dp=hc.get("dp_degree", 1), mp=hc.get("mp_degree", 1),
                              pp=hc.get("pp_degree", 1),
                              sharding=hc.get("sharding_degree", 1))
        # default: pure data parallel over all devices
        devs = np.array(jax.devices())
        return Mesh(devs.reshape(len(devs)), ("dp",))

    def _batch_spec(self, mesh: Mesh):
        data_axes = tuple(a for a in ("dp", "sharding") if a in mesh.axis_names
                          and mesh.shape[a] > 1)
        if data_axes:
            return P(data_axes)
        # generic ProcessMesh: shard the batch over the first mesh dim
        first = mesh.axis_names[0]
        return P(first) if mesh.shape[first] > 1 else P()

    # ------------------------------------------------------------------ train
    def prepare(self, inputs_spec=None, labels_spec=None, mode="train"):
        """Ref engine.py:378 — build the compiled step lazily; kept for API parity."""
        return self

    def tune(self, seq_len, global_batch, n_devices=None, top_k=3,
             measure=True):
        """Pick the parallel plan for THIS engine's model (ref
        auto_parallel/tuner/): analytic shortlist from the cost model, then
        — with measure=True — each candidate compiled + timed on the
        attached devices and the measured winner adopted as the engine's
        process mesh.  Returns the winning CostEstimate."""
        from .planner import Planner, model_spec_from_layer

        n = n_devices or len(jax.devices())
        spec = model_spec_from_layer(self.model, seq_len=seq_len,
                                     global_batch=global_batch)
        planner = Planner(spec)
        best = (planner.plan_measured(n, top_k=top_k) if measure
                else planner.plan(n))
        c = best.config
        from .. import build_mesh

        self._process_mesh = None
        self._tuned_mesh = build_mesh(dp=c.dp, mp=c.mp, pp=c.pp,
                                      sharding=c.sharding)
        # compiled steps are mesh-bound: force a rebuild on the tuned mesh
        self._train_step = None
        self._eval_fn = None
        return best

    def _ensure_train_step(self):
        if self._train_step is None:
            mesh = self._jax_mesh()

            def loss_fn(x, y):
                out = self.model(x)
                return self.loss(out, y), out

            zero = 0
            if self.strategy is not None and getattr(self.strategy, "sharding", False):
                zero = int(getattr(self.strategy, "sharding_configs", {}).get("stage", 2))
            self._train_step = ShardedTrainStep(self.model, loss_fn, self.optimizer,
                                                mesh, batch_spec=self._batch_spec(mesh),
                                                zero_stage=zero)
        return self._train_step

    def fit(self, train_data=None, epochs=1, batch_size=1, steps_per_epoch=None,
            log_freq=10, verbose=1, shuffle=True, **kwargs):
        """Ref engine.py fit — train over a Dataset/DataLoader with the SPMD step."""
        from ...io import DataLoader, Dataset

        loader = (DataLoader(train_data, batch_size=batch_size, shuffle=shuffle,
                             drop_last=True)
                  if isinstance(train_data, Dataset) else train_data)
        step_fn = self._ensure_train_step()
        logs = {}
        for epoch in range(epochs):
            for step, batch in enumerate(loader):
                if steps_per_epoch is not None and step >= steps_per_epoch:
                    break
                x, y = (batch[0], batch[1]) if isinstance(batch, (list, tuple)) else (batch, None)
                out = step_fn(x, y)
                loss = out[0] if isinstance(out, tuple) else out
                lf = float(loss.item())
                self.history["loss"].append(lf)
                logs = {"epoch": epoch, "step": step, "loss": lf}
                if verbose and step % log_freq == 0:
                    print(f"[autoparallel] epoch {epoch} step {step} loss {lf:.5f}")
        return logs

    # ------------------------------------------------------------------ eval
    def _ensure_eval_fn(self):
        if self._eval_fn is None:
            mesh = self._jax_mesh()
            model = self.model
            loss_obj = self.loss
            bspec = self._batch_spec(mesh)

            def eval_step(params, buffers, key, x, y):
                with _random.rng_key_scope(key):
                    restore = model.bind_functional_state(params, buffers)
                    try:
                        with tape.no_grad():
                            out = model(Tensor(x, stop_gradient=True))
                            loss = (loss_obj(out, Tensor(y, stop_gradient=True))
                                    if loss_obj is not None else None)
                    finally:
                        restore()
                return (out._value, loss._value if loss is not None else None)

            rep = NamedSharding(mesh, P())
            bs = NamedSharding(mesh, bspec)
            jitted = jax.jit(eval_step, in_shardings=(None, None, rep, bs, bs))

            def run(x, y):
                with mesh_scope(mesh):
                    params, buffers = model.functional_state()
                    return jitted(params, buffers, _random.get_rng_key(), x, y)

            self._eval_fn = run
        return self._eval_fn

    def evaluate(self, valid_data=None, batch_size=1, steps=None, verbose=0, **kwargs):
        from ...io import DataLoader, Dataset

        loader = (DataLoader(valid_data, batch_size=batch_size, drop_last=True)
                  if isinstance(valid_data, Dataset) else valid_data)
        self.model.eval()
        fn = self._ensure_eval_fn()
        losses = []
        for m in self.metrics:
            m.reset()
        for step, batch in enumerate(loader):
            if steps is not None and step >= steps:
                break
            x, y = (batch[0], batch[1]) if isinstance(batch, (list, tuple)) else (batch, None)
            x = x._value if isinstance(x, Tensor) else np.asarray(x)
            y = y._value if isinstance(y, Tensor) else np.asarray(y)
            out, loss = fn(x, y)
            if loss is not None:
                losses.append(float(loss))
            for m in self.metrics:
                try:
                    m.update(m.compute(Tensor(out), Tensor(y)))
                except Exception:
                    pass
        self.model.train()
        result = {"loss": float(np.mean(losses)) if losses else None}
        for m in self.metrics:
            result[m.name()] = m.accumulate()
        return result

    def predict(self, test_data=None, batch_size=1, steps=None, **kwargs):
        from ...io import DataLoader, Dataset

        loader = (DataLoader(test_data, batch_size=batch_size)
                  if isinstance(test_data, Dataset) else test_data)
        self.model.eval()
        outs = []
        with tape.no_grad():
            for step, batch in enumerate(loader):
                if steps is not None and step >= steps:
                    break
                x = batch[0] if isinstance(batch, (list, tuple)) else batch
                outs.append(self.model(x).numpy())
        self.model.train()
        return outs

    # ------------------------------------------------------------------ io
    def save(self, path, training=True):
        from ...framework.io import save as psave

        psave(self.model.state_dict(), path + ".pdparams")
        if training and self.optimizer is not None:
            psave(self.optimizer.state_dict(), path + ".pdopt")

    def load(self, path, strict=True, load_optimizer=True):
        import os

        from ...framework.io import load as pload

        self.model.set_state_dict(pload(path + ".pdparams"))
        if load_optimizer and self.optimizer is not None and os.path.exists(path + ".pdopt"):
            self.optimizer.set_state_dict(pload(path + ".pdopt"))

    @property
    def main_program(self):  # static-graph parity shims
        return None

    @property
    def startup_program(self):
        return None
