"""auto_parallel (ref: python/paddle/distributed/auto_parallel/ — ProcessMesh
process_mesh.py:39, shard_tensor/shard_op interface.py:34,73, Engine engine.py:53).

The reference's completion (dist-attr propagation), partitioner (program slicing) and
resharder (cross-mesh moves) are replaced wholesale by XLA's GSPMD partitioner: users
annotate with ProcessMesh + shard_tensor, and the Engine compiles one SPMD program.
"""
from .process_mesh import ProcessMesh, get_current_process_mesh  # noqa: F401
from .interface import shard_tensor, shard_op, reshard  # noqa: F401
from .engine import Engine  # noqa: F401

from . import cost_model  # noqa: F401
from . import planner  # noqa: F401
from .planner import Planner, plan, model_spec_from_layer  # noqa: F401
from .cost_model import ClusterSpec, ModelSpec, ParallelConfig  # noqa: F401
