"""paddle.utils parity shims."""
from __future__ import annotations

from . import dlpack  # noqa: F401


def try_import(name):
    import importlib

    try:
        return importlib.import_module(name)
    except ImportError as e:
        raise ImportError(f"module {name} not available: {e}") from e


def run_check():
    import jax

    print("paddle_tpu is installed successfully!")
    print(f"devices: {jax.devices()}")


class unique_name:
    _counters = {}

    @classmethod
    def generate(cls, key="tmp"):
        cls._counters[key] = cls._counters.get(key, 0) + 1
        return f"{key}_{cls._counters[key]}"


def deprecated(update_to="", since="", reason=""):
    def wrapper(fn):
        return fn

    return wrapper


def require_version(min_version, max_version=None):
    """Raise unless the installed (parity) version is inside the range
    (ref utils/__init__.py require_version)."""
    from .. import version as _v

    def parse(s):
        return tuple(int(p) for p in str(s).split(".")[:3] if p.isdigit())

    cur = parse(_v.full_version)
    if parse(min_version) > cur:
        raise Exception(
            f"installed version {_v.full_version} < required min {min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"installed version {_v.full_version} > allowed max {max_version}")
