"""paddle.utils.dlpack — zero-copy tensor exchange via the DLPack protocol.

Ref: python/paddle/utils/dlpack.py (to_dlpack/from_dlpack over pybind
capsules); here the capsule comes from the jax.Array __dlpack__ protocol.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor.tensor import Tensor

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Export a Tensor (or jax.Array) as a DLPack capsule.  Devices whose PJRT
    plugin cannot hand out external buffer references (e.g. tunneled TPU)
    fall back to a host copy — correct, just not zero-copy."""
    import numpy as np

    arr = x._value if isinstance(x, Tensor) else x
    try:
        return arr.__dlpack__()
    except Exception:
        return np.asarray(jax.device_get(arr)).__dlpack__()


def from_dlpack(dlpack):
    """Import a DLPack capsule (or any object exposing __dlpack__) as a Tensor."""
    if hasattr(dlpack, "__dlpack__"):
        arr = jnp.from_dlpack(dlpack)
    else:
        # a raw PyCapsule, e.g. produced by another framework's to_dlpack —
        # modern jax only takes protocol objects, so consume the capsule via
        # torch (which still accepts legacy capsules) and re-export
        import torch.utils.dlpack as _tdl

        arr = jnp.asarray(_tdl.from_dlpack(dlpack).numpy())
    return Tensor(arr)
