"""Pallas TPU single-query (decode) attention over a static kv-cache.

Reference gap: the snapshot has no decode-path attention at all (its
AnalysisPredictor era predates kv-cache serving); the XLA-composed decode
attention this replaces reads the head-minor [B, L, H, D] cache through
strided gathers and realizes well under half of the chip's streaming
bandwidth.  This kernel owns the decode hot loop instead:

- the static cache is HEAD-MAJOR [B, H, L, D]: each (batch, head) grid point
  streams its keys/values as one contiguous [L, D] block (minor dims satisfy
  the (8, 128) Mosaic tile) — no relayout between HBM and the VPU;
- online softmax over key blocks (the flash recipe at query-length 1);
- optional int8 cache: the kernel dequantizes INSIDE VMEM against
  per-(head, token) scales, so the int8 cache HALVES the HBM bytes decode
  actually streams — on XLA the dequantized bf16 buffer materializes to HBM
  and int8 was a capacity-only lever (models/kv_cache.py history);
- GQA folds into the BlockSpec index map (query head h reads kv head
  h // rep) — kv blocks are fetched once per query head with no repeated
  materialization;
- the valid-length mask rides a scalar-prefetch argument, replacing the
  [1, 1, S, L] additive-mask tensor the composed path rebuilt every step.

Forward-only by design: decode runs under no_grad inside the compiled
generate() loop (models/generation.py).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _interpret_default():
    return jax.default_backend() not in ("tpu", "axon")


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, kw_ref, vw_ref, *,
                   bk, L, G, rep, scale, quant, ks_ref=None, vs_ref=None):
    """One (batch, kv-head-group) grid point: G*rep query heads against their
    G kv heads' [L, D] caches.  Grouping amortizes the per-grid-point DMA +
    dispatch overhead ~G*x vs the old per-(batch, head) grid (measured 0.165
    -> ~0.04 ms/layer/step at B8 H16 L1152).  int8 caches dequantize ONCE
    into VMEM scratch before the block loop — the in-loop cast was VPU-bound
    and serialized against the dots (isolated: 300 -> 142 us)."""
    H = G * rep
    valid = len_ref[pl.program_id(0)]
    nkb = L // bk
    D = q_ref.shape[-1]
    Hp = q_ref.shape[-2]  # H padded to the 8-sublane tile

    if quant:
        kw_ref[...] = k_ref[0].astype(jnp.bfloat16)
        vw_ref[...] = v_ref[0].astype(jnp.bfloat16)
        kb, vb = kw_ref, vw_ref
    else:
        kb, vb = k_ref, v_ref

    def body(kj, carry):
        m, l, acc = carry  # [H, 1], [H, 1], [H, D] f32
        rows_s = []
        for g in range(G):
            if quant:
                kg = kb[g, pl.ds(kj * bk, bk), :]
            else:
                kg = kb[0, g, pl.ds(kj * bk, bk), :]
            for r in range(rep):
                h = g * rep + r
                qh = q_ref[0, 0, h:h + 1, :]  # [1, D]
                s = jax.lax.dot_general(qh, kg, (((1,), (1,)), ((), ())),
                                        preferred_element_type=jnp.float32)
                rows_s.append(s)
        s = jnp.concatenate(rows_s, axis=0) * scale  # [H, bk]
        if quant:
            rows = bk // 128
            ks = ks_ref[0, :, pl.ds(kj * rows, rows), :].reshape(G, bk)
            s = s * jnp.repeat(ks, rep, axis=0) if rep > 1 else s * ks
        kpos = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
        s = jnp.where(kpos < valid, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)  # [H, bk] f32
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=1, keepdims=True)
        if quant:
            vs = vs_ref[0, :, pl.ds(kj * rows, rows), :].reshape(G, bk)
            p = p * jnp.repeat(vs, rep, axis=0) if rep > 1 else p * vs
        pb = p.astype(jnp.bfloat16 if quant else vb.dtype)
        outs = []
        for g in range(G):
            if quant:
                vg = vb[g, pl.ds(kj * bk, bk), :]
            else:
                vg = vb[0, g, pl.ds(kj * bk, bk), :]
            outs.append(jax.lax.dot_general(
                pb[g * rep:(g + 1) * rep], vg, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))
        pv = jnp.concatenate(outs, axis=0)  # [H, D]
        acc = acc * corr + pv
        return m_new, l, acc

    m0 = jnp.full((H, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((H, 1), jnp.float32)
    acc0 = jnp.zeros((H, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nkb, body, (m0, l0, acc0))
    out = (acc / l).astype(o_ref.dtype)
    if Hp != H:
        out = jnp.concatenate(
            [out, jnp.zeros((Hp - H, D), o_ref.dtype)], axis=0)
    o_ref[0, 0] = out


def _pick_group(Hkv, L, D, quant):
    """kv heads per grid point: largest divisor of Hkv whose blocks (plus the
    dequant scratch for int8) stay within ~6 MB of VMEM."""
    per_head = L * D * (1 if quant else 2) * 2          # k + v blocks
    scratch = L * D * 2 * 2 if quant else 0             # bf16 dequant scratch
    for g in (16, 8, 4, 2, 1):
        if Hkv % g == 0 and g * (per_head + scratch) <= 6 * 1024 * 1024:
            return g
    return 1


def _decode_pallas(q, k, v, offset, k_scale, v_scale, scale, bk, interpret):
    B, S, H, D = q.shape
    Hkv, L = k.shape[1], k.shape[2]
    rep = H // Hkv
    quant = k_scale is not None
    valid = jnp.broadcast_to(
        jnp.asarray(offset, jnp.int32) + S, (B,)).astype(jnp.int32)
    # head-major query so every block's trailing dims are tile-clean
    q = jnp.transpose(q, (0, 2, 1, 3))  # [B, H, 1, D]
    G = _pick_group(Hkv, L, D, quant)
    ng = Hkv // G
    Hg = G * rep  # query heads per grid point
    Hp = max(Hg, 8)  # sublane-tile floor for the per-group q/out blocks
    qg = q.reshape(B, ng, Hg, D)
    if Hp != Hg:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, Hp - Hg), (0, 0)))

    # index maps receive the prefetched scalar ref as a trailing argument
    in_specs = [
        pl.BlockSpec((1, 1, Hp, D), lambda b, j, _len: (b, j, 0, 0)),
        pl.BlockSpec((1, G, L, D), lambda b, j, _len: (b, j, 0, 0)),
        pl.BlockSpec((1, G, L, D), lambda b, j, _len: (b, j, 0, 0)),
    ]
    args = [qg, k, v]
    if quant:
        in_specs += [
            pl.BlockSpec((1, G, L // 128, 128), lambda b, j, _len: (b, j, 0, 0)),
            pl.BlockSpec((1, G, L // 128, 128), lambda b, j, _len: (b, j, 0, 0)),
        ]
        args += [k_scale.reshape(B, Hkv, L // 128, 128),
                 v_scale.reshape(B, Hkv, L // 128, 128)]

    def kernel(len_ref, q_ref, k_ref, v_ref, *rest):
        if quant:
            ks_ref, vs_ref, o_ref, kw_ref, vw_ref = rest
        else:
            (o_ref,) = rest[:1]
            ks_ref = vs_ref = kw_ref = vw_ref = None
        return _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, kw_ref,
                              vw_ref, bk=bk, L=L, G=G, rep=rep, scale=scale,
                              quant=quant, ks_ref=ks_ref, vs_ref=vs_ref)

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, ng),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, 1, Hp, D), lambda b, j, _len: (b, j, 0, 0)),
            scratch_shapes=([pltpu.VMEM((G, L, D), jnp.bfloat16)] * 2
                            if quant else []),
        ),
        out_shape=jax.ShapeDtypeStruct((B, ng, Hp, D), q.dtype),
        interpret=interpret,
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel")),
    )(valid, *args)
    out = out[:, :, :Hg, :].reshape(B, H, 1, D)
    return out.transpose(0, 2, 1, 3)  # [B, 1, H, D]


def _decode_dense(q, k, v, offset, k_scale, v_scale, scale):
    """XLA fallback (CPU tests, S > 1, odd shapes): same math, dense."""
    B, S, H, D = q.shape
    Hkv, L = k.shape[1], k.shape[2]
    rep = H // Hkv
    if k_scale is not None:
        k = k.astype(q.dtype) * k_scale.astype(q.dtype)[..., None]
        v = v.astype(q.dtype) * v_scale.astype(q.dtype)[..., None]
    if rep > 1:
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    s = jnp.einsum("bshd,bhld->bhsl", q, k).astype(jnp.float32) * scale
    kpos = jnp.arange(L)[None, None, None, :]
    off = jnp.asarray(offset, jnp.int32)
    if off.ndim >= 1:  # per-slot offsets [B]
        off = off[:, None, None, None]
    qpos = off + jnp.arange(S)[None, None, :, None]
    s = jnp.where(kpos <= qpos, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhsl,bhld->bshd", p, v)


def decode_attention(q, k, v, offset, k_scale=None, v_scale=None, scale=None,
                     block_k=None, interpret=None):
    """Attention of q [B, S, H, D] against a head-major static cache
    k/v [B, Hkv, L, D] whose first `offset + s` positions are valid for
    query position s.  int8 caches pass per-(head, token) scales [B, Hkv, L].
    Returns [B, S, H, D] in q's dtype."""
    B, S, H, D = q.shape
    L = k.shape[2]
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    if interpret is None:
        interpret = _interpret_default()
    bk = block_k
    if bk is None:
        for cand in (512, 384, 256, 128):
            if L % cand == 0:
                bk = cand
                break
    shapes_ok = (S == 1 and D % 128 == 0 and bk is not None
                 and L % bk == 0 and H % k.shape[1] == 0
                 and (k_scale is None or L % 128 == 0))
    # Measured on v5e (same-session A/B, 12-layer 738M decode, P=1024):
    #   int8:  kernel 3.7 ms/tok vs dense-XLA 6.8 (the XLA path materializes
    #          the dequantized bf16 cache in HBM) -> kernel always.
    #   bf16:  kernel 3.5 vs dense 3.8 at B=8, but dense 6.8 vs kernel 9.6 at
    #          B=32 (the per-(b,h) DMA grid stops amortizing) -> kernel only
    #          while the grid stays small.
    use_kernel = shapes_ok and (k_scale is not None or B * H <= 192)
    if use_kernel:
        return _decode_pallas(q, k, v, offset, k_scale, v_scale, scale, bk,
                              interpret)
    return _decode_dense(q, k, v, offset, k_scale, v_scale, scale)
