"""Pallas TPU single-query (decode) attention over a static kv-cache.

Reference gap: the snapshot has no decode-path attention at all (its
AnalysisPredictor era predates kv-cache serving); the XLA-composed decode
attention this replaces reads the head-minor [B, L, H, D] cache through
strided gathers and realizes well under half of the chip's streaming
bandwidth.  This kernel owns the decode hot loop instead:

- the static cache is HEAD-MAJOR [B, H, L, D]: each (batch, head) grid point
  streams its keys/values as one contiguous [L, D] block (minor dims satisfy
  the (8, 128) Mosaic tile) — no relayout between HBM and the VPU;
- online softmax over key blocks (the flash recipe at query-length 1);
- optional int8 cache: the kernel dequantizes INSIDE VMEM against
  per-(head, token) scales, so the int8 cache HALVES the HBM bytes decode
  actually streams — on XLA the dequantized bf16 buffer materializes to HBM
  and int8 was a capacity-only lever (models/kv_cache.py history);
- GQA folds into the BlockSpec index map (query head h reads kv head
  h // rep) — kv blocks are fetched once per query head with no repeated
  materialization;
- the valid-length mask rides a scalar-prefetch argument, replacing the
  [1, 1, S, L] additive-mask tensor the composed path rebuilt every step.

Forward-only by design: decode runs under no_grad inside the compiled
generate() loop (models/generation.py).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..observability import metrics as _obs

NEG_INF = -1e30

#: Dispatch decisions are made at TRACE time (the kernel/fallback choice is
#: shape-static), so the counter ticks once per attention call site per
#: compiled program — a fallback regression shows up as `path="paged_dense"`
#: increments on /metrics the moment the offending program compiles, not as
#: a silent latency cliff.  reason ∈ {tile_aligned, off_tile,
#: query_rows_over_vmem, grid_too_large, forced}.
_M_ATTN_DISPATCH = _obs.counter(
    "llm_attn_kernel_total",
    "Attention dispatch decisions at trace time: which path (Pallas kernel "
    "vs dense fallback) served an attention call site and why",
    labelnames=("path", "reason"))

#: Test hook: "dense" forces every dispatcher onto the fallback path (used
#: by the kernel-vs-fallback engine parity suite and bench.py's ragged
#: round to A/B the SAME shapes through both paths).  None = normal
#: shape-based dispatch.
_FORCE_PATH = None


def _note(path, reason):
    _M_ATTN_DISPATCH.labels(path=path, reason=reason).inc()


def _interpret_default():
    return jax.default_backend() not in ("tpu", "axon")


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, kw_ref, vw_ref, *,
                   bk, L, G, rep, scale, quant, ks_ref=None, vs_ref=None):
    """One (batch, kv-head-group) grid point: G*rep query heads against their
    G kv heads' [L, D] caches.  Grouping amortizes the per-grid-point DMA +
    dispatch overhead ~G*x vs the old per-(batch, head) grid (measured 0.165
    -> ~0.04 ms/layer/step at B8 H16 L1152).  int8 caches dequantize ONCE
    into VMEM scratch before the block loop — the in-loop cast was VPU-bound
    and serialized against the dots (isolated: 300 -> 142 us)."""
    H = G * rep
    valid = len_ref[pl.program_id(0)]
    nkb = L // bk
    D = q_ref.shape[-1]
    Hp = q_ref.shape[-2]  # H padded to the 8-sublane tile

    if quant:
        kw_ref[...] = k_ref[0].astype(jnp.bfloat16)
        vw_ref[...] = v_ref[0].astype(jnp.bfloat16)
        kb, vb = kw_ref, vw_ref
    else:
        kb, vb = k_ref, v_ref

    def body(kj, carry):
        m, l, acc = carry  # [H, 1], [H, 1], [H, D] f32
        rows_s = []
        for g in range(G):
            if quant:
                kg = kb[g, pl.ds(kj * bk, bk), :]
            else:
                kg = kb[0, g, pl.ds(kj * bk, bk), :]
            for r in range(rep):
                h = g * rep + r
                qh = q_ref[0, 0, h:h + 1, :]  # [1, D]
                s = jax.lax.dot_general(qh, kg, (((1,), (1,)), ((), ())),
                                        preferred_element_type=jnp.float32)
                rows_s.append(s)
        s = jnp.concatenate(rows_s, axis=0) * scale  # [H, bk]
        if quant:
            rows = bk // 128
            ks = ks_ref[0, :, pl.ds(kj * rows, rows), :].reshape(G, bk)
            s = s * jnp.repeat(ks, rep, axis=0) if rep > 1 else s * ks
        kpos = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
        s = jnp.where(kpos < valid, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)  # [H, bk] f32
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=1, keepdims=True)
        if quant:
            vs = vs_ref[0, :, pl.ds(kj * rows, rows), :].reshape(G, bk)
            p = p * jnp.repeat(vs, rep, axis=0) if rep > 1 else p * vs
        pb = p.astype(jnp.bfloat16 if quant else vb.dtype)
        outs = []
        for g in range(G):
            if quant:
                vg = vb[g, pl.ds(kj * bk, bk), :]
            else:
                vg = vb[0, g, pl.ds(kj * bk, bk), :]
            outs.append(jax.lax.dot_general(
                pb[g * rep:(g + 1) * rep], vg, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))
        pv = jnp.concatenate(outs, axis=0)  # [H, D]
        acc = acc * corr + pv
        return m_new, l, acc

    m0 = jnp.full((H, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((H, 1), jnp.float32)
    acc0 = jnp.zeros((H, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nkb, body, (m0, l0, acc0))
    out = (acc / l).astype(o_ref.dtype)
    if Hp != H:
        out = jnp.concatenate(
            [out, jnp.zeros((Hp - H, D), o_ref.dtype)], axis=0)
    o_ref[0, 0] = out


def _pick_group(Hkv, L, D, quant):
    """kv heads per grid point: largest divisor of Hkv whose blocks (plus the
    dequant scratch for int8) stay within ~6 MB of VMEM."""
    per_head = L * D * (1 if quant else 2) * 2          # k + v blocks
    scratch = L * D * 2 * 2 if quant else 0             # bf16 dequant scratch
    for g in (16, 8, 4, 2, 1):
        if Hkv % g == 0 and g * (per_head + scratch) <= 6 * 1024 * 1024:
            return g
    return 1


def _decode_pallas(q, k, v, offset, k_scale, v_scale, scale, bk, interpret):
    B, S, H, D = q.shape
    Hkv, L = k.shape[1], k.shape[2]
    rep = H // Hkv
    quant = k_scale is not None
    valid = jnp.broadcast_to(
        jnp.asarray(offset, jnp.int32) + S, (B,)).astype(jnp.int32)
    # head-major query so every block's trailing dims are tile-clean
    q = jnp.transpose(q, (0, 2, 1, 3))  # [B, H, 1, D]
    G = _pick_group(Hkv, L, D, quant)
    ng = Hkv // G
    Hg = G * rep  # query heads per grid point
    Hp = max(Hg, 8)  # sublane-tile floor for the per-group q/out blocks
    qg = q.reshape(B, ng, Hg, D)
    if Hp != Hg:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, Hp - Hg), (0, 0)))

    # index maps receive the prefetched scalar ref as a trailing argument
    in_specs = [
        pl.BlockSpec((1, 1, Hp, D), lambda b, j, _len: (b, j, 0, 0)),
        pl.BlockSpec((1, G, L, D), lambda b, j, _len: (b, j, 0, 0)),
        pl.BlockSpec((1, G, L, D), lambda b, j, _len: (b, j, 0, 0)),
    ]
    args = [qg, k, v]
    if quant:
        in_specs += [
            pl.BlockSpec((1, G, L // 128, 128), lambda b, j, _len: (b, j, 0, 0)),
            pl.BlockSpec((1, G, L // 128, 128), lambda b, j, _len: (b, j, 0, 0)),
        ]
        args += [k_scale.reshape(B, Hkv, L // 128, 128),
                 v_scale.reshape(B, Hkv, L // 128, 128)]

    def kernel(len_ref, q_ref, k_ref, v_ref, *rest):
        if quant:
            ks_ref, vs_ref, o_ref, kw_ref, vw_ref = rest
        else:
            (o_ref,) = rest[:1]
            ks_ref = vs_ref = kw_ref = vw_ref = None
        return _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, kw_ref,
                              vw_ref, bk=bk, L=L, G=G, rep=rep, scale=scale,
                              quant=quant, ks_ref=ks_ref, vs_ref=vs_ref)

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, ng),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, 1, Hp, D), lambda b, j, _len: (b, j, 0, 0)),
            scratch_shapes=([pltpu.VMEM((G, L, D), jnp.bfloat16)] * 2
                            if quant else []),
        ),
        out_shape=jax.ShapeDtypeStruct((B, ng, Hp, D), q.dtype),
        interpret=interpret,
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel")),
    )(valid, *args)
    out = out[:, :, :Hg, :].reshape(B, H, 1, D)
    return out.transpose(0, 2, 1, 3)  # [B, 1, H, D]


def _decode_dense(q, k, v, offset, k_scale, v_scale, scale):
    """XLA fallback (CPU tests, S > 1, odd shapes): same math, dense."""
    B, S, H, D = q.shape
    Hkv, L = k.shape[1], k.shape[2]
    rep = H // Hkv
    if k_scale is not None:
        k = k.astype(q.dtype) * k_scale.astype(q.dtype)[..., None]
        v = v.astype(q.dtype) * v_scale.astype(q.dtype)[..., None]
    if rep > 1:
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    s = jnp.einsum("bshd,bhld->bhsl", q, k).astype(jnp.float32) * scale
    kpos = jnp.arange(L)[None, None, None, :]
    off = jnp.asarray(offset, jnp.int32)
    if off.ndim >= 1:  # per-slot offsets [B]
        off = off[:, None, None, None]
    qpos = off + jnp.arange(S)[None, None, :, None]
    s = jnp.where(kpos <= qpos, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhsl,bhld->bshd", p, v)


def decode_attention(q, k, v, offset, k_scale=None, v_scale=None, scale=None,
                     block_k=None, interpret=None):
    """Attention of q [B, S, H, D] against a head-major static cache
    k/v [B, Hkv, L, D] whose first `offset + s` positions are valid for
    query position s.  int8 caches pass per-(head, token) scales [B, Hkv, L].
    Returns [B, S, H, D] in q's dtype."""
    B, S, H, D = q.shape
    L = k.shape[2]
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    if interpret is None:
        interpret = _interpret_default()
    bk = block_k
    if bk is None:
        for cand in (512, 384, 256, 128):
            if L % cand == 0:
                bk = cand
                break
    shapes_ok = (S == 1 and D % 128 == 0 and bk is not None
                 and L % bk == 0 and H % k.shape[1] == 0
                 and (k_scale is None or L % 128 == 0))
    # Measured on v5e (same-session A/B, 12-layer 738M decode, P=1024):
    #   int8:  kernel 3.7 ms/tok vs dense-XLA 6.8 (the XLA path materializes
    #          the dequantized bf16 cache in HBM) -> kernel always.
    #   bf16:  kernel 3.5 vs dense 3.8 at B=8, but dense 6.8 vs kernel 9.6 at
    #          B=32 (the per-(b,h) DMA grid stops amortizing) -> kernel only
    #          while the grid stays small.
    use_kernel = shapes_ok and (k_scale is not None or B * H <= 192)
    if _FORCE_PATH == "dense":
        use_kernel = False
        reason = "forced"
    elif use_kernel:
        reason = "tile_aligned"
    elif not shapes_ok:
        reason = "multi_query" if S != 1 else "off_tile"
    else:
        reason = "grid_too_large"
    if use_kernel:
        _note("static_kernel", reason)
        return _decode_pallas(q, k, v, offset, k_scale, v_scale, scale, bk,
                              interpret)
    _note("static_dense", reason)
    return _decode_dense(q, k, v, offset, k_scale, v_scale, scale)


# ------------------------------------------------------------------- paged
#
# Ragged paged attention (the arxiv 2604.15464 design, adapted to this
# stack's head-major page layout): the kv cache is a global page pool
# [P, Hkv, page_size, D] plus per-slot page tables [B, max_pages] — capacity
# scales with ACTUAL sequence lengths, not max_seq_len.  ONE kernel serves
# every ragged query-block shape the serving engine produces: S=1 continuous
# -batching decode, prefill chunks of S=C tokens at arbitrary per-slot chunk
# offsets, and the S=K+1 speculative-verify ladder — the per-slot (offset,
# query-length) pair rides the scalar-prefetched `lengths` vector
# (lengths[b] = offset[b] + S) and drives a per-ROW causal mask inside the
# online-softmax page loop: query s of slot b attends keys
# [0, lengths[b] - S + s].  The kernel walks each slot's pages through the
# scalar-prefetched page table: the BlockSpec index map reads pt_ref[b, ·],
# so the pipeline DMAs exactly the pages the slot owns.  Slots shorter than
# max_pages point their unused table entries at the trash page
# (kv_cache.TRASH_PAGE) — the index map CLAMPS the walk to the slot's last
# valid page, so the ragged tail repeats a block index the pipeline has
# already fetched and the trash page is never DMA'd at all (trash-fetch
# elision; the tail compute is skipped by the valid-length gate).


def gather_pages(pool, page_tbl):
    """[P, H, ps, D] pool + [B, M] table -> contiguous [B, H, M*ps, D]
    (scale pools [P, H, ps] -> [B, H, M*ps]).  The dense fallback's view of
    the paged cache; also the test oracle."""
    g = pool[page_tbl]  # [B, M, H, ps, ...]
    if g.ndim == 5:
        B, M, H, ps, D = g.shape
        return jnp.transpose(g, (0, 2, 1, 3, 4)).reshape(B, H, M * ps, D)
    B, M, H, ps = g.shape
    return jnp.transpose(g, (0, 2, 1, 3)).reshape(B, H, M * ps)


def _paged_kernel(len_ref, pt_ref, q_ref, k_ref, v_ref, *refs, ps, S, G,
                  rep, scale, quant):
    """One (slot, kv-head-group, page) grid step: fold this page's keys and
    values into the slot's online-softmax state (m/l/acc VMEM scratch that
    persists across the sequential page axis).  The query block is RAGGED:
    its rows are laid out [G kv heads, S query positions, rep query heads]
    (row g*S*rep + s*rep + r is query position s of query head g*rep + r),
    so one [S*rep, D] x [ps, D]^T dot per kv head scores every query row of
    that head at once, and a per-row causal threshold
    lengths[b] - S + s + 1 masks each row to its own prefix — S=1 decode,
    prefill chunks, and the K+1 verify ladder are the SAME kernel at
    different static S.  int8 pages dequantize in VMEM: payload cast once
    per page, per-(head, token) scales applied to the score/probability
    rows outside the dots (the static kernel's recipe)."""
    if quant:  # inputs continue with the scale pages, THEN output + scratch
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = refs
    else:
        o_ref, m_ref, l_ref, acc_ref = refs
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    p = pl.program_id(2)
    M = pl.num_programs(2)
    valid = len_ref[b]
    sg = S * rep          # query rows per kv head
    rows = G * sg         # query rows per grid step
    D = q_ref.shape[-1]
    Rp = q_ref.shape[-2]  # rows padded to the 8-sublane tile

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(p * ps < valid)
    def _page():
        if quant:
            kb = k_ref[0].astype(jnp.bfloat16)  # [G, ps, D]
            vb = v_ref[0].astype(jnp.bfloat16)
        else:
            kb, vb = k_ref[0], v_ref[0]
        rows_s = []
        for g in range(G):
            qg = q_ref[0, 0, g * sg:(g + 1) * sg, :]  # [S*rep, D]
            rows_s.append(jax.lax.dot_general(
                qg, kb[g], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32))
        s = (jnp.concatenate(rows_s, axis=0) if G > 1
             else rows_s[0]) * scale  # [rows, ps]
        if quant:
            ks = ks_ref[0].reshape(G, ps)
            s = s * (jnp.repeat(ks, sg, axis=0) if sg > 1 else ks)
        # per-row causal end: row g*sg + s*rep + r is query position s, and
        # query s of a slot whose lengths entry is `valid` = offset + S may
        # read keys [0, offset + s] — i.e. kpos < valid - S + s + 1.  Row 0
        # always has offset + 1 >= 1 valid keys, so page 0 (the only page
        # guaranteed to participate) leaves no row's running max at NEG_INF.
        ri = jax.lax.broadcasted_iota(jnp.int32, (rows, 1), 0)
        qend = valid - S + (ri // rep) % S + 1
        kpos = p * ps + jax.lax.broadcasted_iota(jnp.int32, (rows, ps), 1)
        s = jnp.where(kpos < qend, s, NEG_INF)
        m_prev = m_ref[:rows, :1]
        l_prev = l_ref[:rows, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        pexp = jnp.exp(s - m_new)  # [rows, ps] f32
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(pexp, axis=1, keepdims=True)
        if quant:
            vs = vs_ref[0].reshape(G, ps)
            pexp = pexp * (jnp.repeat(vs, sg, axis=0) if sg > 1 else vs)
        pb = pexp.astype(jnp.bfloat16 if quant else vb.dtype)
        outs = []
        for g in range(G):
            outs.append(jax.lax.dot_general(
                pb[g * sg:(g + 1) * sg], vb[g], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))
        pv = jnp.concatenate(outs, axis=0) if G > 1 else outs[0]  # [rows, D]
        m_ref[:rows, :1] = m_new
        l_ref[:rows, :1] = l_new
        acc_ref[:rows, :] = acc_ref[:rows, :] * corr + pv

    @pl.when(p == M - 1)
    def _emit():
        l = l_ref[:rows, :1]
        out = (acc_ref[:rows, :]
               / jnp.where(l <= 0.0, 1.0, l)).astype(o_ref.dtype)
        if Rp != rows:
            out = jnp.concatenate(
                [out, jnp.zeros((Rp - rows, D), o_ref.dtype)], axis=0)
        o_ref[0, 0] = out


def _paged_state_bytes(rows, D):
    """VMEM bytes of the per-grid-step ragged query state: the q block plus
    the f32 m/l/acc online-softmax scratch (shared bound between the group
    picker and the dispatcher's S cap)."""
    return rows * (4 * D            # q block (f32 worst case)
                   + 2 * 4 * 128    # m + l scratch rows
                   + 4 * D)         # acc scratch


def _pick_group_paged(Hkv, ps, D, quant, S=1, rep=1):
    """kv heads per grid step: page blocks are small (one page, not the
    whole sequence), so the bounds are the double-buffered page pair
    staying comfortably inside VMEM plus — now that query blocks are
    ragged — the G*S*rep query rows of q/m/l/acc state."""
    per_head = ps * D * (1 if quant else 2) * 2  # k + v page blocks
    for g in (16, 8, 4, 2, 1):
        if (Hkv % g == 0 and g * per_head <= 2 * 1024 * 1024
                and _paged_state_bytes(g * S * rep, D) <= 6 * 1024 * 1024):
            return g
    return 1


def _paged_pallas(q, k_pages, v_pages, lengths, page_tbl, k_scale, v_scale,
                  scale, interpret):
    B, S, H, D = q.shape
    Hkv, ps = k_pages.shape[1], k_pages.shape[2]
    M = page_tbl.shape[1]
    rep = H // Hkv
    quant = k_scale is not None
    G = _pick_group_paged(Hkv, ps, D, quant, S, rep)
    ng = Hkv // G
    rows = G * S * rep
    Rp = max(8, -(-rows // 8) * 8)  # 8-sublane tile floor for q/out blocks
    # ragged row layout [G, S, rep]: query head h = j*G*rep + g*rep + r of
    # position s lands at row g*S*rep + s*rep + r of group j — each kv
    # head's S*rep query rows are contiguous, so the kernel scores them
    # with ONE dot per kv head (at S=1 this is exactly the old [G, rep]
    # head order)
    qg = jnp.transpose(q, (0, 2, 1, 3))        # [B, H, S, D]
    qg = qg.reshape(B, ng, G, rep, S, D)
    qg = jnp.transpose(qg, (0, 1, 2, 4, 3, 5)).reshape(B, ng, rows, D)
    if Rp != rows:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, Rp - rows), (0, 0)))
    lengths = jnp.asarray(lengths, jnp.int32).reshape(B)
    page_tbl = jnp.asarray(page_tbl, jnp.int32)

    # Index maps receive the prefetched (lengths, page-table) refs last;
    # the page axis walks the slot's table — THE ragged gather.  Trash-fetch
    # elision: grid steps past the slot's last valid page CLAMP to that last
    # page, so the pipeline sees a repeated block index and skips the DMA
    # entirely (the valid-length gate already skips the compute) — the
    # ragged tail of a short slot in a long-max-len pool costs zero
    # bandwidth instead of one trash-page fetch per (slot, head-group).
    def _pidx(b, p, lens, pt):
        return pt[b, jnp.minimum(p, jnp.maximum(lens[b] - 1, 0) // ps)]

    in_specs = [
        pl.BlockSpec((1, 1, Rp, D), lambda b, g, p, _len, _pt: (b, g, 0, 0)),
        pl.BlockSpec((1, G, ps, D),
                     lambda b, g, p, lens, pt: (_pidx(b, p, lens, pt), g, 0, 0)),
        pl.BlockSpec((1, G, ps, D),
                     lambda b, g, p, lens, pt: (_pidx(b, p, lens, pt), g, 0, 0)),
    ]
    args = [qg, k_pages, v_pages]
    if quant:
        sb = ps // 128
        in_specs += [
            pl.BlockSpec((1, G, sb, 128),
                         lambda b, g, p, lens, pt: (_pidx(b, p, lens, pt), g, 0, 0)),
            pl.BlockSpec((1, G, sb, 128),
                         lambda b, g, p, lens, pt: (_pidx(b, p, lens, pt), g, 0, 0)),
        ]
        P = k_pages.shape[0]
        args += [k_scale.reshape(P, Hkv, sb, 128),
                 v_scale.reshape(P, Hkv, sb, 128)]

    kernel = functools.partial(_paged_kernel, ps=ps, S=S, G=G, rep=rep,
                               scale=scale, quant=quant)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, ng, M),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, 1, Rp, D), lambda b, g, p, _len, _pt: (b, g, 0, 0)),
            scratch_shapes=[pltpu.VMEM((Rp, 128), jnp.float32),
                            pltpu.VMEM((Rp, 128), jnp.float32),
                            pltpu.VMEM((Rp, D), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((B, ng, Rp, D), q.dtype),
        interpret=interpret,
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(lengths, page_tbl, *args)
    out = out[:, :, :rows, :].reshape(B, ng, G, S, rep, D)
    out = jnp.transpose(out, (0, 1, 2, 4, 3, 5)).reshape(B, H, S, D)
    return out.transpose(0, 2, 1, 3)  # [B, S, H, D]


def _paged_dense(q, k_pages, v_pages, offset, page_tbl, k_scale, v_scale,
                 scale):
    """XLA fallback (CPU / odd page or head shapes): gather the slot's
    pages into a contiguous view, then the dense math.  The gather is
    CAPPED at the batch-max logical length when the offsets are concrete
    (page tables are padded to max_pages, but no slot can have valid keys
    past max(offset) + S): on a mixed-length batch in a long-max-len pool
    this trims the materialized view — and the O(S * M * ps) masked score
    matrix behind it — from every slot's FULL table to the pages anyone
    actually uses.  Traced offsets (shape-polymorphic callers) keep the
    full-table gather: the cap must be static to change the gather shape."""
    S, M, ps = q.shape[1], page_tbl.shape[1], k_pages.shape[2]
    if not isinstance(jnp.asarray(offset), jax.core.Tracer):
        import numpy as np

        used = min(M, -(-(int(np.max(np.asarray(offset))) + S) // ps))
        page_tbl = page_tbl[:, :max(used, 1)]
    k = gather_pages(k_pages, page_tbl)
    v = gather_pages(v_pages, page_tbl)
    if k_scale is not None:
        k = k.astype(q.dtype) * gather_pages(
            k_scale, page_tbl).astype(q.dtype)[..., None]
        v = v.astype(q.dtype) * gather_pages(
            v_scale, page_tbl).astype(q.dtype)[..., None]
        k_scale = v_scale = None
    return _decode_dense(q, k, v, offset, None, None, scale)


def paged_decode_attention(q, k_pages, v_pages, offset, page_tbl,
                           k_scale=None, v_scale=None, scale=None,
                           interpret=None):
    """Attention of q [B, S, H, D] against a PAGED cache: pool
    [P, Hkv, page_size, D] + page table [B, max_pages], with the first
    offset + s positions of each slot valid for query position s (offset a
    scalar or a per-slot [B] vector).  int8 pools pass per-(head, token)
    scale pools [P, Hkv, page_size].  Any S >= 1 rides the ONE ragged
    Pallas kernel on tile-aligned shapes — S=1 decode, prefill chunks,
    and the K+1 spec-verify ladder; the gathered dense path survives only
    for CPU-odd shapes (D/page off the 128 tile, mismatched head counts)
    or a query block too large for VMEM.  Returns [B, S, H, D] in q's
    dtype."""
    B, S, H, D = q.shape
    Hkv, ps = k_pages.shape[1], k_pages.shape[2]
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    if interpret is None:
        interpret = _interpret_default()
    lengths = jnp.broadcast_to(
        jnp.asarray(offset, jnp.int32), (B,)).astype(jnp.int32) + S
    # ps % 128 == 0 keeps every page block (and the reshaped scale pages)
    # on clean (sublane, 128-lane) tiles; anything else is fallback-only
    tile_ok = D % 128 == 0 and ps % 128 == 0 and H % Hkv == 0
    # even at G=1 the S*rep query rows of q/m/l/acc state must fit VMEM
    rows_ok = tile_ok and _paged_state_bytes(
        S * (H // Hkv), D) <= 6 * 1024 * 1024
    if _FORCE_PATH == "dense":
        reason, use_kernel = "forced", False
    elif not tile_ok:
        reason, use_kernel = "off_tile", False
    elif not rows_ok:
        reason, use_kernel = "query_rows_over_vmem", False
    else:
        reason, use_kernel = "tile_aligned", True
    if use_kernel:
        _note("paged_kernel", reason)
        return _paged_pallas(q, k_pages, v_pages, lengths, page_tbl,
                             k_scale, v_scale, scale, interpret)
    _note("paged_dense", reason)
    return _paged_dense(q, k_pages, v_pages, offset, page_tbl,
                        k_scale, v_scale, scale)
