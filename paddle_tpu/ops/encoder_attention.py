"""Pallas TPU fused short-sequence attention (forward + backward, dropout).

Reference analog: `/root/reference/paddle/fluid/operators/fused/fused_attention_op.cu`
(+ fmha_ref.h) — the reference's only fused attention is exactly this regime:
full [S, S] probs held on-chip for modest S, no online-softmax tiling.  The
flash kernel (ops/flash_attention.py) covers long sequences; at S ~ 128-512 its
per-(b,h) grid makes tiny DMA blocks and loses to dense XLA (measured 25 ms vs
6.7 ms per ERNIE layer fwd+bwd).  This kernel instead packs G heads per grid
step — large DMA blocks — and computes each head's whole attention in VMEM:

    s = q @ k^T * scale        [S, S] f32, softmax rows
    p = dropout(softmax(s))    mask from the ON-CORE PRNG (pltpu), no HBM bits
    o = p @ v

The backward regenerates the dropout mask from the same per-(step, head) seed
and recomputes s/p in VMEM (flash-style recompute, no probs residual), so the
only saved tensors are the natural q/k/v inputs.

Dense-path cost this replaces (ERNIE b512 s128 h12 d64): [B,H,S,S] logits+probs
round-trips plus u16 mask traffic — ~9.9 ms/layer fwd+bwd with dropout.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


from ._prng import (interpret_default as _interpret_default,
                    keep_mask as _keep_mask,
                    parallel_params as _params)


# VMEM budget: per head S*S f32 probs (+ masks) plus G*(q,k,v,o) blocks.
_VMEM_ELEMS = 2 * 1024 * 1024


def pick_g(bh, s, d):
    """Heads per grid step.  g=16 measured fastest for fwd+bwd at the encoder
    shapes (5.47 ms/layer vs 5.66 at g=8, 6.61 at g=4; BH=6144/S=128/D=64
    with dropout); fall through to any divisor that fits VMEM."""
    for g in (16, 8, 4, 2, 1):
        if bh % g == 0 and g * s * d * 4 + g * s * s <= _VMEM_ELEMS:
            return g
    return None


def supported(bh, s, d, seq_kv=None):
    if seq_kv is not None and seq_kv != s:
        return False  # self-attention only (q/k same length)
    return (s % 128 == 0 and s <= 512 and d in (64, 128)
            and pick_g(bh, s, d) is not None)


def _block_masks(seed_ref, pid, g, s, rate, interpret):
    """[G, S, S] keep-masks for this grid step (fwd and bwd call with the same
    (seed, pid) so the masks regenerate bit-identically — shared seed-mix
    contract in ops/_prng.py)."""
    return _keep_mask(seed_ref, pid, (g, s, s), rate, interpret)


def _softmax_rows(s):
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    return e / jnp.sum(e, axis=-1, keepdims=True)


def _causal_neg(s_len):
    qpos = jax.lax.broadcasted_iota(jnp.int32, (s_len, s_len), 0)
    kpos = jax.lax.broadcasted_iota(jnp.int32, (s_len, s_len), 1)
    return jnp.where(qpos >= kpos, 0.0, -1e30)


def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, *, scale, rate, g,
                causal, interpret):
    # one BATCHED dot_general over the G heads per MXU dispatch: measured ~2x
    # the throughput of a python loop of per-head 2D matmuls at these shapes
    pid = pl.program_id(0)
    s_len = q_ref.shape[1]
    q = q_ref[...]
    k = k_ref[...]
    v = v_ref[...]
    s = jax.lax.dot_general(q, k, (((2,), (2,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        s = s + _causal_neg(s_len)[None]
    p = _softmax_rows(s)
    if rate > 0.0:
        keep = _block_masks(seed_ref, pid, g, s_len, rate, interpret)
        p = jnp.where(keep, p * (1.0 / (1.0 - rate)), 0.0)
    o_ref[...] = jax.lax.dot_general(p.astype(v.dtype), v,
                                     (((2,), (1,)), ((0,), (0,))),
                                     preferred_element_type=jnp.float32
                                     ).astype(o_ref.dtype)


def _bwd_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref,
                dq_ref, dk_ref, dv_ref, *, scale, rate, g, causal, interpret):
    pid = pl.program_id(0)
    s_len = q_ref.shape[1]
    inv = 1.0 / (1.0 - rate) if rate > 0.0 else 1.0
    q = q_ref[...]
    k = k_ref[...]
    v = v_ref[...]
    do = do_ref[...].astype(jnp.float32)
    s = jax.lax.dot_general(q, k, (((2,), (2,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        s = s + _causal_neg(s_len)[None]
    p = _softmax_rows(s)
    if rate > 0.0:
        keep = _block_masks(seed_ref, pid, g, s_len, rate, interpret)
        p_d = jnp.where(keep, p * inv, 0.0)
    else:
        p_d = p
    # o = p_d @ v   (batch dim 0 = heads throughout)
    dv_ref[...] = jax.lax.dot_general(
        p_d.astype(do.dtype), do, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).astype(dv_ref.dtype)
    dp_d = jax.lax.dot_general(do, v.astype(do.dtype),
                               (((2,), (2,)), ((0,), (0,))),
                               preferred_element_type=jnp.float32)
    dp = jnp.where(keep, dp_d * inv, 0.0) if rate > 0.0 else dp_d
    ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True)) * scale
    dq_ref[...] = jax.lax.dot_general(
        ds.astype(k.dtype), k, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).astype(dq_ref.dtype)
    dk_ref[...] = jax.lax.dot_general(
        ds.astype(q.dtype), q, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).astype(dk_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _attn_core(q, k, v, seed, scale, rate, causal):
    out, _ = _attn_fwd(q, k, v, seed, scale, rate, causal)
    return out


def _attn_fwd(q, k, v, seed, scale, rate, causal):
    bh, s, d = q.shape
    g = pick_g(bh, s, d)
    interpret = _interpret_default()
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, rate=rate, g=g,
                          causal=causal, interpret=interpret),
        grid=(bh // g,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((g, s, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((g, s, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((g, s, d), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((g, s, d), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        interpret=interpret,
        compiler_params=_params(interpret),
    )(seed, q, k, v)
    return out, (q, k, v, seed)


def _attn_bwd(scale, rate, causal, res, do):
    q, k, v, seed = res
    bh, s, d = q.shape
    g = pick_g(bh, s, d)
    interpret = _interpret_default()
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_kernel, scale=scale, rate=rate, g=g,
                          causal=causal, interpret=interpret),
        grid=(bh // g,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((g, s, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((g, s, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((g, s, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((g, s, d), lambda i: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((g, s, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((g, s, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((g, s, d), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, s, d), k.dtype),
            jax.ShapeDtypeStruct((bh, s, d), v.dtype),
        ],
        interpret=interpret,
        compiler_params=_params(interpret),
    )(seed, q, k, v, do)
    return dq, dk, dv, None


_attn_core.defvjp(
    lambda q, k, v, seed, scale, rate, causal: _attn_fwd(q, k, v, seed, scale,
                                                         rate, causal),
    _attn_bwd)


def encoder_attention(q, k, v, seed=None, scale=None, dropout_rate=0.0,
                      causal=False):
    """Fused self-attention for short sequences.

    q/k/v: [B, S, H, D] (paddle layout); seed: int32 [2] array (required when
    dropout_rate > 0); returns [B, S, H, D].
    """
    b, s, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if dropout_rate > 0.0 and seed is None:
        raise ValueError("encoder_attention: dropout_rate > 0 requires a seed")
    if seed is None:
        seed = jnp.zeros((2,), jnp.int32)
    if not supported(b * h, s, d, k.shape[1]):
        raise ValueError(
            f"encoder_attention: shape B*H={b*h} S={s} D={d} unsupported "
            "(need S%128==0, S<=512, D in (64,128)) — use the dense SDPA path")

    def pack(t):
        return jnp.swapaxes(t, 1, 2).reshape(b * h, s, d)

    out = _attn_core(pack(q), pack(k), pack(v), seed, float(scale),
                     float(dropout_rate), bool(causal))
    return jnp.swapaxes(out.reshape(b, h, s, d), 1, 2)
