"""Pallas TPU fused dropout + residual-add + LayerNorm (forward + backward).

Reference analog: `/root/reference/paddle/fluid/operators/fused/fused_dropout_helper.h`
(ResidualDropoutBias + LayerNorm fused epilogues used by fused_attention /
fused_feedforward) — the CUDA fusion that keeps transformer-encoder glue off the
memory bus.  TPU edition: one kernel reads the residual and the branch output,
draws the dropout mask from the ON-CORE PRNG (pltpu.prng_random_bits — no mask
HBM traffic, no stored mask residual), adds, normalizes with f32 single-pass
sum/sumsq stats, and writes the normalized output.

Residual policy: the ONLY saved activation is `s = residual + dropout(branch)`
(the same tensor XLA's composed LN keeps); the dropout mask is REGENERATED in
the backward from the per-block seed, so no [n, h] bool/bits residuals exist —
that storage OOMed the dense-head ERNIE step when rbg masks became
non-rematerializable for XLA (tools/ernie_breakdown.py history).

Both grids are embarrassingly parallel: dgamma/dbeta come out as per-block
partials reduced by XLA outside the kernel (a [nblocks, h] f32 array — KBs).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


from ._prng import (interpret_default as _interpret_default,
                    keep_mask as _keep_mask_bits,
                    parallel_params as _params)


def _pick_bn(n, h):
    """Largest row-block that divides n and keeps bn*h temporaries VMEM-friendly."""
    budget = 256 * 1024  # elements per f32 temp (~1M)
    for bn in (512, 256, 128, 64, 32, 16, 8):
        if n % bn == 0 and bn * h <= budget:
            return bn
    return None


def _stats(s, eps):
    # two-pass mean/var: s lives in VMEM here, so the second pass is free and
    # avoids the E[x^2]-E[x]^2 cancellation when |mean| >> spread
    mean = jnp.mean(s, axis=-1, keepdims=True)
    c = s - mean
    var = jnp.mean(c * c, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    return mean, rstd


def _fwd_kernel(seed_ref, x_ref, y_ref, g_ref, b_ref, o_ref, s_ref,
                *, rate, eps, upscale, interpret):
    pid = pl.program_id(0)
    xf = x_ref[...].astype(jnp.float32)
    yf = y_ref[...].astype(jnp.float32)
    if rate > 0.0:
        keep = _keep_mask_bits(seed_ref, pid, y_ref.shape, rate, interpret)
        scale = (1.0 / (1.0 - rate)) if upscale else 1.0
        yf = jnp.where(keep, yf * scale, 0.0)
    s = xf + yf
    s_ref[...] = s.astype(s_ref.dtype)
    # stats and normalization run on the ROUNDED s (what the backward will
    # re-read): for bf16 activations this keeps fwd and bwd consistent — the
    # same function of the same stored tensor — instead of a ~2^-8 bias
    # between f32-fwd stats and bf16-recomputed bwd stats
    sq = s_ref[...].astype(jnp.float32)
    mean, rstd = _stats(sq, eps)
    out = (sq - mean) * rstd * g_ref[...].astype(jnp.float32) \
        + b_ref[...].astype(jnp.float32)
    o_ref[...] = out.astype(o_ref.dtype)


def _bwd_kernel(seed_ref, s_ref, g_ref, dz_ref,
                dx_ref, dy_ref, dg_ref, db_ref, *, rate, eps, upscale, interpret):
    pid = pl.program_id(0)
    s = s_ref[...].astype(jnp.float32)
    mean, rstd = _stats(s, eps)
    xhat = (s - mean) * rstd

    dz = dz_ref[...].astype(jnp.float32)
    dxhat = dz * g_ref[...].astype(jnp.float32)
    a = jnp.mean(dxhat, axis=-1, keepdims=True)
    b = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
    ds = rstd * (dxhat - a - xhat * b)
    dx_ref[...] = ds.astype(dx_ref.dtype)
    if rate > 0.0:
        keep = _keep_mask_bits(seed_ref, pid, s_ref.shape, rate, interpret)
        scale = (1.0 / (1.0 - rate)) if upscale else 1.0
        dy_ref[...] = jnp.where(keep, ds * scale, 0.0).astype(dy_ref.dtype)
    else:
        dy_ref[...] = ds.astype(dy_ref.dtype)
    # per-block partials, broadcast over the 8-sublane min tile (Pallas TPU
    # rejects 1-row output blocks inside a larger array); XLA reduces the
    # [nblocks, 8, h] partials outside the kernel
    h = s.shape[-1]
    dg_ref[...] = jnp.broadcast_to(jnp.sum(dz * xhat, axis=0, keepdims=True), (8, h))
    db_ref[...] = jnp.broadcast_to(jnp.sum(dz, axis=0, keepdims=True), (8, h))


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _fused_core(x, y, gamma, beta, seed, rate, eps, upscale):
    out, _ = _fused_fwd(x, y, gamma, beta, seed, rate, eps, upscale)
    return out


def _fused_fwd(x, y, gamma, beta, seed, rate, eps, upscale):
    n, h = x.shape
    bn = _pick_bn(n, h)
    interpret = _interpret_default()
    out, s = pl.pallas_call(
        functools.partial(_fwd_kernel, rate=rate, eps=eps, upscale=upscale,
                          interpret=interpret),
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x.dtype),
            jax.ShapeDtypeStruct((n, h), x.dtype),
        ],
        interpret=interpret,
        compiler_params=_params(interpret),
    )(seed, x, y, gamma.reshape(1, h), beta.reshape(1, h))
    return out, (s, gamma, seed)


def _fused_bwd(rate, eps, upscale, res, dz):
    s, gamma, seed = res
    n, h = s.shape
    bn = _pick_bn(n, h)
    nb = n // bn
    interpret = _interpret_default()
    dx, dy, dgp, dbp = pl.pallas_call(
        functools.partial(_bwd_kernel, rate=rate, eps=eps, upscale=upscale,
                          interpret=interpret),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((bn, h), lambda i: (i, 0)),
            pl.BlockSpec((8, h), lambda i: (i, 0)),
            pl.BlockSpec((8, h), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), s.dtype),
            jax.ShapeDtypeStruct((n, h), s.dtype),
            jax.ShapeDtypeStruct((nb * 8, h), jnp.float32),
            jax.ShapeDtypeStruct((nb * 8, h), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=_params(interpret),
    )(seed, s, gamma.reshape(1, h), dz)
    dg = jnp.sum(dgp.reshape(nb, 8, h)[:, 0], axis=0).astype(gamma.dtype)
    db = jnp.sum(dbp.reshape(nb, 8, h)[:, 0], axis=0).astype(gamma.dtype)
    return dx, dy, dg, db, None


_fused_core.defvjp(lambda x, y, g, b, s, rate, eps, up: _fused_fwd(x, y, g, b, s, rate, eps, up),
                   _fused_bwd)


def supported(n, h):
    """Can the kernel tile this shape?  (rows split into an even block grid,
    feature dim lane-aligned)."""
    return h % 128 == 0 and _pick_bn(n, h) is not None


def fused_dropout_add_layer_norm(branch, residual, gamma, beta, seed, rate=0.0,
                                 eps=1e-12, upscale=True):
    """out = LayerNorm(residual + dropout(branch)) over the last dim.

    Argument order matches nn.functional.fused_dropout_add_layer_norm: the
    FIRST tensor is the branch output that gets dropped, the SECOND is the
    residual stream kept intact.  branch/residual: [..., H] (flattened to rows
    internally); gamma/beta: [H]; seed: int32 [2] array (two words of the
    per-call dropout stream; ignored at rate=0).
    """
    shape = branch.shape
    h = shape[-1]
    n = 1
    for d in shape[:-1]:
        n *= d
    if not supported(n, h):
        raise ValueError(
            f"fused_dropout_add_layer_norm: shape rows={n} h={h} not tileable "
            "(h must be a multiple of 128 and rows divisible by a block size "
            "of 8..512) — check ops.fused_ln.supported(n, h) and fall back to "
            "the composed nn.functional path")
    if rate >= 1.0:
        raise ValueError("fused_dropout_add_layer_norm requires rate < 1 "
                         "(rate>=1 drops the whole branch; compute LN(residual) "
                         "directly instead)")
    # kernel-internal convention: x = residual (kept), y = branch (dropped)
    x2 = residual.reshape(n, h)
    y2 = branch.reshape(n, h)
    out = _fused_core(x2, y2, gamma, beta, seed, float(rate), float(eps),
                      bool(upscale))
    return out.reshape(shape)
