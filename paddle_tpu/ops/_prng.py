"""Shared in-kernel PRNG plumbing for Pallas dropout kernels.

The fwd/bwd mask-regeneration contract of ops/fused_ln.py and
ops/encoder_attention.py depends on BIT-IDENTICAL seed mixing between the
forward and backward kernels — this module is the single home for that logic
(seed hash, uint threshold rounding, interpret-mode fallback) so the two
kernels cannot silently diverge.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.pallas import tpu as pltpu

# Knuth multiplicative hash constant (2654435769 as int32): spreads
# neighbouring block ids far apart in the seed space.
_MIX = np.int32(-1640531527)


def interpret_default():
    from ..core.device import is_tpu_backend

    return not is_tpu_backend()


def thresh_u32(rate):
    """uint32 keep-threshold: P(bits < thresh) = 1 - rate (granularity 2^-32)."""
    return np.uint32(min(int(round((1.0 - rate) * 4294967296.0)), 4294967295))


def block_bits(seed_ref, pid, shape, interpret):
    """Raw uint32 random bits for grid block `pid`, deterministic in
    (seed_ref[0], seed_ref[1], pid) — fwd and bwd kernels calling with the
    same triple regenerate identical bits.

    seed_ref: SMEM ref holding int32[2] (two words of the per-call stream).
    On-chip: the hardware PRNG (pltpu).  Interpret mode (CPU tests): the
    functional RNG — masks differ from on-chip masks, which is fine; dropout
    streams are platform-local (same stance as the rbg/threefry split in
    framework.random).
    """
    if interpret:
        key = jax.random.PRNGKey(seed_ref[0].astype(jnp.uint32))
        key = jax.random.fold_in(key, seed_ref[1].astype(jnp.uint32))
        key = jax.random.fold_in(key, pid)
        return jax.random.bits(key, shape, jnp.uint32)
    # Mosaic accepts at most 2 seed words: fold the block id into word 0
    pltpu.prng_seed(seed_ref[0] ^ (pid * _MIX), seed_ref[1])
    return pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)


def keep_mask(seed_ref, pid, shape, rate, interpret):
    """Bernoulli(1-rate) keep-mask from block_bits."""
    return block_bits(seed_ref, pid, shape, interpret) < thresh_u32(rate)


def parallel_params(interpret):
    """CompilerParams for embarrassingly-parallel 1-D grids."""
    return None if interpret else pltpu.CompilerParams(
        dimension_semantics=("parallel",))
