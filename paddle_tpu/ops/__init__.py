"""Pallas TPU kernels + hand-rolled distributed primitives (flash attention, ring
attention, MoE dispatch) — the few ops where XLA's automatic lowering leaves MXU/HBM
performance on the table (see /opt/skills/guides/pallas_guide.md)."""
