"""Batched-gather LoRA epilogue: ``y += (x @ A_g) @ B_g`` per row.

Multi-tenant serving runs MANY LoRA fine-tunes through ONE compiled
program.  The adapter weights live in paged device pools (one
``[num_adapter_pages, D_in, r]`` A-pool and one ``[num_adapter_pages, r,
D_out]`` B-pool per projection site — see ``models/lora.py``), and each
batch row carries an int32 adapter-page id.  The epilogue gathers that
row's A/B pages with ``jnp.take`` and adds the low-rank delta to the base
projection — no per-adapter branch, no recompile when the mix changes,
exactly the per-slot DEVICE-ARRAY knob mechanism the fused sampler uses
for top-k/top-p.

Zero-adapter convention: page 0 of every pool is all zeros and is never
written.  ``adapter_id=None`` rows gather page 0, so a mixed batch of
base-model and adapter traffic needs no masking branch — the delta is an
exact ``+0`` (zero matmuls produce exact zeros, and adding them cannot
change any logit comparison).

The context threading is deliberately out-of-band: model forwards call
``apply_site(site, x)`` which returns ``None`` unless a pool context is
active (``with activate(...)``), so the base model's traced program is
bit-for-bit unchanged when multi-tenancy is off.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

import jax.numpy as jnp

from ..tensor.tensor import apply_op

__all__ = ["lora_epilogue", "activate", "active_sites", "apply_site"]


def lora_epilogue(x, a_pool, b_pool, rows):
    """Low-rank delta for a batch of rows against paged A/B pools.

    ``x``: ``[B, S, D_in]`` activations (any float dtype).
    ``a_pool``: ``[P, D_in, r]`` adapter A pages (bf16; page 0 zeros).
    ``b_pool``: ``[P, r, D_out]`` adapter B pages (bf16; page 0 zeros).
    ``rows``: ``[B]`` int32 adapter-page id per batch row.

    Returns ``[B, S, D_out]`` in ``x.dtype``.  The gathered pages are cast
    up to the activation dtype BEFORE the matmuls so an f32 model gets f32
    accumulation (bf16 -> f32 is exact), keeping engine-vs-solo runs
    bitwise comparable as long as both read the same bf16 page bits.
    """
    a = jnp.take(a_pool, rows, axis=0).astype(x.dtype)  # [B, D_in, r]
    b = jnp.take(b_pool, rows, axis=0).astype(x.dtype)  # [B, r, D_out]
    u = jnp.einsum("bsd,bdr->bsr", x, a)
    return jnp.einsum("bsr,bro->bso", u, b)


class _Ctx:
    __slots__ = ("sites", "rows")

    def __init__(self, sites, rows):
        self.sites = sites  # {site: (a_pool, b_pool)} raw arrays/tracers
        self.rows = rows    # [B] int32 raw array/tracer


_tls = threading.local()


def _current():
    return getattr(_tls, "ctx", None)


@contextmanager
def activate(site_pools, rows):
    """Make ``site_pools`` ({site: (a_pool, b_pool)}) + per-row page ids
    visible to ``apply_site`` for the duration of the block.  Used INSIDE
    jitted functions at trace time, so the pools/rows may be tracers; the
    context is thread-local because tracing happens in the caller's
    thread."""
    prev = _current()
    _tls.ctx = _Ctx(dict(site_pools), rows)
    try:
        yield
    finally:
        _tls.ctx = prev


def active_sites():
    """Site names visible in the current context ('' when inactive)."""
    ctx = _current()
    return frozenset(ctx.sites) if ctx is not None else frozenset()


def apply_site(site, x):
    """The hook model forwards call: low-rank delta Tensor for ``site``
    computed from Tensor ``x``, or ``None`` when no pool context is active
    (the common single-tenant case — zero trace-graph change)."""
    ctx = _current()
    if ctx is None:
        return None
    ab = ctx.sites.get(site)
    if ab is None:
        return None
    a_pool, b_pool = ab
    rows = ctx.rows
    return apply_op(lambda h: lora_epilogue(h, a_pool, b_pool, rows),
                    (x,), name=f"lora_{site}")
