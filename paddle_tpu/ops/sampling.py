"""Fused on-device token sampling + speculative-decoding acceptance.

One sampling implementation for every decode surface: the solo compiled
``generate`` loop (``models/generation._select``), the serving engine's
per-slot ``_select_rows``, and the speculative verify programs.  Everything
here runs INSIDE the compiled decode/verify step — temperature, top-k and
top-p masking, the categorical draw, and the spec-decode accept/residual
sampling all stay on device, so the only thing that crosses the host
tunnel per step is the token ids.

Per-row knobs ride as device ARRAYS (one entry per batch slot), so slots
with different sampling settings share one compiled program.  ``top_k`` is
per-row too: the k-th largest value is read out of the descending sort the
top-p mask needs anyway (``take_along_axis`` at index ``k-1``), so a
per-slot k never changes the program shape — the restriction the serving
engine used to document is gone.

Contracts the repo's parity tests pin down:

- greedy rows are a bare ``argmax`` — bitwise identical to
  ``generation._select`` and to the pre-fusion ``_select_rows``;
- the masking order is explicit token-mask -> temperature -> top-k ->
  top-p (top-p renormalizes over the top-k survivors), matching
  ``generation._select``; masks apply only where enabled (k in [1, V),
  p < 1, token mask all-True rows untouched), so disabled knobs are
  exact no-ops;
- ``spec_accept``'s greedy path accepts the longest draft prefix that
  matches the verifier's argmax ladder — by construction the emitted
  tokens are the verifier's own argmaxes, which is what makes speculative
  greedy decoding bitwise identical to non-speculative greedy.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["mask_logits", "sample_rows", "spec_accept"]


def mask_logits(logits, temperature, top_k, top_p, token_mask=None):
    """Temperature/top-k/top-p masking, vectorized per row.

    logits ``[B, V]``; ``temperature``/``top_p`` f32 ``[B]``; ``top_k``
    int32 ``[B]`` (0, or >= V, disables).  Returns f32 logits with
    masked-out entries at ``-inf`` — feed to ``jax.random.categorical``
    (which normalizes) or ``softmax``.

    ``token_mask`` (optional bool ``[B, V]``) is the EXPLICIT mask path
    used by constrained decoding: False entries are forced to ``-inf``
    before top-k/top-p, so the constraint shrinks the candidate set the
    statistical knobs then act on.  An all-True mask is an exact no-op
    (``jnp.where`` returns the untouched lane), preserving bitwise parity
    for unconstrained rows.
    """
    V = logits.shape[-1]
    lt = logits.astype(jnp.float32) / jnp.maximum(temperature, 1e-6)[:, None]
    if token_mask is not None:
        lt = jnp.where(token_mask, lt, -jnp.inf)
    k = jnp.asarray(top_k, jnp.int32)
    use_k = (k > 0) & (k < V)
    # k-th largest value per row; masking by VALUE (< kth) keeps ties at
    # the threshold, exactly like generation._select's lax.top_k variant
    sorted_lt = jnp.sort(lt, axis=-1)[..., ::-1]
    kth = jnp.take_along_axis(
        sorted_lt, jnp.clip(k - 1, 0, V - 1)[:, None], axis=-1)
    lt = jnp.where(use_k[:, None] & (lt < kth), -jnp.inf, lt)
    # top-p over the top-k SURVIVORS (re-sort: the -inf entries must fall
    # out of the cumulative mass, generation._select's order of operations)
    use_p = top_p < 1.0
    sorted_lt = jnp.sort(lt, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_lt, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep the smallest set with cumulative prob >= top_p (always >= 1 tok)
    cutoff_idx = jnp.sum(cum < top_p[:, None], axis=-1, keepdims=True)
    cutoff = jnp.take_along_axis(sorted_lt, cutoff_idx, axis=-1)
    return jnp.where(use_p[:, None] & (lt < cutoff), -jnp.inf, lt)


def sample_rows(logits, key, do_sample, temperature, top_k, top_p,
                token_mask=None):
    """Per-row token selection: logits ``[B, V]`` -> int32 ids ``[B]``.

    Each row carries its own ``(do_sample, temperature, top_k, top_p)``;
    greedy rows take the raw argmax (no masking touches them), sampled
    rows draw categorically from the masked distribution.

    ``token_mask`` (bool ``[B, V]``) constrains BOTH paths: greedy rows
    argmax over the masked logits (a constrained greedy row must emit an
    allowed token), and sampled rows inherit the mask through
    :func:`mask_logits`.  Rows with an all-True mask are untouched.
    """
    greedy_src = logits if token_mask is None else jnp.where(
        token_mask, logits, -jnp.inf)
    greedy = jnp.argmax(greedy_src, axis=-1).astype(jnp.int32)
    masked = mask_logits(logits, temperature, top_k, top_p, token_mask)
    sampled = jax.random.categorical(key, masked, axis=-1).astype(jnp.int32)
    return jnp.where(do_sample, sampled, greedy)


def spec_accept(logits, drafts, key, do_sample, temperature, top_k, top_p):
    """Speculative-decoding accept/rollback decision, fully on device.

    ``logits`` ``[B, K+1, V]`` is the verify pass's scoring ladder: column
    ``i`` is the model's next-token distribution GIVEN the context plus
    the first ``i`` draft tokens (the verify input is
    ``[last_token, draft_0 .. draft_{K-1}]``, so every column conditions
    only on accepted-or-earlier tokens).  ``drafts`` ``[B, K]`` int32.
    Sampling knobs are per-row arrays as in :func:`sample_rows`.

    Returns ``(out [B, K+1] int32, n_accept [B] int32)``: row ``b`` emits
    ``out[b, :n_accept[b] + 1]`` — the accepted draft tokens followed by
    one correction/bonus token — so every verify call advances every row
    by at least one token.  Columns past the emission count are the
    would-have-been tokens of rejected positions; callers ignore them.

    - Greedy rows accept the longest prefix where ``argmax(logits[:, i])
      == drafts[:, i]``; the emitted tokens are the argmax ladder itself,
      hence bitwise-identical to non-speculative greedy decoding.
    - Sampled rows run standard rejection sampling against the drafter's
      ONE-HOT proposal (the n-gram drafter is deterministic): draft ``i``
      is accepted with probability ``p_i(draft_i)`` under the masked
      target distribution; the first rejection resamples from the
      residual (target with the rejected token zeroed, renormalized —
      ``norm(max(p - q, 0))`` for one-hot ``q``), and a fully accepted
      run samples the bonus token from the last column.  The emitted
      token distribution is exactly the non-speculative sampler's.
    """
    B, S, V = logits.shape
    K = S - 1
    # ---- greedy path: longest argmax-matching prefix
    ladder = jnp.argmax(logits, axis=-1).astype(jnp.int32)        # [B, K+1]
    g_match = (ladder[:, :K] == drafts).astype(jnp.int32)
    g_acc = jnp.sum(jnp.cumprod(g_match, axis=-1), axis=-1)       # [B]
    # ---- sampled path: one-hot-q rejection sampling on masked logits
    flat = mask_logits(
        logits.reshape(B * S, V),
        jnp.repeat(temperature, S), jnp.repeat(top_k, S),
        jnp.repeat(top_p, S))
    masked = flat.reshape(B, S, V)
    p = jax.nn.softmax(masked, axis=-1)
    p_draft = jnp.take_along_axis(
        p[:, :K], drafts[..., None], axis=-1)[..., 0]             # [B, K]
    key_u, key_r = jax.random.split(key)
    u = jax.random.uniform(key_u, (B, K), jnp.float32)
    s_match = (u < p_draft).astype(jnp.int32)
    s_acc = jnp.sum(jnp.cumprod(s_match, axis=-1), axis=-1)       # [B]
    n_acc = jnp.where(do_sample, s_acc, g_acc).astype(jnp.int32)
    # correction/bonus token for sampled rows, drawn at column n_acc:
    # a rejection (n_acc < K) zeroes the rejected draft out of the
    # residual; a clean run (n_acc == K) samples the bonus unmodified
    col = jnp.take_along_axis(masked, n_acc[:, None, None], axis=1)[:, 0]
    rej_draft = jnp.take_along_axis(
        drafts, jnp.clip(n_acc, 0, K - 1)[:, None], axis=-1)[:, 0]
    rejected = n_acc < K
    col = jnp.where(
        rejected[:, None] & (jnp.arange(V)[None, :] == rej_draft[:, None]),
        -jnp.inf, col)
    corr = jax.random.categorical(key_r, col, axis=-1).astype(jnp.int32)
    s_out = jnp.concatenate(
        [drafts, jnp.zeros((B, 1), jnp.int32)], axis=-1)          # [B, K+1]
    s_out = jnp.where(
        jnp.arange(K + 1)[None, :] == n_acc[:, None], corr[:, None], s_out)
    out = jnp.where(do_sample[:, None], s_out, ladder)
    return out.astype(jnp.int32), n_acc
