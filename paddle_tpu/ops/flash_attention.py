"""Pallas TPU flash attention (forward + backward).

Reference gap: the snapshot's only fused attention is a single-device CUDA kernel
(`/root/reference/paddle/fluid/operators/fused/fused_attention_op.cu`, `fmha_ref.h`)
with no flash/online-softmax algorithm.  This is the TPU-native replacement: a
FlashAttention-2-style tiled kernel — online softmax over key blocks, O(S) memory,
logsumexp saved for a recompute-based backward — written against the MXU/VMEM model
(`/opt/skills/guides/pallas_guide.md`): [block_q, D] @ [D, block_k] contractions on
the MXU with f32 accumulators, K/V streamed block-by-block from VMEM.

Layout contract: paddle attention layout [B, S, H, D] at the API; kernels run on
[B*H, S, D].  Causal masking uses block-level early exit (upper-triangular key
blocks are never visited) plus an iota mask on the diagonal block.

On CPU (tests / debugging) the kernels run in Pallas interpret mode automatically.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
# TPU vector lanes: the lse/dsum residuals are broadcast along a 128-lane minor dim
# so their block shapes satisfy the mosaic (8, 128) tiling rule (same trick as
# jax.experimental.pallas.ops.tpu.flash_attention MIN_BLOCK_SIZE).
LANES = 128


def _interpret_default():
    return jax.default_backend() not in ("tpu", "axon")


def _compiler_params(interpret):
    """All three kernels write disjoint output blocks along both grid axes."""
    if interpret:
        return None
    return pltpu.CompilerParams(dimension_semantics=("parallel", "parallel"))


# --------------------------------------------------------------------- forward
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal, bq, bk, seq_q, seq_k):
    qi = pl.program_id(1)
    # keep matmul inputs in their storage dtype (bf16): the MXU contracts
    # bf16 x bf16 -> f32 at full rate; upcasting first forces f32 passes
    q = q_ref[0]  # [bq, D]
    nkb = pl.cdiv(seq_k, bk)
    # bottom-right alignment (matches the dense path): query i attends kpos <= i + off
    off = seq_k - seq_q
    if causal:
        # visit key blocks only up to (and including) this q block's diagonal
        nkb = jnp.minimum(nkb, ((qi + 1) * bq + off + bk - 1) // bk)

    def body(kj, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(kj * bk, bk), :]  # [bk, D]
        v = v_ref[0, pl.ds(kj * bk, bk), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale  # [bq, bk] f32
        if causal:
            qpos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(qpos + off >= kpos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.dot(p.astype(v.dtype), v,
                                   preferred_element_type=jnp.float32)
        return m_new, l, acc

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, q.shape[-1]), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nkb, body, (m0, l0, acc0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = jnp.broadcast_to(m + jnp.log(l), (bq, LANES))


def _flash_fwd(q, k, v, causal, scale, bq, bk, interpret):
    BH, Sq, D = q.shape
    Sk = k.shape[1]
    grid = (BH, Sq // bq)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal, bq=bq, bk=bk,
                          seq_q=Sq, seq_k=Sk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, Sk, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, Sk, D), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, bq, LANES), lambda bh, qi: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((BH, Sq, LANES), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=_compiler_params(interpret),
    )(q, k, v)
    return o, lse


# -------------------------------------------------------------------- backward
def _dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, *rest,
               scale, causal, bq, bk, seq_q, seq_k):
    # rest = (dlse_ref, dq_ref) for the lse-returning variant (ring combine
    # backprop), else (dq_ref,): the lse cotangent adds p * dlse to ds
    if len(rest) == 2:
        dlse_ref, dq_ref = rest
        dlse = dlse_ref[0][:, :1]
    else:
        (dq_ref,) = rest
        dlse = 0.0
    qi = pl.program_id(1)
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0][:, :1]     # [bq, 1] (lanes-broadcast residual)
    dsum = jnp.sum(do.astype(jnp.float32) * o_ref[0].astype(jnp.float32),
                   axis=-1, keepdims=True) - dlse
    nkb = pl.cdiv(seq_k, bk)
    off = seq_k - seq_q
    if causal:
        nkb = jnp.minimum(nkb, ((qi + 1) * bq + off + bk - 1) // bk)

    def body(kj, dq):
        k = k_ref[0, pl.ds(kj * bk, bk), :]
        v = v_ref[0, pl.ds(kj * bk, bk), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(qpos + off >= kpos, s, NEG_INF)
        p = jnp.exp(s - lse)                       # [bq, bk] f32
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - dsum)).astype(k.dtype)
        return dq + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32) * scale

    dq = jax.lax.fori_loop(0, nkb, body,
                           jnp.zeros((bq, q.shape[-1]), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, *rest,
                scale, causal, bq, bk, seq_q, seq_k):
    if len(rest) == 3:
        dlse_ref, dk_ref, dv_ref = rest
    else:
        dlse_ref = None
        dk_ref, dv_ref = rest
    kj = pl.program_id(1)
    k = k_ref[0]   # [bk, D]
    v = v_ref[0]
    nqb = pl.cdiv(seq_q, bq)
    off = seq_k - seq_q
    start = jnp.maximum((kj * bk - off) // bq, 0) if causal else 0

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qi * bq, bq), :]
        do = do_ref[0, pl.ds(qi * bq, bq), :]
        o = o_ref[0, pl.ds(qi * bq, bq), :]
        lse = lse_ref[0, pl.ds(qi * bq, bq), :1]
        dsum = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                       axis=-1, keepdims=True)
        if dlse_ref is not None:
            dsum = dsum - dlse_ref[0, pl.ds(qi * bq, bq), :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(qpos + off >= kpos, s, NEG_INF)
        p = jnp.exp(s - lse)                       # [bq, bk] f32
        pc = p.astype(do.dtype)
        dv = dv + jax.lax.dot_general(pc, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - dsum)).astype(q.dtype)
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32) * scale
        return dk, dv

    D = k.shape[-1]
    dk0 = jnp.zeros((bk, D), jnp.float32)
    dv0 = jnp.zeros((bk, D), jnp.float32)
    dk, dv = jax.lax.fori_loop(start, nqb, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, causal, scale, bq, bk, interpret, dlse=None):
    BH, Sq, D = q.shape
    Sk = k.shape[1]
    lse_spec = pl.BlockSpec((1, bq, LANES), lambda bh, qi: (bh, qi, 0))
    lse_full = pl.BlockSpec((1, Sq, LANES), lambda bh, kj: (bh, 0, 0))
    dq_extra_in = [lse_spec] if dlse is not None else []
    dq_args = (q, k, v, o, do, lse) + ((dlse,) if dlse is not None else ())

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal, bq=bq, bk=bk,
                          seq_q=Sq, seq_k=Sk),
        grid=(BH, Sq // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, Sk, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, Sk, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
            lse_spec,
        ] + dq_extra_in,
        out_specs=pl.BlockSpec((1, bq, D), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, Sq, D), q.dtype),
        interpret=interpret,
        compiler_params=_compiler_params(interpret),
    )(*dq_args)

    dkv_extra_in = [lse_full] if dlse is not None else []
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal, bq=bq, bk=bk,
                          seq_q=Sq, seq_k=Sk),
        grid=(BH, Sk // bk),
        in_specs=[
            pl.BlockSpec((1, Sq, D), lambda bh, kj: (bh, 0, 0)),
            pl.BlockSpec((1, bk, D), lambda bh, kj: (bh, kj, 0)),
            pl.BlockSpec((1, bk, D), lambda bh, kj: (bh, kj, 0)),
            pl.BlockSpec((1, Sq, D), lambda bh, kj: (bh, 0, 0)),
            pl.BlockSpec((1, Sq, D), lambda bh, kj: (bh, 0, 0)),
            lse_full,
        ] + dkv_extra_in,
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda bh, kj: (bh, kj, 0)),
            pl.BlockSpec((1, bk, D), lambda bh, kj: (bh, kj, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Sk, D), k.dtype),
            jax.ShapeDtypeStruct((BH, Sk, D), v.dtype),
        ],
        interpret=interpret,
        compiler_params=_compiler_params(interpret),
    )(*dq_args)
    return dq, dk, dv


# ---------------------------------------------------------------- public entry
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_bhsd(q, k, v, causal, scale, bq, bk, interpret):
    o, _ = _flash_fwd(q, k, v, causal, scale, bq, bk, interpret)
    return o


def _flash_bhsd_fwd(q, k, v, causal, scale, bq, bk, interpret):
    o, lse = _flash_fwd(q, k, v, causal, scale, bq, bk, interpret)
    return o, (q, k, v, o, lse)


def _flash_bhsd_bwd(causal, scale, bq, bk, interpret, res, do):
    q, k, v, o, lse = res
    return _flash_bwd(q, k, v, o, lse, do, causal, scale, bq, bk, interpret)


_flash_bhsd.defvjp(_flash_bhsd_fwd, _flash_bhsd_bwd)


# lse-returning entry for blockwise/ring combines: (o, lse) with a backward
# that honors the lse cotangent (d s_ij += p_ij * dlse_i, folded into dsum)
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_bhsd_lse(q, k, v, causal, scale, bq, bk, interpret):
    o, lse = _flash_fwd(q, k, v, causal, scale, bq, bk, interpret)
    return o, lse[..., 0]


def _flash_bhsd_lse_fwd(q, k, v, causal, scale, bq, bk, interpret):
    o, lse = _flash_fwd(q, k, v, causal, scale, bq, bk, interpret)
    return (o, lse[..., 0]), (q, k, v, o, lse)


def _flash_bhsd_lse_bwd(causal, scale, bq, bk, interpret, res, cts):
    q, k, v, o, lse = res
    do, dlse0 = cts
    dlse = jnp.broadcast_to(dlse0[..., None].astype(jnp.float32), lse.shape)
    return _flash_bwd(q, k, v, o, lse, do, causal, scale, bq, bk, interpret,
                      dlse=dlse)


_flash_bhsd_lse.defvjp(_flash_bhsd_lse_fwd, _flash_bhsd_lse_bwd)


def flash_attention_with_lse(q, k, v, causal=False, scale=None, block_q=None,
                             block_k=None, interpret=None):
    """Like flash_attention but also returns the per-query logsumexp
    [B, H, S] — the hook for blockwise combines (ring attention)."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    if interpret is None:
        interpret = _interpret_default()
    bq = min(block_q, Sq) if block_q else _auto_block(Sq)
    bk = min(block_k, Sk) if block_k else _auto_block(Sk)
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    to_bhsd = lambda x: jnp.swapaxes(x, 1, 2).reshape(B * H, x.shape[1], D)  # noqa: E731
    o, lse = _flash_bhsd_lse(to_bhsd(q), to_bhsd(k), to_bhsd(v),
                             causal, float(scale), bq, bk, interpret)
    return (jnp.swapaxes(o.reshape(B, H, Sq, D), 1, 2),
            lse.reshape(B, H, Sq))


def supports_seq(seq):
    """Shapes the kernel handles without degenerate blocks (callers use this to
    gate flash vs dense SDPA)."""
    return seq % 128 == 0 or (seq <= 512 and seq % 8 == 0)


def _auto_block(seq):
    """Largest power-of-two block <= 512 dividing seq: big blocks amortize the
    per-grid-step overhead (measured on v5e: 512 beats 128 by ~25% at S=2048).
    Short sequences (<=512, 8-aligned) run as a single block; anything else is
    an error — tiny blocks would silently be 100x slower than dense SDPA."""
    for b in (512, 256, 128):
        if seq % b == 0:
            return b
    if seq <= 512 and seq % 8 == 0:
        return seq
    raise ValueError(
        f"flash_attention: sequence length {seq} is not divisible by a "
        f">=128 block (and too long for a single block) — pad the sequence "
        f"or use the dense SDPA path")


def flash_attention(q, k, v, causal=False, scale=None, block_q=None, block_k=None,
                    interpret=None):
    """q/k/v: [B, S, H, D] (paddle layout).  Returns [B, S, H, D].

    Requires S divisible by the block sizes and equal q/k head counts (the GQA
    repeat happens in the caller).  Differentiable via a recompute-based
    FlashAttention-2 backward.  Block sizes default to the largest power of two
    <= 512 dividing the sequence.
    """
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    if causal and Sq > Sk:
        # queries 0..Sq-Sk-1 would attend zero keys (all-masked rows -> 0/0); the
        # dense path is the right tool for that degenerate shape
        raise ValueError(
            f"flash_attention(causal=True) requires Sq <= Sk, got Sq={Sq} Sk={Sk}; "
            "use the dense SDPA path")
    if interpret is None:
        interpret = _interpret_default()
    if block_q is None and block_k is None:
        from ..incubate import autotune as _autotune

        if _autotune.kernel_autotune_enabled():
            key = (Sq, Sk, D, bool(causal))
            cached = _autotune.flash_attention_block_cache.get(key)
            if cached is None and not isinstance(q, jax.core.Tracer):
                # first concrete call with this signature: measure candidates
                # (one-time compile cost per config, the phi autotune contract)
                sc = 1.0 / (D ** 0.5) if scale is None else float(scale)
                cached = _autotune.tune_flash_attention(
                    jnp.swapaxes(jnp.asarray(q), 1, 2).reshape(B * H, Sq, D),
                    jnp.swapaxes(jnp.asarray(k), 1, 2).reshape(B * H, Sk, D),
                    jnp.swapaxes(jnp.asarray(v), 1, 2).reshape(B * H, Sk, D),
                    causal, sc)
            if cached is not None:
                block_q, block_k = cached
    bq = min(block_q, Sq) if block_q else _auto_block(Sq)
    bk = min(block_k, Sk) if block_k else _auto_block(Sk)
    if Sq % bq or Sk % bk:
        raise ValueError(f"seq lens ({Sq},{Sk}) must divide block sizes ({bq},{bk})")
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    to_bhsd = lambda x: jnp.swapaxes(x, 1, 2).reshape(B * H, x.shape[1], D)
    o = _flash_bhsd(to_bhsd(q), to_bhsd(k), to_bhsd(v),
                    causal, float(scale), bq, bk, interpret)
    return jnp.swapaxes(o.reshape(B, H, Sq, D), 1, 2)
