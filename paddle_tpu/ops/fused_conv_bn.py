"""Pallas TPU fused 1x1-conv + BatchNorm kernel family (ResNet fast path).

Reference analog: the conv+BN fusion the reference applies at inference
(`/root/reference/paddle/fluid/framework/ir/conv_bn_fuse_pass.cc`) and the
cuDNN-style fused BN-stats/apply epilogues its CUDA kernels rely on
(`/root/reference/paddle/phi/kernels/gpu/batch_norm_kernel.cu` saved-stats
contract).  This is the TRAINING-mode analog, designed for the TPU memory
system rather than translated.

The measured ResNet-50 train step is HBM-bound end to end (44.8 GB/step at
~780 GB/s; conv MXU time is ~17.5 ms of a 47.5 ms step — RESNET_BREAKDOWN.md).
Every win here is a removed full-tensor memory pass:

- forward "fold": the PREVIOUS BatchNorm's normalize + ReLU is applied on the
  fly to the conv input as it streams from HBM, so the normalized activation
  is never materialized (XLA cannot fuse producers into convolution inputs).
  The conv output's per-channel sum/sumsq accumulate in the same kernel's
  epilogue.  The un-folded forward stays on XLA: its conv+stats fusion is
  already minimal-traffic there.
- backward: ONE kernel computes dy_tot (the sum/sumsq backward terms), dX,
  dW, and the fold backward (ReLU mask, per-channel dscale/doffset reduces)
  sharing a single HBM read of each operand.  XLA autodiff emits separate
  dW / dX convolution fusions that EACH re-read dy and y (the profiled
  ~1.3-1.4 ms multiply_reduce fusions).

Layout contract: NHWC with W padded to a multiple of 8 ("W'") so every
[1, bh, W', C] block reshapes to 2-D MXU rows free of sublane re-tiling; pad
columns (w >= wv) hold zeros, enforced by in-kernel masks wherever an affine
offset could make them non-zero.  dy_tot is formed in bf16 (the stats terms
are per-channel and small relative to dy; measured −12% kernel time vs f32).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._prng import interpret_default as _interpret_default


def _params(interpret, n=2):
    return None if interpret else pltpu.CompilerParams(
        dimension_semantics=("arbitrary",) * n)


def _pick_bh(H, Wp, per_row_bytes, budget=3 * 1024 * 1024):
    """Largest divisor of H whose block stays under ~budget bytes/step,
    leaving VMEM room for Pallas double-buffering of the streamed blocks."""
    best = 1
    for bh in range(1, H + 1):
        if H % bh == 0 and bh * Wp * per_row_bytes <= budget:
            best = bh
    return best


def _row_mask(M, Wp, Wv):
    w_id = jax.lax.broadcasted_iota(jnp.int32, (M, 1), 0) % Wp
    return (w_id < Wv).astype(jnp.float32)


# ---------------------------------------------------------------- forward

def _fwd_kernel(x_ref, w_ref, s_ref, o_ref, y_ref, st_ref,
                *, relu, K, Wp, Wv):
    j = pl.program_id(1)
    _, bh = x_ref.shape[0], x_ref.shape[1]
    Cout = y_ref.shape[-1]
    M = bh * Wp
    x2 = x_ref[...].reshape(M, K)
    a = x2.astype(jnp.float32) * s_ref[...].reshape(K) + o_ref[...].reshape(K)
    if relu:
        a = jnp.maximum(a, 0.0)
    if Wp != Wv:
        a = a * _row_mask(M, Wp, Wv)
    x2 = a.astype(x2.dtype)
    acc = jax.lax.dot_general(x2, w_ref[...], (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    y = acc.astype(y_ref.dtype)
    y_ref[...] = y.reshape(y_ref.shape)
    # stats on the ROUNDED output (what downstream consumers read), matching
    # the composed batch_norm path which reduces the materialized bf16 y
    yf = y.astype(jnp.float32)
    st = jnp.stack([jnp.broadcast_to(jnp.sum(yf, 0)[None, :], (8, Cout)),
                    jnp.broadcast_to(jnp.sum(yf * yf, 0)[None, :], (8, Cout))],
                   0)[None]

    @pl.when(j == 0)
    def _init():
        st_ref[...] = jnp.zeros_like(st_ref)

    st_ref[...] += st


def _fwd_fold(x, w, scale, offset, relu, Wv, interpret):
    N, H, Wp, K = x.shape
    Cout = w.shape[-1]
    bh = _pick_bh(H, Wp, (K + Cout) * 2 + Cout * 4)
    gi, gj = N, H // bh
    kern = functools.partial(_fwd_kernel, relu=relu, K=K, Wp=Wp, Wv=Wv)
    y, stp = pl.pallas_call(
        kern,
        grid=(gi, gj),
        in_specs=[
            pl.BlockSpec((1, bh, Wp, K), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((K, Cout), lambda i, j: (0, 0)),
            pl.BlockSpec((1, K), lambda i, j: (0, 0)),
            pl.BlockSpec((1, K), lambda i, j: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bh, Wp, Cout), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, 2, 8, Cout), lambda i, j: (i, 0, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, H, Wp, Cout), x.dtype),
            jax.ShapeDtypeStruct((N, 2, 8, Cout), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=_params(interpret),
    )(x, w.reshape(K, Cout), scale, offset)
    s = jnp.sum(stp[:, :, 0, :], axis=0)
    return y, s[0], s[1]


def _fwd_plain(x, w):
    """No-fold forward: XLA's conv + fused sum/sumsq epilogue is already
    minimal-traffic; only the backward needs the combined kernel."""
    K, Cout = w.shape[2], w.shape[3]
    y = jax.lax.dot_general(x, w.reshape(K, Cout), (((3,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32).astype(x.dtype)
    yf = y.astype(jnp.float32)
    return y, jnp.sum(yf, (0, 1, 2)), jnp.sum(yf * yf, (0, 1, 2))


# ---------------------------------------------------------------- backward

def _bwd_kernel(dy_ref, y_ref, x_ref, wt_ref, s_ref, o_ref, ds_ref,
                dx_ref, dw_ref, dso_ref, *, fold, relu, K, Wp, Wv):
    i = pl.program_id(0)
    j = pl.program_id(1)
    _, bh = dy_ref.shape[0], dy_ref.shape[1]
    Cout = dy_ref.shape[-1]
    M = bh * Wp
    dy2 = dy_ref[...].reshape(M, Cout)
    y2 = y_ref[...].reshape(M, Cout)
    mask = _row_mask(M, Wp, Wv) if Wp != Wv else None
    # bf16 dy_tot: ds1/ds2 are per-channel and small next to dy
    dyt = dy2 + (ds_ref[0, :].astype(dy2.dtype)
                 + y2 * (2.0 * ds_ref[1, :]).astype(dy2.dtype))
    if mask is not None:
        dyt = dyt * mask.astype(dyt.dtype)
    x2 = x_ref[...].reshape(M, K)
    if fold:
        a = x2.astype(jnp.float32) * s_ref[...].reshape(K) + o_ref[...].reshape(K)
        xf = jnp.maximum(a, 0.0) if relu else a
        if mask is not None:
            xf = xf * mask
        xf = xf.astype(x2.dtype)
    else:
        xf = x2
    dw = jax.lax.dot_general(xf, dyt, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)

    first = jnp.logical_and(i == 0, j == 0)

    @pl.when(first)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)
        dso_ref[...] = jnp.zeros_like(dso_ref)

    dw_ref[...] += dw
    dxf = jax.lax.dot_general(dyt, wt_ref[...], (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    if fold:
        g = jnp.where(a > 0.0, dxf, 0.0) if relu else dxf
        dx_ref[...] = (g * s_ref[...].reshape(K)).astype(dx_ref.dtype).reshape(dx_ref.shape)
        dsc = jnp.sum(g * x2.astype(jnp.float32), axis=0)
        dof = jnp.sum(g, axis=0)
        dso_ref[...] += jnp.stack([jnp.broadcast_to(dsc[None, :], (8, K)),
                                   jnp.broadcast_to(dof[None, :], (8, K))], 0)
    else:
        dx_ref[...] = dxf.astype(dx_ref.dtype).reshape(dx_ref.shape)


def _bwd_call(dy, y, x, w, scale, offset, ds1, ds2, relu, Wv, interpret):
    N, H, Wp, K = x.shape
    Cout = w.shape[-1]
    fold = scale is not None
    if not fold:
        scale = jnp.zeros((1, K), jnp.float32)
        offset = jnp.zeros((1, K), jnp.float32)
    wt = w.reshape(K, Cout).T
    ds = jnp.concatenate([ds1.reshape(1, Cout).astype(jnp.float32),
                          ds2.reshape(1, Cout).astype(jnp.float32)], 0)
    bh = _pick_bh(H, Wp, (2 * Cout + 2 * K) * 2 + (Cout + K) * 2)
    gi, gj = N, H // bh
    kern = functools.partial(_bwd_kernel, fold=fold, relu=relu, K=K, Wp=Wp, Wv=Wv)
    dx, dwp, dsop = pl.pallas_call(
        kern,
        grid=(gi, gj),
        in_specs=[
            pl.BlockSpec((1, bh, Wp, Cout), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, bh, Wp, Cout), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, bh, Wp, K), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((Cout, K), lambda i, j: (0, 0)),
            pl.BlockSpec((1, K), lambda i, j: (0, 0)),
            pl.BlockSpec((1, K), lambda i, j: (0, 0)),
            pl.BlockSpec((2, Cout), lambda i, j: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bh, Wp, K), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((K, Cout), lambda i, j: (0, 0)),
            pl.BlockSpec((2, 8, K), lambda i, j: (0, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, H, Wp, K), x.dtype),
            jax.ShapeDtypeStruct((K, Cout), jnp.float32),
            jax.ShapeDtypeStruct((2, 8, K), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=_params(interpret),
    )(dy, y, x, wt, scale, offset, ds)
    dw = dwp.reshape(1, 1, K, Cout)
    if fold:
        return dx, dw, dsop[0, :1, :], dsop[1, :1, :]
    return dx, dw, None, None


# ---------------------------------------------------------------- custom vjp

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _conv1x1_bn(x, w, scale, offset, relu, Wv):
    return _conv1x1_bn_fwd(x, w, scale, offset, relu, Wv)[0]


def _conv1x1_bn_fwd(x, w, scale, offset, relu, Wv):
    if scale is None:
        y, s1, s2 = _fwd_plain(x, w)
    else:
        y, s1, s2 = _fwd_fold(x, w, scale, offset, relu, Wv,
                              _interpret_default())
    return (y, s1, s2), (x, w, scale, offset, y)


def _conv1x1_bn_bwd(relu, Wv, res, cts):
    x, w, scale, offset, y = res
    dy, ds1, ds2 = cts
    dx, dw, dsc, dof = _bwd_call(dy, y, x, w, scale, offset, ds1, ds2, relu,
                                 Wv, _interpret_default())
    return dx, dw.astype(w.dtype), dsc, dof


_conv1x1_bn.defvjp(_conv1x1_bn_fwd, _conv1x1_bn_bwd)


def supported(x_shape, w_shape):
    """Fast-path admission: 4-D NHWC, 1x1 kernel, lane-aligned channels,
    W a multiple of 8 (the caller pads)."""
    if len(x_shape) != 4 or len(w_shape) != 4:
        return False
    N, H, Wp, K = x_shape
    kh, kw, K2, Cout = w_shape
    return (kh == 1 and kw == 1 and K2 == K and Wp % 8 == 0
            and K % 64 == 0 and Cout % 64 == 0 and N >= 1)


def conv1x1_bn(x, w, scale=None, offset=None, relu=True, wv=None):
    """y = conv1x1(act(x*scale+offset)), plus per-channel (sum, sumsq) of y.

    x: [N, H, W', Cin] (W' % 8 == 0; columns >= wv hold zeros).  w: [1, 1,
    Cin, Cout].  scale/offset: f32 [1, Cin] fold of the previous BatchNorm
    (None = input already normalized; no fold, XLA forward).  Returns
    (y, s1, s2); s1/s2 are f32 [Cout] sums over valid columns.  The backward
    runs the combined Pallas kernel in all cases.
    """
    wv = wv or x.shape[2]
    if not supported(x.shape, w.shape):
        raise ValueError(f"conv1x1_bn: unsupported shapes {x.shape} {w.shape}")
    return _conv1x1_bn(x, w, scale, offset, bool(relu), int(wv))
