"""Sequence/context parallelism primitives: ring attention and Ulysses.

Reference gap (SURVEY.md §5.7): the Paddle snapshot has NO sequence/context
parallelism of any kind (tree-wide grep: zero hits for ring_attention /
context_parallel / ulysses) — these are designed fresh for TPU:

- `ring_attention`: blockwise attention over a sequence-sharded axis.  Each device
  holds a [B, S/n, H, D] shard of q/k/v; k/v blocks rotate around the ring via
  `lax.ppermute` (riding ICI neighbor links) while each device accumulates its
  local q block's attention with the online-softmax combine (order-independent,
  so the rotation order doesn't matter).  Causal masking is block-level: blocks
  strictly in the future are skipped with `lax.cond` (no compute, no NaNs from
  all-masked rows), the diagonal block gets an iota mask.  O(S/n) memory per
  device; autodiff flows through cond + ppermute, giving the reverse ring in the
  backward pass automatically.

- `ulysses_attention` (DeepSpeed-Ulysses style): `lax.all_to_all` swaps the
  sharded axis from sequence to heads, runs DENSE/flash attention on the full
  sequence with H/n local heads, and swaps back.  Cheaper than a ring when
  H % n == 0 and the full-sequence scores fit (two all-to-alls vs n-1 permutes).

Both must be called INSIDE jit/shard_map with the sequence axis sharded over
`axis_name` (the 'sep' axis of paddle_tpu.distributed.build_mesh).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _block_scores(q, k, scale):
    # q: [B, Sq, H, D], k: [B, Sk, H, D] -> [B, H, Sq, Sk] f32
    return jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                      k.astype(jnp.float32)) * scale


def ring_attention(q, k, v, axis_name: str, causal: bool = False, scale=None,
                   use_flash: bool = False):
    """Blockwise ring attention.  q/k/v: local shards [B, S/n, H, D] inside
    shard_map over `axis_name`.  Returns the local output shard [B, S/n, H, D].

    use_flash=True computes each visited block with the Pallas flash kernel
    (O(block) memory instead of materializing [B,H,S/n,S/n] scores) and
    combines blocks by their logsumexp — the long-context configuration."""
    if use_flash:
        return _ring_attention_flash(q, k, v, axis_name, causal, scale)
    n = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    B, Sl, H, D = q.shape
    if scale is None:
        scale = 1.0 / (D ** 0.5)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(t, carry):
        m, l, acc, kb, vb = carry
        src = (me - t) % n  # whose k/v block we hold at step t

        def visible(_):
            s = _block_scores(q, kb, scale)  # [B, H, Sq, Sk]
            if causal:
                qpos = me * Sl + lax.broadcasted_iota(jnp.int32, (Sl, Sl), 0)
                kpos = src * Sl + lax.broadcasted_iota(jnp.int32, (Sl, Sl), 1)
                mask = (qpos >= kpos)[None, None]
                s2 = jnp.where(mask, s, NEG_INF)
            else:
                s2 = s
            m_new = jnp.maximum(m, jnp.max(s2, axis=-1, keepdims=True))
            p = jnp.exp(s2 - m_new)
            corr = jnp.exp(m - m_new)
            l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
            acc_new = acc * corr + jnp.einsum(
                "bhqk,bkhd->bhqd", p, vb.astype(jnp.float32))
            return m_new, l_new, acc_new

        def hidden(_):
            return m, l, acc

        if causal:
            m, l, acc = lax.cond(src <= me, visible, hidden, None)
        else:
            m, l, acc = visible(None)

        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return m, l, acc, kb, vb

    m0 = jnp.full((B, H, Sl, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Sl, 1), jnp.float32)
    acc0 = jnp.zeros((B, H, Sl, D), jnp.float32)
    carry = (m0, l0, acc0, k, v)
    # python loop: n is static; each iteration is a distinct ppermute in the HLO
    for t in range(n):
        carry = step(t, carry)
    m, l, acc, _, _ = carry
    out = acc / l  # [B, H, Sq, D]
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)  # [B, Sq, H, D]


def _ring_attention_flash(q, k, v, axis_name, causal, scale):
    """Ring attention with the Pallas flash kernel per block.

    Each visited block yields (o_b, lse_b) from flash_attention_with_lse;
    blocks combine by the standard unnormalized online-softmax update keyed
    on lse (contribution o_b * exp(lse_b - m)).  Hidden blocks contribute
    lse=-1e30, whose weight underflows to exactly 0 once any real block has
    been seen — and causal rings always see the diagonal block.  Gradients
    flow through the flash kernel's lse-aware backward and the reverse
    ppermute automatically."""
    from .flash_attention import flash_attention_with_lse

    n = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    B, Sl, H, D = q.shape
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def flash_block(kb, vb, block_causal):
        o_b, lse_b = flash_attention_with_lse(q, kb, vb, causal=block_causal,
                                              scale=scale)
        # [B, Sl, H, D] / [B, H, Sl] -> combine layout [B, H, Sl, *]
        return jnp.swapaxes(o_b, 1, 2).astype(jnp.float32), lse_b[..., None]

    def step(t, carry):
        m, l, acc, kb, vb = carry
        src = (me - t) % n

        def full(_):
            return flash_block(kb, vb, False)

        def diag(_):
            return flash_block(kb, vb, True)

        def hidden(_):
            return (jnp.zeros((B, H, Sl, D), jnp.float32),
                    jnp.full((B, H, Sl, 1), NEG_INF, jnp.float32))

        if causal:
            case = jnp.where(src == me, 1, jnp.where(src < me, 2, 0))
            o_b, lse_b = lax.switch(case, [hidden, diag, full], None)
        else:
            o_b, lse_b = full(None)

        m_new = jnp.maximum(m, lse_b)
        corr = jnp.exp(m - m_new)
        w_b = jnp.exp(lse_b - m_new)
        l = l * corr + w_b
        acc = acc * corr + o_b * w_b
        m = m_new

        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return m, l, acc, kb, vb

    m0 = jnp.full((B, H, Sl, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Sl, 1), jnp.float32)
    acc0 = jnp.zeros((B, H, Sl, D), jnp.float32)
    carry = (m0, l0, acc0, k, v)
    for t in range(n):
        carry = step(t, carry)
    m, l, acc, _, _ = carry
    out = acc / jnp.maximum(l, 1e-30)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def ring_attention_global(q, k, v, causal=False, scale=None, sep_axis="sep",
                          use_flash=False):
    """Global-array entry point: q/k/v are [B, S, H, D] GLOBAL tracers inside
    a jitted step with an active mesh (sharding_ctx.mesh_scope — what
    ShardedTrainStep installs).  Shards S over `sep_axis` with a shard_map
    that is manual ONLY over that axis (axis_names={sep}), so dp/mp/sharding
    stay with the SPMD partitioner, and runs ring attention across the
    sequence shards.  Falls back to local dense attention when there is no
    mesh, no sep axis, or sep size 1 — same numerics, no communication."""
    mesh = None
    if isinstance(q, jax.core.Tracer):
        from ..distributed.sharding_ctx import current_mesh

        mesh = current_mesh()
    if mesh is None or sep_axis not in mesh.axis_names \
            or mesh.shape[sep_axis] == 1:
        B, S, H, D = q.shape
        sc = 1.0 / (D ** 0.5) if scale is None else scale
        s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * sc
        if causal:
            mask = jnp.tril(jnp.ones((S, S), bool))[None, None]
            s = jnp.where(mask, s, NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p,
                          v.astype(jnp.float32)).astype(q.dtype)
    from jax.sharding import PartitionSpec as P

    spec = P(None, sep_axis, None, None)
    fn = lambda a, b, c: ring_attention(a, b, c, sep_axis, causal=causal,  # noqa: E731
                                        scale=scale, use_flash=use_flash)
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names={sep_axis},
                         check_vma=False)(q, k, v)


def ulysses_attention(q, k, v, axis_name: str, causal: bool = False, scale=None,
                      attn_fn=None):
    """Ulysses alltoall attention.  q/k/v: local shards [B, S/n, H, D] inside
    shard_map over `axis_name`; needs H % n == 0.  `attn_fn(q, k, v)` runs the
    full-sequence attention on [B, S, H/n, D] (defaults to dense softmax;
    pass the Pallas flash kernel for long sequences)."""
    n = lax.axis_size(axis_name)
    B, Sl, H, D = q.shape
    if H % n != 0:
        raise ValueError(f"num_heads {H} not divisible by axis size {n}")
    if scale is None:
        scale = 1.0 / (D ** 0.5)

    # [B, S/n, H, D] -> [B, S, H/n, D]: split heads, concat sequence
    def seq2head(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def head2seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qg, kg, vg = seq2head(q), seq2head(k), seq2head(v)

    if attn_fn is None:
        s = _block_scores(qg, kg, scale)  # [B, h_loc, S, S]
        if causal:
            S = s.shape[-1]
            mask = jnp.tril(jnp.ones((S, S), bool))[None, None]
            s = jnp.where(mask, s, NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        og = jnp.einsum("bhqk,bkhd->bqhd", p, vg.astype(jnp.float32)).astype(q.dtype)
    else:
        og = attn_fn(qg, kg, vg)

    return head2seq(og)
