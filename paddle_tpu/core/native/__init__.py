"""Native runtime bindings (ctypes over paddle_tpu/core/native/native.cc).

The reference's native surface (layers 1-6 of SURVEY.md §1) collapses on TPU into
XLA/PJRT for everything device-side; what stays native is the host control plane and
IO: the TCPStore rendezvous server, the DataLoader prefetch ring, the chrome-trace
collector, and the pinned host staging pool.  This module compiles `native.cc` with
g++ on first use (cached in `_build/`), loads it with ctypes, and exposes typed
wrappers.  Every consumer has a pure-Python fallback, so a missing toolchain only
costs performance, never functionality (`AVAILABLE` tells you which path you're on).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_HERE, "_build")
_SRC = os.path.join(_HERE, "native.cc")
_LIB_PATH = os.path.join(_BUILD_DIR, "libpaddle_tpu_native.so")

_lib = None
_lib_lock = threading.Lock()
AVAILABLE = None  # resolved on first load_library() call


def _needs_rebuild() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    return os.path.getmtime(_SRC) > os.path.getmtime(_LIB_PATH)


def build(verbose: bool = False) -> str:
    """Compile native.cc -> libpaddle_tpu_native.so (cached by mtime)."""
    os.makedirs(_BUILD_DIR, exist_ok=True)
    if not _needs_rebuild():
        return _LIB_PATH
    cmd = ["g++", "-O2", "-std=c++17", "-fPIC", "-shared", "-pthread",
           _SRC, "-o", _LIB_PATH + ".tmp"]
    subprocess.run(cmd, check=True, capture_output=not verbose)
    os.replace(_LIB_PATH + ".tmp", _LIB_PATH)
    return _LIB_PATH


def load_library():
    """Load (building if needed).  Returns the CDLL or None if unavailable.

    Resolution order: env kill-switch -> fresh build (dev checkout with a
    toolchain) -> PREBUILT .so even if stale (wheel install on a
    compiler-less host) -> pure-Python fallbacks (AVAILABLE=False)."""
    global _lib, AVAILABLE
    if _lib is not None or AVAILABLE is False:
        return _lib
    with _lib_lock:
        if _lib is not None or AVAILABLE is False:
            return _lib
        if os.environ.get("PADDLE_TPU_DISABLE_NATIVE"):
            AVAILABLE = False
            return None
        # a lib loaded without a fresh compile THIS call may be a stale
        # artifact (copied build dir, docker layer with equal mtimes) — any
        # missing symbol then degrades instead of raising
        from_stale_prebuilt = not _needs_rebuild()
        try:
            path = build()
            lib = ctypes.CDLL(path)
        except Exception:
            # no toolchain: a prebuilt library (shipped in the wheel) still
            # loads — staleness only matters in dev checkouts, which have g++
            if os.path.exists(_LIB_PATH):
                try:
                    lib = ctypes.CDLL(_LIB_PATH)
                    from_stale_prebuilt = True
                except OSError:
                    AVAILABLE = False
                    return None
            else:
                AVAILABLE = False
                return None
        try:
            _declare(lib)
        except AttributeError:
            if not from_stale_prebuilt:
                raise  # fresh build missing a symbol IS a bug: fail loudly
            # a stale prebuilt .so missing newly-bound symbols: honor the
            # "CDLL or None" contract and degrade to pure Python
            AVAILABLE = False
            return None
        _lib = lib
        AVAILABLE = True
    return _lib


def _declare(lib):
    c = ctypes
    lib.pt_store_server_start.restype = c.c_void_p
    lib.pt_store_server_start.argtypes = [c.c_int]
    lib.pt_store_server_port.restype = c.c_int
    lib.pt_store_server_port.argtypes = [c.c_void_p]
    lib.pt_store_server_stop.argtypes = [c.c_void_p]

    lib.pt_ring_new.restype = c.c_void_p
    lib.pt_ring_new.argtypes = [c.c_int]
    lib.pt_ring_push.restype = c.c_int
    lib.pt_ring_push.argtypes = [c.c_void_p, c.c_char_p, c.c_int64, c.c_double]
    lib.pt_ring_pop.restype = c.c_int64
    lib.pt_ring_pop.argtypes = [c.c_void_p, c.c_char_p, c.c_int64, c.c_double]
    lib.pt_ring_peek_size.restype = c.c_int64
    lib.pt_ring_peek_size.argtypes = [c.c_void_p]
    lib.pt_ring_size.restype = c.c_int
    lib.pt_ring_size.argtypes = [c.c_void_p]
    lib.pt_ring_close.argtypes = [c.c_void_p]
    lib.pt_ring_free.argtypes = [c.c_void_p]

    lib.pt_trace_enable.argtypes = [c.c_int]
    lib.pt_trace_enabled.restype = c.c_int
    lib.pt_trace_begin.argtypes = [c.c_char_p]
    lib.pt_trace_complete.argtypes = [c.c_char_p, c.c_uint64, c.c_uint64]
    lib.pt_trace_count.restype = c.c_int64
    lib.pt_trace_dump_json.restype = c.c_int64
    lib.pt_trace_dump_json.argtypes = [c.c_char_p, c.c_int64]
    lib.pt_trace_now_us.restype = c.c_uint64

    lib.pt_pool_new.restype = c.c_void_p
    lib.pt_pool_alloc.restype = c.c_void_p
    lib.pt_pool_alloc.argtypes = [c.c_void_p, c.c_int64]
    lib.pt_pool_free.restype = c.c_int
    lib.pt_pool_free.argtypes = [c.c_void_p, c.c_void_p]
    lib.pt_pool_stats.argtypes = [c.c_void_p, c.POINTER(c.c_int64 * 5)]
    lib.pt_pool_trim.argtypes = [c.c_void_p]
    lib.pt_pool_delete.argtypes = [c.c_void_p]

    lib.pt_native_abi_version.restype = c.c_int


# ------------------------------------------------------------------ wrappers
class NativeKVServer:
    """C++ TCPStore server (same wire protocol as distributed.store.TCPStore,
    so Python clients talk to it unchanged)."""

    def __init__(self, port: int = 0):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.pt_store_server_start(port)
        if not self._h:
            raise OSError(f"failed to bind KV server on port {port}")
        self.port = lib.pt_store_server_port(self._h)

    def stop(self):
        if self._h:
            self._lib.pt_store_server_stop(self._h)
            self._h = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class NativeRing:
    """GIL-free bounded byte queue for DataLoader prefetch."""

    def __init__(self, capacity: int = 8):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.pt_ring_new(capacity)

    def push(self, data: bytes, timeout: float = -1.0) -> bool:
        if self._h is None:
            return False
        rc = self._lib.pt_ring_push(self._h, data, len(data), timeout)
        if rc == -1:
            raise TimeoutError("ring push timed out")
        return rc == 1

    def pop(self, timeout: float = -1.0) -> bytes | None:
        while True:
            if self._h is None:
                return None
            size = self._lib.pt_ring_peek_size(self._h)
            cap = max(size, 1 << 16)
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.pt_ring_pop(self._h, buf, cap, timeout)
            if n == -1:
                raise TimeoutError("ring pop timed out")
            if n == -2:
                continue  # raced with a larger item; retry with its size
            if n == -3:
                return b""  # popped item with empty payload (distinct from end)
            if n == 0:
                return None  # closed and drained
            return buf.raw[:n]

    def qsize(self) -> int:
        return self._lib.pt_ring_size(self._h) if self._h is not None else 0

    def close(self):
        if self._h is not None:
            self._lib.pt_ring_close(self._h)

    def free(self):
        if self._h:
            self._lib.pt_ring_free(self._h)
            self._h = None


class NativeTracer:
    """Span collector; dump() returns chrome://tracing JSON."""

    def __init__(self):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib

    def enable(self, on: bool = True):
        self._lib.pt_trace_enable(1 if on else 0)

    def now_us(self) -> int:
        return self._lib.pt_trace_now_us()

    def complete(self, name: str, ts_us: int, dur_us: int):
        self._lib.pt_trace_complete(name.encode(), ts_us, dur_us)

    def count(self) -> int:
        return self._lib.pt_trace_count()

    def clear(self):
        self._lib.pt_trace_clear()

    def dump_json(self) -> str:
        need = self._lib.pt_trace_dump_json(None, 0)
        buf = ctypes.create_string_buffer(need + 1)
        self._lib.pt_trace_dump_json(buf, need)
        return buf.raw[:need].decode()


class NativePool:
    """Host staging-buffer pool with stats (allocated, in_use, peak, hits, misses)."""

    def __init__(self):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.pt_pool_new()

    def alloc(self, n: int) -> int:
        ptr = self._lib.pt_pool_alloc(self._h, n)
        if not ptr:
            raise MemoryError(f"pool alloc of {n} bytes failed")
        return ptr

    def free(self, ptr: int):
        if self._lib.pt_pool_free(self._h, ptr) != 0:
            raise ValueError("pointer not allocated from this pool")

    def stats(self) -> dict:
        arr = (ctypes.c_int64 * 5)()
        self._lib.pt_pool_stats(self._h, ctypes.byref(arr))
        return {"allocated": arr[0], "in_use": arr[1], "peak": arr[2],
                "hits": arr[3], "misses": arr[4]}

    def trim(self):
        self._lib.pt_pool_trim(self._h)

    def delete(self):
        if self._h:
            self._lib.pt_pool_delete(self._h)
            self._h = None
