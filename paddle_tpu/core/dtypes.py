"""Dtype surface.

The reference exposes ``paddle.float32``-style dtype objects backed by a C++ enum
(`/root/reference/paddle/phi/common/data_type.h`).  TPU-natively there is no enum —
jax/numpy dtypes are the single currency — so we alias them directly and keep a
global default dtype (ref: python/paddle/framework/framework.py set_default_dtype).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

# Canonical dtype aliases (np.dtype instances compare equal to np.float32 etc.)
bool_ = np.dtype("bool")
uint8 = np.dtype("uint8")
int8 = np.dtype("int8")
int16 = np.dtype("int16")
int32 = np.dtype("int32")
int64 = np.dtype("int64")
float16 = np.dtype("float16")
bfloat16 = jnp.bfloat16  # numpy has no bfloat16; use the ml_dtypes-backed one
float32 = np.dtype("float32")
float64 = np.dtype("float64")
complex64 = np.dtype("complex64")
complex128 = np.dtype("complex128")

_STR_ALIASES = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "float32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
}

_default_dtype = float32


def set_default_dtype(d):
    """paddle.set_default_dtype parity (ref: python/paddle/framework/framework.py)."""
    global _default_dtype
    _default_dtype = convert_dtype(d)


def get_default_dtype():
    return _default_dtype


def convert_dtype(d):
    """Normalise str/np/jnp dtype-likes to a np.dtype (or bfloat16 scalar type).

    TPU note: without x64, int64/float64 are represented as int32/float32 (the
    reference's int64 indices map to XLA s32 — wider types buy nothing on the MXU).
    """
    if d is None:
        return _default_dtype
    if isinstance(d, str):
        out = _STR_ALIASES.get(d) or np.dtype(d)
    elif d is bfloat16 or d is jnp.bfloat16:
        return jnp.dtype(jnp.bfloat16)
    else:
        out = jnp.dtype(d)
    import jax

    if not jax.config.jax_enable_x64:
        if out == int64:
            return int32
        if out == float64:
            return float32
        if out == complex128:
            return complex64
    return out


def is_floating(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.floating)


def is_complex(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.complexfloating)


def is_differentiable(dtype) -> bool:
    """Dtypes gradients can flow through (float or complex — the fft family
    produces complex intermediates on the tape)."""
    return is_floating(dtype) or is_complex(dtype)


def is_integer(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.integer)
