"""Device / Place surface.

Reference: `Place`/`CPUPlace`/`CUDAPlace` (`/root/reference/paddle/phi/common/place.h:115`)
and `paddle.set_device` (`python/paddle/device/__init__.py`).  On TPU, device identity
is a `jax.Device`; Places are thin descriptors that resolve to one.
"""
from __future__ import annotations

import functools

import jax


class Place:
    """Base place descriptor (ref place.h:115)."""

    device_type = "unknown"

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def jax_device(self):
        devs = [d for d in jax.devices() if _kind(d) == self.device_type]
        if not devs:
            devs = jax.devices()
        return devs[self.device_id % len(devs)]


class CPUPlace(Place):
    device_type = "cpu"

    def jax_device(self):
        return jax.devices("cpu")[self.device_id % len(jax.devices("cpu"))]


class TPUPlace(Place):
    device_type = "tpu"


# CUDAPlace parity alias: on this framework "gpu" means the accelerator (TPU).
CUDAPlace = TPUPlace
XPUPlace = TPUPlace
CustomPlace = TPUPlace


def _kind(dev) -> str:
    plat = dev.platform
    if plat in ("tpu", "axon"):
        return "tpu"
    return plat


@functools.lru_cache(None)
def _accelerator_available() -> bool:
    return any(_kind(d) == "tpu" for d in jax.devices())


def is_tpu_backend() -> bool:
    """True when the default JAX backend is a TPU-family platform ("tpu", or
    the tunneled "axon" plugin).  THE single predicate for fast-path dispatch
    (Pallas kernels, hardware RNG) — don't re-implement the platform list."""
    return jax.default_backend() in ("tpu", "axon")


_current_place: Place | None = None


def set_device(device: str):
    """paddle.set_device parity: 'tpu', 'tpu:0', 'cpu', 'gpu' (alias of tpu)."""
    global _current_place
    name, _, idx = device.partition(":")
    idx = int(idx) if idx else 0
    if name in ("tpu", "gpu", "xpu", "npu", "custom_device"):
        _current_place = TPUPlace(idx) if _accelerator_available() else CPUPlace(idx)
    elif name == "cpu":
        _current_place = CPUPlace(idx)
    else:
        raise ValueError(f"unknown device {device!r}")
    return _current_place


def get_device() -> str:
    p = _get_place()
    return f"{p.device_type}:{p.device_id}"


def _get_place() -> Place:
    global _current_place
    if _current_place is None:
        _current_place = TPUPlace(0) if _accelerator_available() else CPUPlace(0)
    return _current_place


def is_compiled_with_cuda() -> bool:  # parity shim
    return False


def is_compiled_with_tpu() -> bool:
    return _accelerator_available()


def default_jax_device():
    return _get_place().jax_device()
