"""GradScaler (ref: python/paddle/amp/grad_scaler.py:26 over fluid AmpScaler
loss_scaler.py:40, using check_finite_and_unscale + update_loss_scaling ops).

On TPU bf16 training needs no loss scaling; the scaler still implements the full
dynamic-scaling contract for fp16 parity (scale/unscale/found-inf bookkeeping in jnp).

Sync semantics: THIS eager path pulls the found-inf bool to the host every
step (the isfinite check in `_unscale_and_check` — fine for interactive
use).  The fast path is `jit.TrainStep(..., scaler=scaler)`, which keeps
the (scale, good, bad) counters device-resident and does the
skip-update-on-overflow select inside the compiled step with NO per-step
host sync (jit/_step_impl.py — the in-graph twin of update_loss_scaling).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..tensor.tensor import Tensor
from ..autograd import tape


class AmpScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0**15, incr_ratio=2.0,
                 decr_ratio=0.5, incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
                 use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def scale(self, var):
        if not self._enable:
            return var
        self._sync_from_device()
        return var * self._scale

    def _unscale_and_check(self, optimizer):
        self._sync_from_device()
        params = [p for p in optimizer._params() if p._grad is not None]
        found = False
        inv = 1.0 / self._scale
        for p in params:
            g = p._grad * inv
            p._grad = g
        if params:
            tot = sum(jnp.sum(p._grad.astype(jnp.float32)) for p in params)
            found = bool(~jnp.isfinite(tot))
        self._found_inf = found
        return found

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        found = self._unscale_and_check(optimizer)
        if not found:
            optimizer.step()

    def unscale_(self, optimizer):
        if self._enable:
            self._unscale_and_check(optimizer)

    def update(self):
        if not (self._enable and self._dynamic):
            return
        self._sync_from_device()
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    # ---- compiled-step integration (TrainStep/ShardedTrainStep scaler=...):
    # the (scale, good, bad) counters live on device inside the jitted step;
    # host reads sync lazily so the fast path never blocks on a transfer.
    def _attach_device_state(self, st):
        self._device_state = st

    def _sync_from_device(self):
        st = getattr(self, "_device_state", None)
        if st is not None:
            self._scale = float(st["scale"])
            self._good_steps = int(st["good"])
            self._bad_steps = int(st["bad"])
            self._device_state = None

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        self._sync_from_device()
        return Tensor(jnp.asarray(self._scale))

    def set_init_loss_scaling(self, v):
        self._device_state = None  # explicit host write wins over pending device state
        self._host_dirty = True    # compiled steps re-seed their device state
        self._scale = float(v)

    def state_dict(self):
        self._sync_from_device()
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every_n_steps,
            "decr_every_n_nan_or_inf": self._decr_every_n,
            "good_steps": self._good_steps,
            "bad_steps": self._bad_steps,
        }

    def load_state_dict(self, sd):
        self._device_state = None  # restored host state wins over pending device state
        self._host_dirty = True    # compiled steps re-seed their device state
        self._scale = sd.get("scale", self._scale)
        self._good_steps = sd.get("good_steps", 0)
        self._bad_steps = sd.get("bad_steps", 0)


class GradScaler(AmpScaler):
    """Public API (ref grad_scaler.py:26)."""
    pass
