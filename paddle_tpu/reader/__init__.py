"""paddle.reader — composable sample-reader decorators.

Ref: python/paddle/reader/decorator.py (cache/map_readers/shuffle/chain/
compose/buffered/firstn/xmap_readers).  A "reader" is a zero-arg callable
returning an iterable of samples; these helpers wrap readers into new readers.
"""
from __future__ import annotations

import itertools
import queue
import random as _random
import threading

__all__ = ["cache", "map_readers", "buffered", "compose", "chain", "shuffle",
           "firstn", "xmap_readers", "ComposeNotAligned"]


class ComposeNotAligned(ValueError):
    pass


def cache(reader):
    """Eagerly read every sample once, then replay from memory."""
    all_data = tuple(reader())

    def cached_reader():
        return iter(all_data)

    return cached_reader


def map_readers(func, *readers):
    """Yield func(*one_sample_from_each_reader)."""

    def reader():
        for items in zip(*[r() for r in readers]):
            yield func(*items)

    return reader


def shuffle(reader, buf_size):
    """Shuffle within a sliding buffer of buf_size samples."""

    def shuffled_reader():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return shuffled_reader


def chain(*readers):
    """Concatenate readers back to back."""

    def chained_reader():
        return itertools.chain(*[r() for r in readers])

    return chained_reader


def compose(*readers, **kwargs):
    """Zip readers sample-wise into flat tuples; check_alignment (default True)
    raises ComposeNotAligned when one reader runs short."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def composed_reader():
        iters = [iter(r()) for r in readers]
        while True:
            outputs = []
            done = 0
            for it in iters:
                try:
                    outputs.append(next(it))
                except StopIteration:
                    done += 1
                    outputs.append(None)
            if done == len(iters):
                return
            if done > 0:
                if check_alignment:
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned (some ended early)")
                return
            yield sum((make_tuple(o) for o in outputs), ())

    return composed_reader


def buffered(reader, size):
    """Prefetch up to `size` samples on a producer thread."""

    class _End:
        pass

    def buffered_reader():
        q = queue.Queue(maxsize=size)

        def produce():
            try:
                for sample in reader():
                    q.put(sample)
            finally:
                q.put(_End)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        while True:
            sample = q.get()
            if sample is _End:
                return
            yield sample

    return buffered_reader


def firstn(reader, n):
    """Limit the reader to its first n samples."""

    def firstn_reader():
        return itertools.islice(reader(), n)

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Apply `mapper` over samples with `process_num` worker threads.
    With order=True results keep the source order (index-tagged reorder,
    same contract as the reference's ordered XmapEndSignal pipeline)."""

    class _End:
        pass

    def xreader():
        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)

        def feed():
            for i, sample in enumerate(reader()):
                in_q.put((i, sample))
            for _ in range(process_num):
                in_q.put(_End)

        def work():
            while True:
                item = in_q.get()
                if item is _End:
                    out_q.put(_End)
                    return
                i, sample = item
                out_q.put((i, mapper(sample)))

        threading.Thread(target=feed, daemon=True).start()
        workers = [threading.Thread(target=work, daemon=True) for _ in range(process_num)]
        for w in workers:
            w.start()

        finished = 0
        if not order:
            while finished < process_num:
                item = out_q.get()
                if item is _End:
                    finished += 1
                    continue
                yield item[1]
        else:
            pending = {}
            next_idx = 0
            while finished < process_num or pending:
                if next_idx in pending:
                    yield pending.pop(next_idx)
                    next_idx += 1
                    continue
                item = out_q.get()
                if item is _End:
                    finished += 1
                    continue
                i, mapped = item
                pending[i] = mapped

    return xreader
