"""Legacy IMDB readers (ref: python/paddle/dataset/imdb.py — word_dict(),
train(word_idx)/test(word_idx) yield (list-of-word-ids, 0/1 label))."""
from __future__ import annotations

import numpy as np

__all__ = ["word_dict", "train", "test"]


def _ds(mode, cutoff=150):
    from ..text import Imdb

    return Imdb(mode=mode, cutoff=cutoff, synthetic=True)


def word_dict(cutoff=150):
    """Word -> id map.  With synthetic data the vocabulary is the id space
    itself (the corpus loader builds the real map when given data_file)."""
    ds = _ds("train", cutoff)
    if ds.word_idx:
        return ds.word_idx
    vocab = int(max(int(np.max(d)) for d in ds.docs)) + 1
    return {str(i): i for i in range(vocab)}


def _reader(mode):
    def reader():
        ds = _ds(mode)
        for doc, label in zip(ds.docs, ds.labels):
            yield list(np.asarray(doc, np.int64)), int(label)

    return reader


def train(word_idx=None):
    return _reader("train")


def test(word_idx=None):
    return _reader("test")
