"""Legacy MNIST readers (ref: python/paddle/dataset/mnist.py — train()/test()
yield (784-float32 image in [-1, 1], int label))."""
from __future__ import annotations

import numpy as np

__all__ = ["train", "test"]


def _reader(mode):
    def reader():
        from ..vision.datasets import MNIST

        ds = MNIST(mode=mode)
        for i in range(len(ds)):
            img, label = ds[i]
            # the Dataset yields [0,1]; the legacy reader contract is [-1,1]
            img = np.asarray(img, np.float32).reshape(-1) * 2.0 - 1.0
            yield img, int(np.asarray(label).reshape(-1)[0])

    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")
