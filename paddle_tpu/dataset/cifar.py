"""Legacy CIFAR readers (ref: python/paddle/dataset/cifar.py — train10()/
test10()/train100()/test100() yield (3072-float32 image in [0,1], int label))."""
from __future__ import annotations

import numpy as np

__all__ = ["train10", "test10", "train100", "test100"]


def _reader(cls_name, mode):
    def reader():
        from ..vision import datasets as vd

        ds = getattr(vd, cls_name)(mode=mode)
        for i in range(len(ds)):
            img, label = ds[i]
            # the Dataset already yields [0,1], which is the legacy contract
            img = np.asarray(img, np.float32).reshape(-1)
            yield img, int(np.asarray(label).reshape(-1)[0])

    return reader


def train10():
    return _reader("Cifar10", "train")


def test10():
    return _reader("Cifar10", "test")


def train100():
    return _reader("Cifar100", "train")


def test100():
    return _reader("Cifar100", "test")
