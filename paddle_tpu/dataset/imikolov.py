"""Legacy imikolov (PTB-style) n-gram readers (ref: python/paddle/dataset/
imikolov.py — build_dict(), train(word_idx, n)/test(word_idx, n) yield n-gram
tuples of word ids).  Without the real tarball this build serves a generated
Zipf-distributed corpus (warned once), same contract as the other datasets.
"""
from __future__ import annotations

import warnings

import numpy as np

__all__ = ["build_dict", "train", "test"]

_VOCAB = 2048
_warned = False


def _corpus(mode):
    global _warned
    if not _warned:
        warnings.warn(
            "imikolov: no local PTB corpus and this build cannot download — "
            "using GENERATED Zipf text (pipeline smoke tests only)", stacklevel=3)
        _warned = True
    rng = np.random.RandomState(0 if mode == "train" else 1)
    n_sent = 512 if mode == "train" else 64
    # Zipf-ish over the vocab, sentences of 5-30 tokens
    for _ in range(n_sent):
        ln = rng.randint(5, 30)
        yield list((rng.zipf(1.3, ln) % (_VOCAB - 2)).astype(np.int64) + 2)


def build_dict(min_word_freq=50):
    return {str(i): i for i in range(_VOCAB)}


def _ngram_reader(mode, word_idx, n):
    def reader():
        for sent in _corpus(mode):
            s = [1] + sent + [2]  # <s> ... <e>
            if len(s) >= n:
                for i in range(n, len(s) + 1):
                    yield tuple(s[i - n:i])

    return reader


def train(word_idx, n, data_type=1):
    return _ngram_reader("train", word_idx, n)


def test(word_idx, n, data_type=1):
    return _ngram_reader("test", word_idx, n)
