"""paddle.dataset — the legacy reader-creator namespace
(ref: python/paddle/dataset/{mnist,cifar,uci_housing,imdb,imikolov}.py).

Each submodule exposes zero-arg reader creators (`train()`, `test()`) that
yield legacy sample tuples.  Backed by the modern `paddle.vision.datasets` /
`paddle.text` Dataset classes, which warn + fall back to generated stand-in
data when the real corpus files are absent (this build cannot download).
"""
from . import cifar, imdb, imikolov, mnist, uci_housing  # noqa: F401

__all__ = ["mnist", "cifar", "uci_housing", "imdb", "imikolov"]
