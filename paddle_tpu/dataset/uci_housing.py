"""Legacy UCI-Housing readers (ref: python/paddle/dataset/uci_housing.py —
train()/test() yield (13-float32 features, 1-float32 price))."""
from __future__ import annotations

import numpy as np

__all__ = ["train", "test"]


def _reader(mode):
    def reader():
        from ..text import UCIHousing

        ds = UCIHousing(mode=mode, synthetic=True)
        for i in range(len(ds)):
            x, y = ds[i]
            yield np.asarray(x, np.float32), np.asarray(y, np.float32).reshape(-1)

    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")
