"""Shape/layout manipulation ops (ref: python/paddle/tensor/manipulation.py).

All are jnp/lax compositions; reshape/transpose are free (layout changes) under XLA.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .tensor import Tensor, apply_op, _unwrap
from ..core import dtypes as _dt


def _shape_arg(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._value))
    return tuple(int(s) if not isinstance(s, Tensor) else int(s.item()) for s in shape)


def reshape(x, shape, name=None):
    s = _shape_arg(shape)
    return apply_op(lambda v: jnp.reshape(v, s), (x,), name="reshape")


def reshape_(x, shape, name=None):
    return x._assume(reshape(x, shape))


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def _f(v):
        nd = v.ndim
        sa = start_axis % nd if nd else 0
        so = stop_axis % nd if nd else 0
        new_shape = v.shape[:sa] + (-1,) + v.shape[so + 1:]
        return jnp.reshape(v, new_shape)

    return apply_op(_f, (x,), name="flatten")


def squeeze(x, axis=None, name=None):
    def _f(v):
        if axis is None:
            return jnp.squeeze(v)
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        axes = tuple(a % v.ndim for a in axes if v.shape[a % v.ndim] == 1)
        return jnp.squeeze(v, axis=axes) if axes else v

    return apply_op(_f, (x,), name="squeeze")


def unsqueeze(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]

    def _f(v):
        out = v
        for a in sorted([a % (out.ndim + len(axes)) if a < 0 else a for a in axes]):
            out = jnp.expand_dims(out, a)
        return out

    return apply_op(_f, (x,), name="unsqueeze")


def transpose(x, perm, name=None):
    p = tuple(int(a) for a in perm)
    return apply_op(lambda v: jnp.transpose(v, p), (x,), name="transpose")


def moveaxis(x, source, destination):
    return apply_op(lambda v: jnp.moveaxis(v, source, destination), (x,), name="moveaxis")


def swapaxes(x, axis1, axis2):
    return apply_op(lambda v: jnp.swapaxes(v, axis1, axis2), (x,), name="swapaxes")


def t(x):
    return apply_op(lambda v: v.T if v.ndim >= 2 else v, (x,), name="t")


def concat(x, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    tensors = list(x)
    return apply_op(lambda *vs: jnp.concatenate(vs, axis=axis), tuple(tensors), name="concat")


def stack(x, axis=0, name=None):
    tensors = list(x)
    return apply_op(lambda *vs: jnp.stack(vs, axis=axis), tuple(tensors), name="stack")


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())

    def _f(v):
        ax = axis % v.ndim
        if isinstance(num_or_sections, int):
            return tuple(jnp.split(v, num_or_sections, axis=ax))
        sections = [
            s if not isinstance(s, Tensor) else int(s.item()) for s in num_or_sections
        ]
        total = v.shape[ax]
        known = builtins_sum(s for s in sections if s != -1)
        sections = [s if s != -1 else total - known for s in sections]
        idx = np.cumsum(sections)[:-1]
        return tuple(jnp.split(v, idx, axis=ax))

    return apply_op(_f, (x,), name="split")


def builtins_sum(it):
    tot = 0
    for v in it:
        tot += v
    return tot


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis=axis)


def unbind(x, axis=0):
    def _f(v):
        ax = axis % v.ndim
        return tuple(jnp.squeeze(s, axis=ax) for s in jnp.split(v, v.shape[ax], axis=ax))

    return apply_op(_f, (x,), name="unbind")


def unstack(x, axis=0, num=None):
    return unbind(x, axis)


def tile(x, repeat_times, name=None):
    reps = _shape_arg(repeat_times)
    return apply_op(lambda v: jnp.tile(v, reps), (x,), name="tile")


def expand(x, shape, name=None):
    s = _shape_arg(shape)

    def _f(v):
        tgt = list(s)
        for i in range(len(tgt)):
            if tgt[i] == -1:
                tgt[i] = v.shape[i - len(tgt) + v.ndim] if i - len(tgt) + v.ndim >= 0 else 1
        return jnp.broadcast_to(v, tuple(tgt))

    return apply_op(_f, (x,), name="expand")


def expand_as(x, y, name=None):
    return apply_op(lambda v, w: jnp.broadcast_to(v, w.shape), (x, y), name="expand_as")


def broadcast_to(x, shape, name=None):
    s = _shape_arg(shape)
    return apply_op(lambda v: jnp.broadcast_to(v, s), (x,), name="broadcast_to")


def broadcast_tensors(inputs):
    shape = np.broadcast_shapes(*[tuple(t.shape) for t in inputs])
    return [broadcast_to(t, shape) for t in inputs]


def flip(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return apply_op(lambda v: jnp.flip(v, axis=tuple(axes)), (x,), name="flip")


def rot90(x, k=1, axes=(0, 1)):
    return apply_op(lambda v: jnp.rot90(v, k=k, axes=tuple(axes)), (x,), name="rot90")


def roll(x, shifts, axis=None, name=None):
    return apply_op(lambda v: jnp.roll(v, shifts, axis=axis), (x,), name="roll")


def cast(x, dtype):
    d = _dt.convert_dtype(dtype)
    return apply_op(lambda v: v.astype(d), (x,), name="cast")


def gather(x, index, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply_op(lambda v, i: jnp.take(v, i.astype(jnp.int32), axis=axis), (x, index), name="gather")


def gather_nd(x, index, name=None):
    def _f(v, idx):
        idx = idx.astype(jnp.int32)
        k = idx.shape[-1]
        out = v[tuple(jnp.moveaxis(idx, -1, 0))]
        return out

    return apply_op(_f, (x, index), name="gather_nd")


def index_select(x, index, axis=0, name=None):
    return apply_op(lambda v, i: jnp.take(v, i.astype(jnp.int32), axis=axis), (x, index), name="index_select")


def index_sample(x, index):
    def _f(v, i):
        return jnp.take_along_axis(v, i.astype(jnp.int32), axis=1)

    return apply_op(_f, (x, index), name="index_sample")


def scatter(x, index, updates, overwrite=True, name=None):
    def _f(v, i, u):
        i = i.astype(jnp.int32)
        if overwrite:
            return v.at[i].set(u)
        # paddle semantics: non-overwrite zeroes target rows then adds
        zeroed = v.at[i].set(jnp.zeros_like(u))
        return zeroed.at[i].add(u)

    return apply_op(_f, (x, index, updates), name="scatter")


def scatter_(x, index, updates, overwrite=True):
    return x._assume(scatter(x, index, updates, overwrite))


def scatter_nd_add(x, index, updates, name=None):
    def _f(v, i, u):
        i = i.astype(jnp.int32)
        return v.at[tuple(jnp.moveaxis(i, -1, 0))].add(u)

    return apply_op(_f, (x, index, updates), name="scatter_nd_add")


def scatter_nd(index, updates, shape, name=None):
    s = _shape_arg(shape)

    def _f(i, u):
        i = i.astype(jnp.int32)
        return jnp.zeros(s, u.dtype).at[tuple(jnp.moveaxis(i, -1, 0))].add(u)

    return apply_op(_f, (index, updates), name="scatter_nd")


def take_along_axis(arr, indices, axis, broadcast=True):
    return apply_op(
        lambda v, i: jnp.take_along_axis(v, i.astype(jnp.int32), axis=axis),
        (arr, indices),
        name="take_along_axis",
    )


def put_along_axis(arr, indices, values, axis, reduce="assign"):
    def _f(v, i, u):
        i = i.astype(jnp.int32)
        u = jnp.broadcast_to(u, i.shape) if jnp.ndim(u) else jnp.full(i.shape, u, v.dtype)
        if reduce == "assign":
            return jnp.put_along_axis(v, i, u, axis=axis, inplace=False)
        if reduce == "add":
            dims = list(range(v.ndim))
            onehot = None
            out = v
            # scatter-add along axis via .at
            idx = [jnp.arange(s).reshape([-1 if d == k else 1 for k in range(v.ndim)]) for d, s in enumerate(i.shape)]
            idx[axis] = i
            return out.at[tuple(idx)].add(u)
        if reduce in ("mul", "multiply"):
            idx = [jnp.arange(s).reshape([-1 if d == k else 1 for k in range(v.ndim)]) for d, s in enumerate(i.shape)]
            idx[axis] = i
            return v.at[tuple(idx)].multiply(u)
        raise ValueError(reduce)

    return apply_op(_f, (arr, indices, values), name="put_along_axis")


def take(x, index, mode="raise"):
    return apply_op(lambda v, i: jnp.take(v.reshape(-1), i.astype(jnp.int32).reshape(-1)).reshape(i.shape), (x, index), name="take")


def slice(input, axes, starts, ends):
    def _f(v):
        out = v
        for ax, st, en in zip(axes, starts, ends):
            st = int(st.item()) if isinstance(st, Tensor) else int(st)
            en = int(en.item()) if isinstance(en, Tensor) else int(en)
            n = v.shape[ax]
            st = max(st + n, 0) if st < 0 else min(st, n)
            en = max(en + n, 0) if en < 0 else min(en, n)
            idx = [slice_builtin(None)] * out.ndim
            idx[ax] = slice_builtin(st, en)
            out = out[tuple(idx)]
        return out

    return apply_op(_f, (input,), name="slice")


import builtins as _builtins

slice_builtin = _builtins.slice


def strided_slice(x, axes, starts, ends, strides):
    def _f(v):
        out = v
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            idx = [slice_builtin(None)] * out.ndim
            idx[ax] = slice_builtin(st, en, sd)
            out = out[tuple(idx)]
        return out

    return apply_op(_f, (x,), name="strided_slice")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    from ..nn import functional as F

    return F.pad(x, pad, mode=mode, value=value, data_format=data_format)


def repeat_interleave(x, repeats, axis=None, name=None):
    def _f(v, r):
        if axis is None:
            v = v.reshape(-1)
            ax = 0
        else:
            ax = axis
        if np.ndim(r) == 0:
            return jnp.repeat(v, int(r), axis=ax)
        return jnp.repeat(v, r, axis=ax, total_repeat_length=int(np.sum(np.asarray(r))))

    return apply_op(_f, (x, repeats), name="repeat_interleave")


def tril(x, diagonal=0, name=None):
    return apply_op(lambda v: jnp.tril(v, k=diagonal), (x,), name="tril")


def triu(x, diagonal=0, name=None):
    return apply_op(lambda v: jnp.triu(v, k=diagonal), (x,), name="triu")


def diag(x, offset=0, padding_value=0, name=None):
    def _f(v):
        if v.ndim == 1:
            out = jnp.diag(v, k=offset)
            if padding_value != 0:
                mask = jnp.diag(jnp.ones_like(v), k=offset) == 0
                out = jnp.where(mask, padding_value, out)
            return out
        return jnp.diag(v, k=offset)

    return apply_op(_f, (x,), name="diag")


def diagflat(x, offset=0):
    return apply_op(lambda v: jnp.diagflat(v, k=offset), (x,), name="diagflat")


def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    def _f(v):
        n = v.shape[-1] + builtins_abs(offset)
        out = jnp.zeros(v.shape[:-1] + (n, n), v.dtype)
        idx = jnp.arange(v.shape[-1])
        if offset >= 0:
            out = out.at[..., idx, idx + offset].set(v)
        else:
            out = out.at[..., idx - offset, idx].set(v)
        return out

    return apply_op(_f, (x,), name="diag_embed")


def builtins_abs(v):
    return v if v >= 0 else -v


def masked_select(x, mask, name=None):
    # dynamic output shape: executes eagerly on host (not jittable by design)
    v = np.asarray(_unwrap(x))
    m = np.asarray(_unwrap(mask))
    return Tensor(jnp.asarray(v[m]))


def masked_fill(x, mask, value):
    return apply_op(lambda v, m, val: jnp.where(m, val, v), (x, mask, value), name="masked_fill")


def index_put(x, indices, value, accumulate=False):
    def _f(v, val, *idx):
        idx = tuple(i.astype(jnp.int32) if jnp.issubdtype(i.dtype, jnp.integer) else i for i in idx)
        if accumulate:
            return v.at[idx].add(val)
        return v.at[idx].set(val)

    return apply_op(_f, (x, value, *indices), name="index_put")


def as_real(x):
    return apply_op(lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1), (x,), name="as_real")


def as_complex(x):
    return apply_op(lambda v: jax.lax.complex(v[..., 0], v[..., 1]), (x,), name="as_complex")


def tensordot(x, y, axes=2):
    return apply_op(lambda a, b: jnp.tensordot(a, b, axes=axes), (x, y), name="tensordot")


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    from ..nn import functional as F

    return F.unfold(x, kernel_sizes, strides, paddings, dilations)


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None):
    # dynamic shape -> host eager
    v = np.asarray(_unwrap(x))
    res = np.unique(v, return_index=return_index, return_inverse=return_inverse, return_counts=return_counts, axis=axis)
    if isinstance(res, tuple):
        return tuple(Tensor(jnp.asarray(r)) for r in res)
    return Tensor(jnp.asarray(res))


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None):
    v = np.asarray(_unwrap(x)).reshape(-1) if axis is None else np.asarray(_unwrap(x))
    keep = np.ones(len(v), bool)
    keep[1:] = v[1:] != v[:-1]
    out = [Tensor(jnp.asarray(v[keep]))]
    if return_inverse:
        out.append(Tensor(jnp.asarray(np.cumsum(keep) - 1)))
    if return_counts:
        idx = np.flatnonzero(keep)
        out.append(Tensor(jnp.asarray(np.diff(np.append(idx, len(v))))))
    return out[0] if len(out) == 1 else tuple(out)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    def _f(v):
        size = (index_num + nshards - 1) // nshards
        lo = shard_id * size
        ok = (v >= lo) & (v < lo + size)
        return jnp.where(ok, v - lo, ignore_value)

    return apply_op(_f, (input,), name="shard_index")


def crop(x, shape=None, offsets=None, name=None):
    """Ref manipulation crop: static slice at `offsets` of size `shape`
    (-1 in shape means to-the-end)."""
    offs = [int(o) for o in (offsets or [0] * len(x.shape))]
    tgt = [int(s) for s in (shape or [-1] * len(x.shape))]

    for o, s, dim in zip(offs, tgt, x.shape):
        stop = dim if s == -1 else o + s
        if o < 0 or stop > dim:
            raise ValueError(
                f"crop out of range: offset {o} + size {s} exceeds dim {dim}")

    def _f(v):
        sl = []
        for o, s, dim in zip(offs, tgt, v.shape):
            stop = dim if s == -1 else o + s
            sl.append(slice_builtin(o, stop))  # paddle.slice shadows builtins
        return v[tuple(sl)]

    return apply_op(_f, (x,), name="crop")


def reverse(x, axis, name=None):
    """Ref manipulation reverse — alias of flip."""
    return flip(x, axis)


def squeeze_(x, axis=None, name=None):
    return x._assume(squeeze(x, axis))


def unsqueeze_(x, axis, name=None):
    return x._assume(unsqueeze(x, axis))


def shape(x, name=None):
    """Ref paddle.shape: the runtime shape as an int32 Tensor."""
    from .tensor import Tensor as _T
    import jax.numpy as _jnp

    return _T(_jnp.asarray(x.shape if isinstance(x, _T) else _jnp.asarray(x).shape,
                           _jnp.int32))


def rank(x, name=None):
    from .tensor import Tensor as _T
    import jax.numpy as _jnp

    return _T(_jnp.asarray(len(x.shape), _jnp.int32))


def tolist(x):
    return x.tolist()
