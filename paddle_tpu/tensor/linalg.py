"""Linear algebra (ref: python/paddle/tensor/linalg.py + phi lapack kernels).

Dense decompositions route to jnp.linalg (XLA custom calls / QR-based paths on TPU).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .tensor import Tensor, apply_op, _unwrap
from .math import matmul, mm, bmm, dot  # re-exported (ref linalg.py exports)


def einsum(equation, *operands):
    """Ref: python/paddle/tensor/einsum.py.  Direct XLA einsum — contractions land
    on the MXU with the compiler choosing the contraction order."""

    def _f(*ops):
        return jnp.einsum(equation, *ops)

    return apply_op(_f, tuple(operands), name="einsum")


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    def _f(v):
        if axis is None and p in ("fro", 2):
            return jnp.sqrt(jnp.sum(jnp.square(v)))
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if p == "fro":
            return jnp.sqrt(jnp.sum(jnp.square(v), axis=ax, keepdims=keepdim))
        if p == np.inf or p == float("inf"):
            return jnp.max(jnp.abs(v), axis=ax, keepdims=keepdim)
        if p == -np.inf or p == float("-inf"):
            return jnp.min(jnp.abs(v), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((v != 0).astype(v.dtype), axis=ax, keepdims=keepdim)
        return jnp.power(jnp.sum(jnp.power(jnp.abs(v), p), axis=ax, keepdims=keepdim), 1.0 / p)

    return apply_op(_f, (x,), name="norm")


def dist(x, y, p=2):
    def _f(a, b):
        d = a - b
        if p == 0:
            return jnp.sum((d != 0).astype(d.dtype)).astype(d.dtype)
        if p == float("inf"):
            return jnp.max(jnp.abs(d))
        if p == float("-inf"):
            return jnp.min(jnp.abs(d))
        return jnp.power(jnp.sum(jnp.power(jnp.abs(d), p)), 1.0 / p)

    return apply_op(_f, (x, y), name="dist")


def cholesky(x, upper=False, name=None):
    def _f(v):
        L = jnp.linalg.cholesky(v)
        return jnp.swapaxes(L, -1, -2) if upper else L

    return apply_op(_f, (x,), name="cholesky")


def inv(x, name=None):
    return apply_op(lambda v: jnp.linalg.inv(v), (x,), name="inv")


def det(x, name=None):
    return apply_op(lambda v: jnp.linalg.det(v), (x,), name="det")


def slogdet(x, name=None):
    def _f(v):
        sign, logdet = jnp.linalg.slogdet(v)
        return jnp.stack([sign, logdet])

    return apply_op(_f, (x,), name="slogdet")


def svd(x, full_matrices=False, name=None):
    return apply_op(lambda v: tuple(jnp.linalg.svd(v, full_matrices=full_matrices)), (x,), name="svd")


def qr(x, mode="reduced", name=None):
    return apply_op(lambda v: tuple(jnp.linalg.qr(v, mode=mode)), (x,), name="qr")


def eigh(x, UPLO="L", name=None):
    return apply_op(lambda v: tuple(jnp.linalg.eigh(v, UPLO=UPLO)), (x,), name="eigh")


def eigvalsh(x, UPLO="L", name=None):
    return apply_op(lambda v: jnp.linalg.eigvalsh(v, UPLO=UPLO), (x,), name="eigvalsh")


def eig(x, name=None):
    # general eig: CPU-only in XLA; host round-trip
    v = np.asarray(_unwrap(x))
    w, vec = np.linalg.eig(v)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(vec))


def eigvals(x, name=None):
    """Eigenvalues of a general (non-symmetric) matrix (ref linalg.py eigvals).
    Host round-trip like eig: XLA has no general-eig kernel on TPU."""
    v = np.asarray(_unwrap(x))
    return Tensor(jnp.asarray(np.linalg.eigvals(v)))


def solve(x, y, name=None):
    return apply_op(lambda a, b: jnp.linalg.solve(a, b), (x, y), name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def _f(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular
        )

    return apply_op(_f, (x, y), name="triangular_solve")


def cholesky_solve(x, y, upper=False):
    def _f(b, chol):
        return jax.scipy.linalg.cho_solve((chol, not upper), b)

    return apply_op(_f, (x, y), name="cholesky_solve")


def lstsq(x, y, rcond=None, driver=None):
    def _f(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv

    return apply_op(_f, (x, y), name="lstsq")


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_op(lambda v: jnp.linalg.pinv(v, rtol=rcond, hermitian=hermitian), (x,), name="pinv")


def matrix_power(x, n, name=None):
    return apply_op(lambda v: jnp.linalg.matrix_power(v, n), (x,), name="matrix_power")


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply_op(lambda v: jnp.linalg.matrix_rank(v, tol=tol), (x,), name="matrix_rank")


def cond(x, p=None, name=None):
    return apply_op(lambda v: jnp.linalg.cond(v, p=p), (x,), name="cond")


def multi_dot(tensors, name=None):
    return apply_op(lambda *vs: jnp.linalg.multi_dot(vs), tuple(tensors), name="multi_dot")


def lu(x, pivot=True, get_infos=False):
    def _f(v):
        lu_, piv = jax.scipy.linalg.lu_factor(v)
        # LAPACK 1-based ipiv (the reference lu op's documented convention);
        # scipy returns 0-based, shift up so saved pivots interop with Paddle
        return lu_, piv.astype(jnp.int32) + 1

    out = apply_op(_f, (x,), name="lu")
    if get_infos:
        from .creation import zeros

        return (*out, zeros([1], "int32"))
    return out


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Split packed LU factors + pivot rows into (P, L, U)
    (ref tensor/linalg.py lu_unpack over the lu_unpack op).

    `x` is the [.., n, n] packed LU from `lu()`, `y` the pivot-row indices
    (LAPACK **1-based** ipiv convention, as `lu()` returns: row i was swapped
    with row y[i]-1)."""
    def _plu(lu_v, piv):
        if lu_v.ndim > 2:
            return jax.vmap(_plu)(lu_v, piv)
        n = lu_v.shape[-1]
        L = jnp.tril(lu_v, -1) + jnp.eye(n, dtype=lu_v.dtype)
        U = jnp.triu(lu_v)
        # ipiv -> permutation: apply the row swaps in order to the identity
        def swap(p, i):
            j = piv[i]
            row_i, row_j = p[i], p[j]
            p = p.at[i].set(row_j).at[j].set(row_i)
            return p, ()
        perm, _ = jax.lax.scan(swap, jnp.arange(n, dtype=jnp.int32),
                               jnp.arange(piv.shape[-1], dtype=jnp.int32))
        # rows were permuted as A[perm] = L @ U  =>  A = P @ L @ U with
        # P[i, perm[i]] = 1 (the inverse permutation as a matrix)
        P = jnp.zeros((n, n), lu_v.dtype).at[perm, jnp.arange(n)].set(1.0)
        return P, L, U

    # 1-based LAPACK ipiv (lu()'s convention) -> 0-based row indices, once,
    # outside the batch recursion
    P, L, U = apply_op(lambda a, b: _plu(a, b - 1), (x, y), name="lu_unpack")
    return P, L, U


def corrcoef(x, rowvar=True):
    return apply_op(lambda v: jnp.corrcoef(v, rowvar=rowvar), (x,), name="corrcoef")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    return apply_op(lambda v: jnp.cov(v, rowvar=rowvar, ddof=1 if ddof else 0), (x,), name="cov")


def histogramdd(x, bins, *a, **k):
    raise NotImplementedError("histogramdd is not yet supported on the TPU build")


def t(x, name=None):
    from .manipulation import t as _t

    return _t(x)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def _f(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis)
        return num / jnp.maximum(den, eps)

    return apply_op(_f, (x1, x2), name="cosine_similarity")
