"""The eager Tensor: a jax.Array wrapper with taped autograd.

Reference analogs: `phi::DenseTensor` (`/root/reference/paddle/phi/core/dense_tensor.h:37`)
for storage, `paddle::experimental::Tensor` (`paddle/phi/api/include/tensor.h`) for the
API object, and `AutogradMeta` (`paddle/fluid/eager/autograd_meta.h:61`) for the grad
slots.  Here all three collapse into one Python class over a `jax.Array` — the device
buffer, layout, and allocation are PJRT/XLA's business, not ours.
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd import tape
from ..core import dtypes as _dt


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else x


class Tensor:
    """Eager tensor. `stop_gradient` defaults True (ref: VarBase default)."""

    __slots__ = (
        "_value",
        "stop_gradient",
        "_grad",
        "_node",
        "_out_index",
        "name",
        "persistable",
        "is_leaf_retain",
        "_grad_hooks",
        "sharding_spec",
        "process_mesh",
        "_st_sym",  # (program, sym_id) when produced under static capture
        "__weakref__",
    )

    def __init__(self, value, stop_gradient: bool = True, name: str | None = None):
        if isinstance(value, Tensor):
            value = value._value
        if not isinstance(value, (jax.Array, jax.core.Tracer)):
            value = jnp.asarray(value)
        self._value = value
        self.stop_gradient = stop_gradient
        self._grad = None
        self._node: tape.TapeNode | None = None
        self._out_index = 0
        self.name = name or ""
        self.persistable = False
        self.is_leaf_retain = False
        self._grad_hooks: list[Callable] = []
        self.sharding_spec = None  # logical PartitionSpec used by distributed train steps

    # ------------------------------------------------------------------ properties
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self):
        from ..core import device as _device

        try:
            devs = self._value.devices()
            dev = next(iter(devs))
            kind = _device._kind(dev)
            return _device.TPUPlace(dev.id) if kind == "tpu" else _device.CPUPlace(dev.id)
        except Exception:
            return _device._get_place()

    @property
    def grad(self):
        if self._grad is None:
            return None
        return Tensor(self._grad, stop_gradient=True)

    @grad.setter
    def grad(self, value):
        self._grad = _unwrap(value) if value is not None else None

    @property
    def is_leaf(self):
        return self._node is None

    @property
    def T(self):
        from . import manipulation

        return manipulation.transpose(self, list(range(self.ndim))[::-1])

    # ------------------------------------------------------------------ numpy bridge
    def numpy(self):
        self._guard_static_inspect("numpy()")
        return np.asarray(self._value)

    def __array__(self, dtype=None):
        self._guard_static_inspect("np.asarray()")
        arr = np.asarray(self._value)
        return arr.astype(dtype) if dtype is not None else arr

    def item(self, *args):
        self._guard_static_inspect("item()")
        return self._value.item(*args)

    def tolist(self):
        return np.asarray(self._value).tolist()

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-D tensor")
        return self._value.shape[0]

    def __repr__(self):
        grad_str = "" if self.stop_gradient else ", stop_gradient=False"
        return (
            f"Tensor(shape={self.shape}, dtype={self._value.dtype}{grad_str},\n"
            f"       {np.array2string(np.asarray(jax.device_get(self._value)), prefix='       ')})"
        )

    def _guard_static_inspect(self, what):
        """Raise when build-time code inspects the VALUE of a symbolic tensor
        during static capture: builders execute on zero placeholders, so any
        Python branching on the value would silently bake in the zero branch.
        (The reference's static Variable cannot be value-inspected at all.)"""
        sym = getattr(self, "_st_sym", None)
        if sym is not None and _static_active_program is not None \
                and sym[0] is _static_active_program:
            raise RuntimeError(
                f"static capture: {what} called on a symbolic tensor during "
                "program build — its value here is a zero placeholder, not "
                "runtime data.  Use static.nn.cond/while_loop for "
                "value-dependent control flow, or fetch the value via "
                "Executor.run")

    def __bool__(self):
        self._guard_static_inspect("bool()")
        if self.size != 1:
            raise ValueError("truth value of multi-element Tensor is ambiguous")
        return bool(self._value)

    def __int__(self):
        self._guard_static_inspect("int()")
        return int(self._value)

    def __float__(self):
        self._guard_static_inspect("float()")
        return float(self._value)

    def __hash__(self):
        return id(self)

    def __format__(self, spec):
        if self.size == 1:
            return format(self.item(), spec)
        return repr(self)

    # ------------------------------------------------------------------ autograd
    def backward(self, grad_tensor=None, retain_graph: bool = False):
        tape.run_backward([self], [grad_tensor], retain_graph=retain_graph)

    def _accumulate_grad(self, g):
        if g.dtype != self._value.dtype:
            g = g.astype(self._value.dtype)
        if self._grad is None:
            self._grad = g
        else:
            self._grad = self._grad + g

    def clear_grad(self):
        self._grad = None

    def clear_gradient(self, set_to_zero: bool = False):
        self._grad = jnp.zeros_like(self._value) if set_to_zero else None

    def zero_grad(self):
        self.clear_grad()

    def detach(self) -> "Tensor":
        t = Tensor(self._value, stop_gradient=True, name=self.name)
        sym = getattr(self, "_st_sym", None)
        if sym is not None:
            # detach is identity on the value: under static capture the
            # detached view keeps the symbolic identity (otherwise it would
            # be mis-classified as an external live leaf holding its
            # build-time placeholder value)
            t._st_sym = sym
        return t

    def detach_(self):
        self._node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from . import math as _math

        return _math.assign(self)

    def register_hook(self, hook):
        """Grad hook (ref: varbase_patch_methods.py register_hook)."""

        def _h(g):
            r = hook(Tensor(g, stop_gradient=True))
            return g if r is None else _unwrap(r)

        self._grad_hooks.append(_h)
        handle = _HookHandle(self._grad_hooks, _h)
        return handle

    def retain_grads(self):
        self.is_leaf_retain = True
        self.stop_gradient = False

    # ------------------------------------------------------------------ mutation
    def set_value(self, value):
        """In-place value swap (rebind; the old autograd history is kept for grads
        already recorded — matches reference set_value semantics for parameters).

        Under static capture, setting a captured value records a program
        STATE WRITE (the analog of batch_norm's MeanOut in-graph output) and
        leaves the eager value untouched — the compiled step updates it."""
        if _static_state_write_hook is not None and isinstance(value, Tensor):
            if _static_state_write_hook(self, value):
                return self
        v = _unwrap(value)
        if not isinstance(v, (jax.Array, jax.core.Tracer)):
            v = jnp.asarray(v, dtype=self._value.dtype)
        if tuple(v.shape) != tuple(self._value.shape):
            raise ValueError(f"set_value shape mismatch {v.shape} vs {self._value.shape}")
        if v.dtype != self._value.dtype:
            v = v.astype(self._value.dtype)
        self._value = v
        return self

    def copy_(self, other, blocking=True):
        return self.set_value(other)

    def _rebind(self, v):
        """Internal: replace the underlying array AND clear tape history."""
        self._value = v
        self._node = None
        self._out_index = 0
        return self

    def _assume(self, other: "Tensor"):
        """Internal: become `other` INCLUDING its tape node — the in-place-op
        contract (relu_ etc. stay differentiable, unlike _rebind).

        The op that produced `other` recorded `self` among its tape inputs; if
        self simply adopted the new node, that recorded input would point at
        the node's own output (a self-loop) and the cotangent would be lost.
        So the recorded input is rewritten to a snapshot carrying self's OLD
        tape position (the reference's TensorWrapper/version-counter dance
        collapses to this under a functional tape)."""
        if other._node is not None:
            if self._node is None and not self.stop_gradient:
                raise RuntimeError(
                    "a leaf Tensor that requires grad is being used in an "
                    "in-place operation")
            snap = Tensor(self._value, stop_gradient=self.stop_gradient)
            snap._node = self._node
            snap._out_index = self._out_index
            # hooks belong to the VARIABLE, which now lives at the new tape
            # position — the snapshot edge must carry none or they fire twice
            snap._grad_hooks = []
            other._node.inputs = [snap if i is self else i
                                  for i in other._node.inputs]
            self._node = other._node
            self._out_index = other._out_index
            # the result of a differentiable op is differentiable, whatever
            # the old flag said (e.g. scatter_ into a constant with tracked
            # updates must pass gradients through)
            self.stop_gradient = False
        # op recorded no node (e.g. under no_grad): keep the existing history —
        # backward uses the tape's saved values, matching reference semantics
        self._value = other._value
        return self

    # value access used throughout the framework
    @property
    def value(self):
        return self._value

    def cpu(self):
        return Tensor(jax.device_get(self._value), stop_gradient=self.stop_gradient)

    def pin_memory(self):
        return self

    def cuda(self, *a, **k):
        return self

    def to(self, *args, **kwargs):
        # minimal parity: .to(dtype) / .to(device)
        for a in args:
            if isinstance(a, str) and a in ("cpu", "tpu", "gpu"):
                continue
            return self.astype(a)
        if "dtype" in kwargs:
            return self.astype(kwargs["dtype"])
        return self


class _HookHandle:
    def __init__(self, store, fn):
        self._store = store
        self._fn = fn

    def remove(self):
        try:
            self._store.remove(self._fn)
        except ValueError:
            pass


class Parameter(Tensor):
    """Trainable tensor (ref: python/paddle/fluid/framework.py Parameter).

    stop_gradient defaults False; `trainable` toggles it.
    """

    __slots__ = ("optimize_attr", "regularizer", "need_clip", "is_distributed",
                 "_asp_mask")

    def __init__(self, value, stop_gradient: bool | None = None, name: str | None = None, trainable=None):
        if trainable is not None:
            sg = not trainable
        elif stop_gradient is not None:
            sg = stop_gradient
        else:
            sg = False
        super().__init__(value, stop_gradient=sg, name=name)
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.is_distributed = False

    @property
    def trainable(self):
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v):
        self.stop_gradient = not v

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


# ----------------------------------------------------------------------------- op apply

# AMP autocast hook, registered by paddle_tpu.amp on import (avoids an import cycle).
_amp_cast_hook = None
# set by static.program._activate while a Program capture is live: records
# (pure_fn, tensor_args, raw_kwargs, outputs, name) onto the active Program
_static_capture_hook = None
# set alongside: set_value(captured) promotes buffer mutations to program
# state (BN running stats); the active program enables the value-inspection
# guard on placeholder-derived tensors
_static_state_write_hook = None
_static_active_program = None
_amp_state_ref = None


def _amp_enabled():
    return _amp_state_ref is not None and _amp_state_ref.get("enabled", False)


def apply_op(fn: Callable, args: tuple, kwargs: dict | None = None, name: str = "op", n_outputs: int | None = None):
    """The single dispatch point for every differentiable primitive op.

    Ref analog: the generated `*_dygraph_function` (eager_gen.py:271-295): run the
    kernel, then create a GradNode capturing inputs.  Here the "kernel" is a pure JAX
    function and the GradNode is the `jax.vjp` closure.
    `fn` receives raw arrays for every Tensor argument (positional only for
    differentiable ones).
    """
    kwargs = kwargs or {}
    raw_args = [_unwrap(a) for a in args]
    raw_kwargs = {k: _unwrap(v) for k, v in kwargs.items()}

    if _amp_cast_hook is not None and _amp_enabled():
        inner = fn
        fn = lambda *a, **k: inner(*_amp_cast_hook(name, list(a)), **k)

    diff_idx = [
        i
        for i, a in enumerate(args)
        if isinstance(a, Tensor)
        and not a.stop_gradient
        and _dt.is_differentiable(a._value.dtype)
    ]

    if not tape.is_grad_enabled() or not diff_idx:
        out = fn(*raw_args, **raw_kwargs)
        res = _wrap_outputs(out, None, name)
        if _static_capture_hook is not None:
            _static_capture_hook(fn, args, raw_kwargs, res, name)
        return res

    def closed(*diff_arrays):
        full = list(raw_args)
        for i, arr in zip(diff_idx, diff_arrays):
            full[i] = arr
        return fn(*full, **raw_kwargs)

    out, vjp_fn = jax.vjp(closed, *[raw_args[i] for i in diff_idx])
    node_inputs = [args[i] for i in diff_idx]
    is_tuple = isinstance(out, (tuple, list))
    outs_flat = out if is_tuple else (out,)
    out_avals = [(o.shape, o.dtype) for o in outs_flat]
    node = tape.TapeNode(vjp_fn, node_inputs, out_avals, name=name, out_is_tuple=is_tuple,
                         primal_fn=closed)
    res = _wrap_outputs(out, node, name)
    if _static_capture_hook is not None:
        _static_capture_hook(fn, args, raw_kwargs, res, name)
    return res


def _host_nan_check(name, arr):
    if not np.all(np.isfinite(arr)):
        raise RuntimeError(
            f"Operator '{name}' output contains Inf or NaN "
            f"(FLAGS_check_nan_inf is on; ref framework/details/nan_inf_utils.h:29)")


def _check_nan_inf(name, out):
    """Per-op NaN/Inf debug mode (ref FLAGS_check_nan_inf + nan_inf_utils.h:29:
    CheckVarHasNanOrInf after every op).  Eager values are checked inline;
    traced values get a host callback so the check also fires inside jit."""
    from ..framework import flags as _flags

    if not _flags.get_flag("FLAGS_check_nan_inf", False):
        return
    for o in out if isinstance(out, (tuple, list)) else (out,):
        if hasattr(o, "dtype") and _dt.is_floating(o.dtype):
            if isinstance(o, jax.core.Tracer):
                jax.debug.callback(_host_nan_check, name, o)
            else:
                _host_nan_check(name, np.asarray(o))


def _wrap_outputs(out, node, name):
    _check_nan_inf(name, out)
    if isinstance(out, (tuple, list)):
        wrapped = []
        for i, o in enumerate(out):
            t = Tensor(o, stop_gradient=node is None)
            t._node = node
            t._out_index = i
            wrapped.append(t)
        return tuple(wrapped)
    t = Tensor(out, stop_gradient=node is None)
    t._node = node
    return t


def defop(name: str, fn: Callable):
    """Declaratively produce a user-facing op from a pure-JAX impl.

    This replaces the reference's YAML->C++ codegen pipeline
    (`paddle/phi/api/yaml/generator/api_gen.py`): the op table IS the API.
    """

    def op(*args, **kwargs):
        return apply_op(fn, args, kwargs, name=name)

    op.__name__ = name
    op.__qualname__ = name
    op.raw = fn
    return op
