"""Tensor creation ops (ref: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .tensor import Tensor, Parameter, apply_op, _unwrap
from ..core import dtypes as _dt
from ..core import device as _device
from ..framework import random as _random


def _shape_arg(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._value))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) if not isinstance(s, Tensor) else int(s.item()) for s in shape)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor parity."""
    if isinstance(data, Tensor):
        v = data._value
        if dtype is not None:
            v = v.astype(_dt.convert_dtype(dtype))
        t = Tensor(v, stop_gradient=stop_gradient)
        return t
    if isinstance(data, (jax.Array,)):
        v = data
    else:
        arr = np.asarray(data)
        if dtype is None and arr.dtype == np.float64:
            arr = arr.astype(_dt.get_default_dtype())
        v = jnp.asarray(arr)
    if dtype is not None:
        v = v.astype(_dt.convert_dtype(dtype))
    return Tensor(v, stop_gradient=stop_gradient)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape_arg(shape), _dt.convert_dtype(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape_arg(shape), _dt.convert_dtype(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    fv = _unwrap(fill_value)
    if dtype is None and isinstance(fill_value, (bool, int, float)):
        dtype = _dt.get_default_dtype() if isinstance(fill_value, float) else None
    d = _dt.convert_dtype(dtype) if dtype is not None else None
    return Tensor(jnp.full(_shape_arg(shape), fv, d))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    d = _dt.convert_dtype(dtype) if dtype is not None else None
    return apply_op(lambda v: jnp.zeros_like(v, dtype=d), (x,), name="zeros_like")


def ones_like(x, dtype=None, name=None):
    d = _dt.convert_dtype(dtype) if dtype is not None else None
    return apply_op(lambda v: jnp.ones_like(v, dtype=d), (x,), name="ones_like")


def full_like(x, fill_value, dtype=None, name=None):
    d = _dt.convert_dtype(dtype) if dtype is not None else None
    return apply_op(lambda v, f: jnp.full_like(v, f, dtype=d), (x, fill_value), name="full_like")


def empty_like(x, dtype=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    start = _unwrap(start)
    end = _unwrap(end)
    step = _unwrap(step)
    if end is None:
        start, end = 0, start
    d = _dt.convert_dtype(dtype) if dtype is not None else None
    return Tensor(jnp.arange(start, end, step, dtype=d))


def linspace(start, stop, num, dtype=None, name=None):
    d = _dt.convert_dtype(dtype) if dtype is not None else None
    return Tensor(jnp.linspace(_unwrap(start), _unwrap(stop), int(_unwrap(num)), dtype=d))


def logspace(start, stop, num, base=10.0, dtype=None):
    d = _dt.convert_dtype(dtype) if dtype is not None else None
    return Tensor(jnp.logspace(_unwrap(start), _unwrap(stop), int(_unwrap(num)), base=base, dtype=d))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt.convert_dtype(dtype)))


def meshgrid(*args, **kwargs):
    tensors = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args
    outs = jnp.meshgrid(*[_unwrap(t) for t in tensors], indexing="ij")
    return [Tensor(o) for o in outs]


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = jnp.tril_indices(row, offset, col)
    return Tensor(jnp.stack([r, c]))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r, c = jnp.triu_indices(row, offset, col if col is not None else row)
    return Tensor(jnp.stack([r, c]))


def clone(x, name=None):
    from . import math as _math

    return _math.assign(x)


def numel(x, name=None):
    return Tensor(jnp.asarray(x.size))


def create_parameter(shape, dtype=None, name=None, attr=None, is_bias=False, default_initializer=None):
    d = _dt.convert_dtype(dtype)
    if default_initializer is None:
        from ..nn.initializer import Constant, XavierNormal

        default_initializer = Constant(0.0) if is_bias else XavierNormal()
    p = Parameter(jnp.zeros(_shape_arg(shape), d), name=name)
    default_initializer(p)
    return p


# --------------------------------------------------------------------- random creation
def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, min=0.0, max=1.0)


def randn(shape, dtype=None, name=None):
    d = _dt.convert_dtype(dtype)
    return Tensor(jax.random.normal(_random.get_rng_key(), _shape_arg(shape), dtype=d))


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if shape is None:
        shape = np.broadcast_shapes(np.shape(_unwrap(mean)), np.shape(_unwrap(std)))
    d = _dt.get_default_dtype()
    noise = jax.random.normal(_random.get_rng_key(), _shape_arg(shape) if shape else (), dtype=d)
    return Tensor(noise * _unwrap(std) + _unwrap(mean))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    d = _dt.convert_dtype(dtype)
    key = _random.make_key(seed) if seed else _random.get_rng_key()
    return Tensor(jax.random.uniform(key, _shape_arg(shape), dtype=d, minval=float(_unwrap(min)), maxval=float(_unwrap(max))))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return Tensor(
        jax.random.randint(_random.get_rng_key(), _shape_arg(shape), int(low), int(high)).astype(
            _dt.convert_dtype(dtype)
        )
    )


def randint_like(x, low=0, high=None, dtype=None):
    return randint(low, high, tuple(x.shape), dtype or str(x.dtype))


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(_random.get_rng_key(), n).astype(_dt.convert_dtype(dtype)))


def multinomial(x, num_samples=1, replacement=False, name=None):
    logits = jnp.log(jnp.clip(_unwrap(x), 1e-30, None))
    if replacement:
        out = jax.random.categorical(_random.get_rng_key(), logits, axis=-1, shape=(*logits.shape[:-1], num_samples) if logits.ndim > 1 else (num_samples,))
        if logits.ndim > 1:
            out = out.reshape(*logits.shape[:-1], num_samples)
        return Tensor(out.astype(jnp.int64))
    # without replacement: gumbel top-k
    g = jax.random.gumbel(_random.get_rng_key(), logits.shape)
    _, idx = jax.lax.top_k(logits + g, num_samples)
    return Tensor(idx.astype(jnp.int64))


def bernoulli(x, name=None):
    return Tensor(jax.random.bernoulli(_random.get_rng_key(), _unwrap(x)).astype(x.dtype))


def poisson(x, name=None):
    return Tensor(jax.random.poisson(_random.get_rng_key(), _unwrap(x)).astype(x.dtype))


def assign(x, output=None):
    from . import math as _math

    return _math.assign(x, output)
