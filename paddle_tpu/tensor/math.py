"""Math ops (paddle.tensor.math parity).

Reference: `python/paddle/tensor/math.py` wrappers dispatching to phi kernels
(`paddle/phi/kernels/*`).  TPU-native: each op is a pure jax.numpy composition that XLA
fuses/tiles onto the VPU/MXU; autograd comes from `apply_op`'s jax.vjp (tensor.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .tensor import Tensor, apply_op, defop, _unwrap
from ..core import dtypes as _dt


def _op(name, fn):
    g = defop(name, fn)
    globals()[name] = g
    return g


# ----------------------------------------------------------------- binary arithmetic
add = _op("add", lambda x, y: jnp.add(x, y))
subtract = _op("subtract", lambda x, y: jnp.subtract(x, y))
multiply = _op("multiply", lambda x, y: jnp.multiply(x, y))
divide = _op("divide", lambda x, y: jnp.divide(x, y))
floor_divide = _op("floor_divide", lambda x, y: jnp.floor_divide(x, y))
mod = _op("mod", lambda x, y: jnp.mod(x, y))
remainder = mod
floor_mod = mod
pow = _op("pow", lambda x, y: jnp.power(x, y))
maximum = _op("maximum", lambda x, y: jnp.maximum(x, y))
minimum = _op("minimum", lambda x, y: jnp.minimum(x, y))
fmax = _op("fmax", lambda x, y: jnp.fmax(x, y))
fmin = _op("fmin", lambda x, y: jnp.fmin(x, y))
atan2 = _op("atan2", lambda x, y: jnp.arctan2(x, y))
hypot = _op("hypot", lambda x, y: jnp.hypot(x, y))
logaddexp = _op("logaddexp", lambda x, y: jnp.logaddexp(x, y))
heaviside = _op("heaviside", lambda x, y: jnp.heaviside(x, y))
copysign = _op("copysign", lambda x, y: jnp.copysign(x, y))
nextafter = _op("nextafter", lambda x, y: jnp.nextafter(x, y))
ldexp = _op("ldexp", lambda x, y: jnp.ldexp(x, y))
gcd = _op("gcd", lambda x, y: jnp.gcd(x, y))
lcm = _op("lcm", lambda x, y: jnp.lcm(x, y))

# ----------------------------------------------------------------- unary
abs = _op("abs", lambda x: jnp.abs(x))
neg = _op("neg", lambda x: jnp.negative(x))
exp = _op("exp", lambda x: jnp.exp(x))
expm1 = _op("expm1", lambda x: jnp.expm1(x))
log = _op("log", lambda x: jnp.log(x))
log2 = _op("log2", lambda x: jnp.log2(x))
log10 = _op("log10", lambda x: jnp.log10(x))
log1p = _op("log1p", lambda x: jnp.log1p(x))
sqrt = _op("sqrt", lambda x: jnp.sqrt(x))
rsqrt = _op("rsqrt", lambda x: jax.lax.rsqrt(x))
square = _op("square", lambda x: jnp.square(x))
sin = _op("sin", lambda x: jnp.sin(x))
cos = _op("cos", lambda x: jnp.cos(x))
tan = _op("tan", lambda x: jnp.tan(x))
asin = _op("asin", lambda x: jnp.arcsin(x))
acos = _op("acos", lambda x: jnp.arccos(x))
atan = _op("atan", lambda x: jnp.arctan(x))
sinh = _op("sinh", lambda x: jnp.sinh(x))
cosh = _op("cosh", lambda x: jnp.cosh(x))
tanh = _op("tanh", lambda x: jnp.tanh(x))
asinh = _op("asinh", lambda x: jnp.arcsinh(x))
acosh = _op("acosh", lambda x: jnp.arccosh(x))
atanh = _op("atanh", lambda x: jnp.arctanh(x))
floor = _op("floor", lambda x: jnp.floor(x))
ceil = _op("ceil", lambda x: jnp.ceil(x))
round = _op("round", lambda x: jnp.round(x))
trunc = _op("trunc", lambda x: jnp.trunc(x))
frac = _op("frac", lambda x: x - jnp.trunc(x))
sign = _op("sign", lambda x: jnp.sign(x))
sgn = sign
reciprocal = _op("reciprocal", lambda x: jnp.reciprocal(x))
erf = _op("erf", lambda x: jax.scipy.special.erf(x))
erfinv = _op("erfinv", lambda x: jax.scipy.special.erfinv(x))
lgamma = _op("lgamma", lambda x: jax.scipy.special.gammaln(x))
digamma = _op("digamma", lambda x: jax.scipy.special.digamma(x))
polygamma = _op("polygamma", lambda x, n=1: jax.scipy.special.polygamma(n, x))
i0 = _op("i0", lambda x: jax.scipy.special.i0(x))
i1 = _op("i1", lambda x: jax.scipy.special.i1(x))
deg2rad = _op("deg2rad", lambda x: jnp.deg2rad(x))
rad2deg = _op("rad2deg", lambda x: jnp.rad2deg(x))
angle = _op("angle", lambda x: jnp.angle(x))
conj = _op("conj", lambda x: jnp.conj(x))
real = _op("real", lambda x: jnp.real(x))
imag = _op("imag", lambda x: jnp.imag(x))
nan_to_num = _op("nan_to_num", lambda x, nan=0.0, posinf=None, neginf=None: jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf))
logit = _op("logit", lambda x, eps=None: jax.scipy.special.logit(jnp.clip(x, eps, 1 - eps) if eps else x))
sigmoid = _op("sigmoid", lambda x: jax.nn.sigmoid(x))
rint = _op("rint", lambda x: jnp.rint(x))
exp2 = _op("exp2", lambda x: jnp.exp2(x))


def clip(x, min=None, max=None):
    return apply_op(lambda v, lo, hi: jnp.clip(v, lo, hi), (x, min, max), name="clip")


def lerp(x, y, weight):
    return apply_op(lambda a, b, w: a + w * (b - a), (x, y, weight), name="lerp")


def stanh(x, scale_a=0.67, scale_b=1.7159):
    return apply_op(lambda v: scale_b * jnp.tanh(scale_a * v), (x,), name="stanh")


# ----------------------------------------------------------------- matmul family
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    """Ref: python/paddle/tensor/linalg.py:128; phi MatmulKernel.  Feeds the MXU."""

    def _mm(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        return jnp.matmul(a, b)

    return apply_op(_mm, (x, y), name="matmul")


mm = matmul


def bmm(x, y):
    return apply_op(lambda a, b: jnp.matmul(a, b), (x, y), name="bmm")


def dot(x, y):
    return apply_op(lambda a, b: jnp.sum(a * b, axis=-1), (x, y), name="dot")


def inner(x, y):
    return apply_op(lambda a, b: jnp.inner(a, b), (x, y), name="inner")


def outer(x, y):
    return apply_op(lambda a, b: jnp.outer(a, b), (x, y), name="outer")


def kron(x, y):
    return apply_op(lambda a, b: jnp.kron(a, b), (x, y), name="kron")


def addmm(input, x, y, beta=1.0, alpha=1.0):
    return apply_op(lambda i, a, b: beta * i + alpha * jnp.matmul(a, b), (input, x, y), name="addmm")


def cross(x, y, axis=9):
    ax = axis if axis != 9 else -1
    return apply_op(lambda a, b: jnp.cross(a, b, axis=ax), (x, y), name="cross")


def multiply_(x, y):  # in-place parity, differentiable like the reference
    out = multiply(x, y)
    if tuple(out.shape) != tuple(x.shape):
        raise ValueError(
            f"multiply_: in-place result shape {out.shape} must match "
            f"x.shape {x.shape} (broadcasting may not resize the target)")
    return x._assume(out)


# ----------------------------------------------------------------- reductions
def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    d = _dt.convert_dtype(dtype) if dtype is not None else None
    return apply_op(
        lambda v: jnp.sum(v, axis=_norm_axis(axis), dtype=d, keepdims=keepdim),
        (x,),
        name="sum",
    )


def mean(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda v: jnp.mean(v, axis=_norm_axis(axis), keepdims=keepdim), (x,), name="mean")


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    d = _dt.convert_dtype(dtype) if dtype is not None else None
    return apply_op(lambda v: jnp.prod(v, axis=_norm_axis(axis), dtype=d, keepdims=keepdim), (x,), name="prod")


def max(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda v: jnp.max(v, axis=_norm_axis(axis), keepdims=keepdim), (x,), name="max")


def min(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda v: jnp.min(v, axis=_norm_axis(axis), keepdims=keepdim), (x,), name="min")


amax = max
amin = min


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op(
        lambda v: jnp.std(v, axis=_norm_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim),
        (x,),
        name="std",
    )


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op(
        lambda v: jnp.var(v, axis=_norm_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim),
        (x,),
        name="var",
    )


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    return apply_op(lambda v: jnp.median(v, axis=_norm_axis(axis), keepdims=keepdim), (x,), name="median")


def quantile(x, q, axis=None, keepdim=False):
    return apply_op(lambda v: jnp.quantile(v, jnp.asarray(q), axis=_norm_axis(axis), keepdims=keepdim), (x,), name="quantile")


def nansum(x, axis=None, dtype=None, keepdim=False):
    return apply_op(lambda v: jnp.nansum(v, axis=_norm_axis(axis), keepdims=keepdim), (x,), name="nansum")


def nanmean(x, axis=None, keepdim=False):
    return apply_op(lambda v: jnp.nanmean(v, axis=_norm_axis(axis), keepdims=keepdim), (x,), name="nanmean")


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply_op(
        lambda v: jax.scipy.special.logsumexp(v, axis=_norm_axis(axis), keepdims=keepdim),
        (x,),
        name="logsumexp",
    )


def cumsum(x, axis=None, dtype=None, name=None):
    def _f(v):
        if axis is None:
            return jnp.cumsum(v.reshape(-1))
        return jnp.cumsum(v, axis=axis)

    return apply_op(_f, (x,), name="cumsum")


def cumprod(x, dim=None, dtype=None, name=None):
    return apply_op(lambda v: jnp.cumprod(v, axis=dim), (x,), name="cumprod")


def cummax(x, axis=None, dtype="int64"):
    def _f(v):
        vals = jax.lax.associative_scan(jnp.maximum, v, axis=axis or 0)
        return vals

    return apply_op(_f, (x,), name="cummax")


def logcumsumexp(x, axis=None, name=None):
    def _f(v):
        if axis is None:
            v = v.reshape(-1)
            ax = 0
        else:
            ax = axis
        return jax.lax.cumlogsumexp(v, axis=ax)

    return apply_op(_f, (x,), name="logcumsumexp")


def trace(x, offset=0, axis1=0, axis2=1):
    return apply_op(lambda v: jnp.trace(v, offset=offset, axis1=axis1, axis2=axis2), (x,), name="trace")


def diagonal(x, offset=0, axis1=0, axis2=1):
    return apply_op(lambda v: jnp.diagonal(v, offset=offset, axis1=axis1, axis2=axis2), (x,), name="diagonal")


def count_nonzero(x, axis=None, keepdim=False):
    return apply_op(lambda v: jnp.count_nonzero(v, axis=_norm_axis(axis), keepdims=keepdim), (x,), name="count_nonzero")


# ----------------------------------------------------------------- misc
def assign(x, output=None):
    out = apply_op(lambda v: v + 0, (x,), name="assign")
    if output is not None:
        output.set_value(out._value)
        return output
    return out


def increment(x, value=1.0):
    x.set_value(x._value + value)
    return x


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    def _f(v, s, b):
        r = v * s + b if bias_after_scale else (v + b) * s
        return r

    out = apply_op(_f, (x, scale, bias), name="scale")
    if act:
        from ..nn import functional as F

        out = getattr(F, act)(out)
    return out


def isfinite(x):
    return apply_op(lambda v: jnp.isfinite(v), (x,), name="isfinite")


def isnan(x):
    return apply_op(lambda v: jnp.isnan(v), (x,), name="isnan")


def isinf(x):
    return apply_op(lambda v: jnp.isinf(v), (x,), name="isinf")


def isneginf(x):
    return apply_op(lambda v: jnp.isneginf(v), (x,), name="isneginf")


def isposinf(x):
    return apply_op(lambda v: jnp.isposinf(v), (x,), name="isposinf")


def isreal(x):
    return apply_op(lambda v: jnp.isreal(v), (x,), name="isreal")


def diff(x, n=1, axis=-1, prepend=None, append=None):
    def _f(v, pre, app):
        kw = {}
        if pre is not None:
            kw["prepend"] = pre
        if app is not None:
            kw["append"] = app
        return jnp.diff(v, n=n, axis=axis, **kw)

    return apply_op(_f, (x, prepend, append), name="diff")


def histogram(x, bins=100, min=0, max=0, name=None):
    def _f(v):
        lo, hi = (min, max) if (min != 0 or max != 0) else (jnp.min(v), jnp.max(v))
        h, _ = jnp.histogram(v, bins=bins, range=(lo, hi))
        return h

    return apply_op(_f, (x,), name="histogram")


def bincount(x, weights=None, minlength=0):
    return apply_op(
        lambda v, w: jnp.bincount(v, weights=w, minlength=minlength, length=None),
        (x, weights),
        name="bincount",
    )


def broadcast_shape(x_shape, y_shape):
    import numpy as np

    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def multiplex(inputs, index):
    def _f(idx, *ins):
        stacked = jnp.stack(ins, axis=0)  # [n, batch, ...]
        sel = idx.reshape(-1)
        return jnp.take_along_axis(
            stacked, sel.reshape(1, -1, *([1] * (stacked.ndim - 2))), axis=0
        )[0]

    return apply_op(_f, (index, *inputs), name="multiplex")


def add_n(inputs, name=None):
    """Ref math.py add_n: elementwise sum of a list of tensors."""
    if isinstance(inputs, Tensor):
        inputs = [inputs]  # still produce a NEW tensor, never an alias
    if not inputs:
        raise ValueError("add_n needs at least one input")

    def _f(*vs):
        out = vs[0]
        for v in vs[1:]:
            out = out + v
        return out

    return apply_op(_f, tuple(inputs), name="add_n")


def mv(x, vec, name=None):
    """Ref linalg mv: matrix @ vector."""
    return apply_op(lambda m, v: jnp.matmul(m, v), (x, vec), name="mv")


def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda v: jnp.nanmedian(v, axis=axis, keepdims=keepdim),
                    (x,), name="nanmedian")


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return apply_op(lambda v: jnp.nanquantile(v, q, axis=axis, keepdims=keepdim),
                    (x,), name="nanquantile")


def renorm(x, p, axis, max_norm, name=None):
    """Ref math.py renorm: clamp the p-norm of every slice along `axis`."""

    def _f(v):
        axes = tuple(i for i in range(v.ndim) if i != (axis % v.ndim))
        norms = jnp.sum(jnp.abs(v.astype(jnp.float32)) ** p, axis=axes,
                        keepdims=True) ** (1.0 / p)
        scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        return (v * scale.astype(v.dtype))

    return apply_op(_f, (x,), name="renorm")


def tanh_(x, name=None):
    """In-place tanh (ref inplace APIs) — differentiable like the reference."""
    return x._assume(tanh(x))
