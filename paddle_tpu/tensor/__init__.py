"""Tensor package: assembles the Tensor API surface.

Reference analog: `python/paddle/tensor/__init__.py`, which monkey-patches generated op
wrappers onto the C++ tensor type (`tensor_method_func` list).  We do the same
declaratively: every public op in the sub-modules becomes a Tensor method, and Python
operators map onto them.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .tensor import Tensor, Parameter, apply_op, defop, _unwrap
from . import creation, math, manipulation, logic, search, linalg
from . import stat  # noqa: F401  (after math to avoid cycle)

# ---------------------------------------------------------------- operator overloads


def _binop(fn, reverse=False):
    def op(self, other):
        if isinstance(other, (list, tuple, np.ndarray)):
            other = Tensor(jnp.asarray(other))
        if reverse:
            return fn(other, self)
        return fn(self, other)

    return op


Tensor.__add__ = _binop(math.add)
Tensor.__radd__ = _binop(math.add, True)
Tensor.__sub__ = _binop(math.subtract)
Tensor.__rsub__ = _binop(math.subtract, True)
Tensor.__mul__ = _binop(math.multiply)
Tensor.__rmul__ = _binop(math.multiply, True)
Tensor.__truediv__ = _binop(math.divide)
Tensor.__rtruediv__ = _binop(math.divide, True)
Tensor.__floordiv__ = _binop(math.floor_divide)
Tensor.__rfloordiv__ = _binop(math.floor_divide, True)
Tensor.__mod__ = _binop(math.mod)
Tensor.__pow__ = _binop(math.pow)
Tensor.__rpow__ = _binop(math.pow, True)
Tensor.__matmul__ = _binop(math.matmul)
Tensor.__rmatmul__ = _binop(math.matmul, True)
Tensor.__neg__ = lambda self: math.neg(self)
Tensor.__abs__ = lambda self: math.abs(self)
Tensor.__eq__ = _binop(logic.equal)
Tensor.__ne__ = _binop(logic.not_equal)
Tensor.__lt__ = _binop(logic.less_than)
Tensor.__le__ = _binop(logic.less_equal)
Tensor.__gt__ = _binop(logic.greater_than)
Tensor.__ge__ = _binop(logic.greater_equal)
Tensor.__and__ = _binop(logic.logical_and)
Tensor.__or__ = _binop(logic.logical_or)
Tensor.__xor__ = _binop(logic.logical_xor)
Tensor.__invert__ = lambda self: logic.logical_not(self)


def _getitem(self, idx):
    def normalize(i):
        if isinstance(i, Tensor):
            a = i._value
            return a
        if isinstance(i, (list, np.ndarray)):
            return jnp.asarray(i)
        return i

    if isinstance(idx, tuple):
        nidx = tuple(normalize(i) for i in idx)
    else:
        nidx = normalize(idx)

    # boolean-mask indexing has a dynamic result shape -> host eager
    def _has_bool(i):
        import jax

        return hasattr(i, "dtype") and i.dtype == jnp.bool_ and not isinstance(i, jax.core.Tracer)

    items = nidx if isinstance(nidx, tuple) else (nidx,)
    if any(_has_bool(i) for i in items):
        v = np.asarray(self._value)
        np_idx = tuple(np.asarray(i) if hasattr(i, "dtype") else i for i in items)
        return Tensor(jnp.asarray(v[np_idx if isinstance(nidx, tuple) else np_idx[0]]))

    return apply_op(lambda v: v[nidx], (self,), name="getitem")


def _setitem(self, idx, value):
    def normalize(i):
        if isinstance(i, Tensor):
            return i._value
        if isinstance(i, (list, np.ndarray)):
            return jnp.asarray(i)
        return i

    nidx = tuple(normalize(i) for i in idx) if isinstance(idx, tuple) else normalize(idx)

    def _set(v, val):
        val = jnp.asarray(val, v.dtype) if not hasattr(val, "dtype") else val.astype(v.dtype)
        return v.at[nidx].set(val)

    out = apply_op(_set, (self, value), name="setitem")
    # adopt the result THROUGH the in-place contract: plainly taking out's
    # node would leave the node's recorded `self` input pointing at the
    # node's own output (a self-loop) and drop the cotangents for both the
    # base and the assigned value (see Tensor._assume)
    return self._assume(out)


Tensor.__getitem__ = _getitem
Tensor.__setitem__ = _setitem

# ---------------------------------------------------------------- method attachment

_METHOD_SOURCES = [math, manipulation, logic, search, linalg, stat]
_SKIP = {
    "einsum",  # first arg is the equation string, not a tensor
    "matmul_",
    "assign",
    "builtins_sum",
    "builtins_abs",
    "broadcast_shape",
    "slice_builtin",
}


def _make_method(fn):
    def method(self, *args, **kwargs):
        return fn(self, *args, **kwargs)

    method.__name__ = fn.__name__
    return method


for _mod in _METHOD_SOURCES:
    for _name in dir(_mod):
        if _name.startswith("_") or _name in _SKIP:
            continue
        _fn = getattr(_mod, _name)
        if not callable(_fn) or isinstance(_fn, type):
            continue
        if getattr(_fn, "__module__", "").startswith("jax") or getattr(_fn, "__module__", "") in ("numpy",):
            continue
        if not hasattr(Tensor, _name):
            setattr(Tensor, _name, _make_method(_fn))

# explicit aliases / overrides
Tensor.astype = lambda self, dtype: manipulation.cast(self, dtype)
Tensor.cast = Tensor.astype
Tensor.add_ = lambda self, y: self.set_value(self._value + _unwrap(y))
Tensor.subtract_ = lambda self, y: self.set_value(self._value - _unwrap(y))
Tensor.scale_ = lambda self, s=1.0, bias=0.0, **k: self.set_value(self._value * s + bias)
Tensor.zero_ = lambda self: self.set_value(jnp.zeros_like(self._value))
Tensor.fill_ = lambda self, v: self.set_value(jnp.full_like(self._value, v))
Tensor.clip_ = lambda self, min=None, max=None: self.set_value(jnp.clip(self._value, min, max))
Tensor.exponential_ = lambda self, lam=1.0: self.set_value(
    -jnp.log1p(-np.random.rand(*self._value.shape).astype(np.float32)) / lam
)
Tensor.uniform_ = lambda self, min=-1.0, max=1.0, seed=0: self.set_value(
    jnp.asarray(np.random.uniform(min, max, self._value.shape).astype(str(self._value.dtype)))
)
Tensor.normal_ = lambda self, mean=0.0, std=1.0: self.set_value(
    jnp.asarray(np.random.normal(mean, std, self._value.shape).astype(str(self._value.dtype)))
)
Tensor.dim = lambda self: self.ndim
Tensor.rank = lambda self: Tensor(jnp.asarray(self.ndim))
Tensor.numel = lambda self: self.size
Tensor.element_size = lambda self: self._value.dtype.itemsize
Tensor.is_floating_point = lambda self: jnp.issubdtype(self._value.dtype, jnp.floating)
Tensor.is_integer = lambda self: jnp.issubdtype(self._value.dtype, jnp.integer)
Tensor.is_complex = lambda self: jnp.issubdtype(self._value.dtype, jnp.complexfloating)
Tensor.pow = lambda self, y: math.pow(self, y)
Tensor.mod = lambda self, y: math.mod(self, y)
Tensor.remainder = lambda self, y: math.mod(self, y)
Tensor.bfloat16 = lambda self: self.astype("bfloat16")
Tensor.half = lambda self: self.astype("float16")
Tensor.float = lambda self: self.astype("float32")
