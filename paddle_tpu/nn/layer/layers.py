"""nn.Layer base class.

Reference: `python/paddle/fluid/dygraph/layers.py:84` (Layer) — parameters/buffers
registries, sublayer tree, forward hooks, state_dict/set_state_dict, train/eval,
apply, to/astype.  TPU-native addition: `functional_state()`/`load_functional_state()`
expose the parameter+buffer pytree so whole layers drop into jit/pjit train steps.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable, Iterator

import jax
import jax.numpy as jnp
import numpy as np

from ...tensor.tensor import Tensor, Parameter
from ...core import dtypes as _dt


class HookRemoveHelper:
    def __init__(self, store, key):
        self._store = store
        self._key = key

    def remove(self):
        self._store.pop(self._key, None)


_global_layer_counter = [0]


class Layer:
    """Base network layer (ref layers.py:84)."""

    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = _dt.convert_dtype(dtype)
        self._parameters: "OrderedDict[str, Parameter]" = OrderedDict()
        self._buffers: "OrderedDict[str, Tensor]" = OrderedDict()
        self._non_persistable_buffer_names = set()
        self._sub_layers: "OrderedDict[str, Layer]" = OrderedDict()
        self._forward_pre_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self._forward_post_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self._hook_counter = [0]
        _global_layer_counter[0] += 1
        self._full_name = (name_scope or self.__class__.__name__.lower()) + f"_{_global_layer_counter[0]}"

    # ------------------------------------------------------------- registration
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call super().__init__() before assigning parameters")
            params[name] = value
            buffers.pop(name, None) if buffers else None
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call super().__init__() before assigning sublayers")
            layers[name] = value
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                if value is None:
                    params.pop(name)
                    object.__setattr__(self, name, None)
                    return
                raise TypeError(f"cannot assign non-Parameter to parameter slot {name}")
            if buffers is not None and name in buffers:
                if value is None or isinstance(value, Tensor):
                    if value is None:
                        buffers.pop(name)
                        object.__setattr__(self, name, None)
                    else:
                        buffers[name] = value
                    return
            if layers is not None and name in layers and value is None:
                layers.pop(name)
                object.__setattr__(self, name, None)
                return
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        if "_parameters" in self.__dict__ and name in self.__dict__["_parameters"]:
            return self.__dict__["_parameters"][name]
        if "_buffers" in self.__dict__ and name in self.__dict__["_buffers"]:
            return self.__dict__["_buffers"][name]
        if "_sub_layers" in self.__dict__ and name in self.__dict__["_sub_layers"]:
            return self.__dict__["_sub_layers"][name]
        raise AttributeError(f"{self.__class__.__name__} has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False, default_initializer=None):
        """Ref layers.py create_parameter: honors ParamAttr initializer/trainable."""
        from ..initializer import Constant, XavierUniform
        from ...framework.param_attr import ParamAttr

        dtype = _dt.convert_dtype(dtype or self._dtype)
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        init = None
        trainable = True
        if attr is not None:
            init = attr.initializer
            trainable = attr.trainable
        if init is None:
            init = default_initializer or (Constant(0.0) if is_bias else XavierUniform())
        p = Parameter(jnp.zeros([int(s) for s in shape], dtype), trainable=trainable,
                      name=(attr.name if attr is not None else None))
        if attr is not None:
            p.regularizer = attr.regularizer  # ParamAttr regularizer outranks the optimizer's
            p.optimize_attr = {"learning_rate": attr.learning_rate}
        init(p)
        return p

    def create_tensor(self, name=None, persistable=None, dtype=None):
        return Tensor(jnp.zeros([], _dt.convert_dtype(dtype or self._dtype)))

    # ------------------------------------------------------------- iteration
    def parameters(self, include_sublayers=True) -> list:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True, include_self=True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p
            if not include_sublayers:
                break

    def buffers(self, include_sublayers=True) -> list:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b
            if not include_sublayers:
                break

    def sublayers(self, include_self=False) -> list:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from sub.named_sublayers(prefix=sub_prefix, include_self=True, layers_set=layers_set)

    def children(self):
        return iter(l for l in self._sub_layers.values() if l is not None)

    def named_children(self):
        return iter((n, l) for n, l in self._sub_layers.items() if l is not None)

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # ------------------------------------------------------------- modes
    def train(self):
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    # ------------------------------------------------------------- hooks
    def register_forward_pre_hook(self, hook):
        self._hook_counter[0] += 1
        key = self._hook_counter[0]
        self._forward_pre_hooks[key] = hook
        return HookRemoveHelper(self._forward_pre_hooks, key)

    def register_forward_post_hook(self, hook):
        self._hook_counter[0] += 1
        key = self._hook_counter[0]
        self._forward_post_hooks[key] = hook
        return HookRemoveHelper(self._forward_post_hooks, key)

    # ------------------------------------------------------------- call
    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def extra_repr(self):
        return ""

    def __repr__(self):
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            sub_repr = [sub_repr[0]] + ["  " + l for l in sub_repr[1:]]
            lines.append(f"  ({name}): " + "\n".join(sub_repr))
        extra = self.extra_repr()
        main = f"{self.__class__.__name__}({extra}" + ("" if not lines else "\n" + "\n".join(lines) + "\n")
        return main + ")"

    # ------------------------------------------------------------- state dict
    def state_dict(self, destination=None, include_sublayers=True, structured_name_prefix="", use_hook=True):
        """Ref layers.py:1407."""
        hook = getattr(self, "_pre_state_hook", None)
        if hook is not None:
            hook()  # e.g. stacked-pipeline weights written back before reading
        dest = destination if destination is not None else OrderedDict()
        for name, p in self.named_parameters():
            dest[structured_name_prefix + name] = p
        for name, b in self.named_buffers():
            leaf = name.rsplit(".", 1)[-1]
            if leaf in self._find_owner(name)._non_persistable_buffer_names:
                continue
            dest[structured_name_prefix + name] = b
        return dest

    def _find_owner(self, qualified_name):
        parts = qualified_name.split(".")[:-1]
        layer = self
        for p in parts:
            layer = layer._sub_layers.get(p, layer)
        return layer

    def set_state_dict(self, state_dict, use_structured_name=True):
        """Ref layers.py:1442."""
        missing, unexpected = [], []
        own = self.state_dict()
        matched = set()
        for name, t in own.items():
            if name in state_dict:
                v = state_dict[name]
                arr = v._value if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
                if tuple(arr.shape) != tuple(t._value.shape):
                    raise ValueError(f"shape mismatch for {name}: {arr.shape} vs {t._value.shape}")
                t.set_value(arr.astype(t._value.dtype))
                matched.add(name)
            else:
                missing.append(name)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    load_dict = set_state_dict

    # ------------------------------------------------------------- dtype/device
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._cast_all(_dt.convert_dtype(dtype))
        return self

    def astype(self, dtype):
        self._cast_all(_dt.convert_dtype(dtype))
        return self

    def float(self):
        return self.astype("float32")

    def half(self):
        return self.astype("float16")

    def bfloat16(self):
        return self.astype("bfloat16")

    def _cast_all(self, dtype):
        for _, p in self.named_parameters():
            if jnp.issubdtype(p._value.dtype, jnp.floating):
                p._rebind(p._value.astype(dtype))
        for _, b in self.named_buffers():
            if jnp.issubdtype(b._value.dtype, jnp.floating):
                b._rebind(b._value.astype(dtype))
        self._dtype = dtype

    def full_name(self):
        return self._full_name

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    # ------------------------------------------------------------- functional bridge
    def functional_state(self, _sync=True):
        """(params_dict, buffers_dict) of raw jax arrays — the pytree handed to jit."""
        hook = getattr(self, "_pre_state_hook", None)
        if _sync and hook is not None:
            hook()
        params = {k: p._value for k, p in self.named_parameters()}
        buffers = {k: b._value for k, b in self.named_buffers()}
        return params, buffers

    def load_functional_state(self, params=None, buffers=None):
        if params:
            own = dict(self.named_parameters())
            for k, v in params.items():
                own[k]._rebind(v)
        if buffers:
            own = dict(self.named_buffers())
            for k, v in buffers.items():
                own[k]._rebind(v)

    def bind_functional_state(self, params=None, buffers=None):
        """Temporarily swap in traced arrays (used by to_static); returns restore fn."""
        saved = []
        own_p = dict(self.named_parameters())
        own_b = dict(self.named_buffers())
        for k, v in (params or {}).items():
            saved.append((own_p[k], own_p[k]._value, own_p[k]._node, own_p[k]._out_index))
            own_p[k]._value = v
            own_p[k]._node = None
        for k, v in (buffers or {}).items():
            saved.append((own_b[k], own_b[k]._value, own_b[k]._node, own_b[k]._out_index))
            own_b[k]._value = v
            own_b[k]._node = None

        def restore():
            for t, val, node, idx in saved:
                t._value, t._node, t._out_index = val, node, idx

        return restore
