"""Common layers: Linear, Embedding, Dropout, ... (ref: python/paddle/nn/layer/common.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .layers import Layer
from .. import functional as F
from ..initializer import XavierNormal, Normal, Constant
from ...framework.param_attr import ParamAttr
from ...tensor.tensor import Tensor, apply_op


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Linear(Layer):
    """y = xW + b (ref common.py Linear; weight [in, out] like the reference)."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.in_features, self.out_features = in_features, out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr, default_initializer=XavierNormal()
        )
        if bias_attr is not False:
            self.bias = self.create_parameter([out_features], attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Embedding(Layer):
    """Ref common.py Embedding; weight [num_embeddings, embedding_dim]."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None, sparse=False,
                 weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=Normal(0.0, 1.0) if weight_attr is None else None,
        )
        if padding_idx is not None:
            self.weight.set_value(self.weight._value.at[padding_idx].set(0.0))

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p, self.axis, self.mode = p, axis, mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, training=self.training, mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training, data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training, data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        from ...tensor.manipulation import flatten

        return flatten(x, self.start_axis, self.stop_axis)


class Pad1D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL", name=None):
        super().__init__()
        self.padding, self.mode, self.value, self.data_format = padding, mode, value, data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW", name=None):
        super().__init__()
        self.padding, self.mode, self.value, self.data_format = padding, mode, value, data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad3D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW", name=None):
        super().__init__()
        self.padding, self.mode, self.value, self.data_format = padding, mode, value, data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class ZeroPad2D(Layer):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__()
        self.padding, self.data_format = padding, data_format

    def forward(self, x):
        return F.zeropad2d(x, self.padding, self.data_format)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest", align_corners=False,
                 align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners, self.align_mode = mode, align_corners, align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode, self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor, self.data_format = upscale_factor, data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor, self.data_format = downscale_factor, data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter([out_features, in1_features, in2_features], attr=weight_attr)
        self.bias = self.create_parameter([out_features], attr=bias_attr, is_bias=True) if bias_attr is not False else None

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, self.output_sizes, *self.args)


class ChannelShuffle(Layer):
    """Ref nn/layer/vision.py ChannelShuffle."""

    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups
        self.data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class Softmax2D(Layer):
    """Ref Softmax2D: softmax over the channel axis of NCHW."""

    def forward(self, x):
        return F.softmax(x, axis=-3)


class SpectralNorm(Layer):
    """Ref nn/layer/norm.py SpectralNorm: power-iteration estimate of the
    largest singular value; forward returns weight / sigma."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.eps = eps
        import numpy as _np

        h = weight_shape[dim]
        w = int(_np.prod(weight_shape)) // h
        rng = _np.random.RandomState(0)
        # u/v are BUFFERS updated every forward (the reference updates them in
        # place so power_iters=1 converges over training steps, like BN stats)
        self.register_buffer("weight_u",
                             Tensor(jnp.asarray(rng.normal(size=h), jnp.float32)))
        self.register_buffer("weight_v",
                             Tensor(jnp.asarray(rng.normal(size=w), jnp.float32)))

    def forward(self, weight):
        dim = self.dim
        iters = self.power_iters
        eps = self.eps

        def _power(wt, u, v):
            perm = (dim,) + tuple(i for i in range(wt.ndim) if i != dim)
            mat = jnp.transpose(wt, perm).reshape(wt.shape[dim], -1)

            def it(c, _):
                uu, vv = c
                vv = mat.T @ uu
                vv = vv / (jnp.linalg.norm(vv) + eps)
                uu = mat @ vv
                uu = uu / (jnp.linalg.norm(uu) + eps)
                return (uu, vv), None

            (u2, v2), _ = jax.lax.scan(it, (u, v), None, length=iters)
            return mat, u2, v2

        def _f(wt, u, v):
            mat, u2, v2 = _power(jax.lax.stop_gradient(wt), u, v)
            # persist the iterates (traced contexts capture this via the
            # functional-buffer machinery, same as BN running stats)
            self.weight_u.set_value(u2)
            self.weight_v.set_value(v2)
            perm = (dim,) + tuple(i for i in range(wt.ndim) if i != dim)
            sigma = u2 @ (jnp.transpose(wt, perm).reshape(wt.shape[dim], -1) @ v2)
            return wt / sigma

        return apply_op(_f, (weight, self.weight_u, self.weight_v),
                        name="spectral_norm")
