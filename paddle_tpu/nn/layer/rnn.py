"""Recurrent layers (ref: python/paddle/nn/layer/rnn.py).

The per-timestep loop is `lax.scan` — compiled once, contrast with the reference's
cudnn RNN kernels (phi/kernels/gpu/rnn_kernel.cu).  Cells expose the same
(inputs, states) -> (outputs, new_states) contract as the reference RNNCellBase.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .layers import Layer
from ..initializer import Uniform
from ...tensor.tensor import Tensor, apply_op
from ...tensor import creation


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype="float32", init_value=0.0, batch_dim_idx=0):
        B = batch_ref.shape[batch_dim_idx]
        shape = shape or self.state_shape
        if isinstance(shape, (list, tuple)) and shape and isinstance(shape[0], (list, tuple)):
            return tuple(creation.full([B, *s], init_value, dtype) for s in shape)
        return creation.full([B, *shape], init_value, dtype)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh", weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        std = 1.0 / hidden_size**0.5
        self.hidden_size = hidden_size
        self.input_size = input_size
        self.activation = activation
        self.weight_ih = self.create_parameter([hidden_size, input_size], attr=weight_ih_attr, default_initializer=Uniform(-std, std))
        self.weight_hh = self.create_parameter([hidden_size, hidden_size], attr=weight_hh_attr, default_initializer=Uniform(-std, std))
        self.bias_ih = self.create_parameter([hidden_size], attr=bias_ih_attr, is_bias=True, default_initializer=Uniform(-std, std))
        self.bias_hh = self.create_parameter([hidden_size], attr=bias_hh_attr, is_bias=True, default_initializer=Uniform(-std, std))

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def _f(x, h, wi, wh, bi, bh):
            return act(x @ wi.T + bi + h @ wh.T + bh)

        h = apply_op(_f, (inputs, states, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh), name="rnn_cell")
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        std = 1.0 / hidden_size**0.5
        self.hidden_size = hidden_size
        self.input_size = input_size
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size], attr=weight_ih_attr, default_initializer=Uniform(-std, std))
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size], attr=weight_hh_attr, default_initializer=Uniform(-std, std))
        self.bias_ih = self.create_parameter([4 * hidden_size], attr=bias_ih_attr, is_bias=True, default_initializer=Uniform(-std, std))
        self.bias_hh = self.create_parameter([4 * hidden_size], attr=bias_hh_attr, is_bias=True, default_initializer=Uniform(-std, std))

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs, self.state_shape)
        h, c = states

        def _f(x, hp, cp, wi, wh, bi, bh):
            gates = x @ wi.T + bi + hp @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            cn = f * cp + i * g
            hn = o * jnp.tanh(cn)
            return hn, cn

        hn, cn = apply_op(_f, (inputs, h, c, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh), name="lstm_cell")
        return hn, (hn, cn)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        std = 1.0 / hidden_size**0.5
        self.hidden_size = hidden_size
        self.input_size = input_size
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size], attr=weight_ih_attr, default_initializer=Uniform(-std, std))
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size], attr=weight_hh_attr, default_initializer=Uniform(-std, std))
        self.bias_ih = self.create_parameter([3 * hidden_size], attr=bias_ih_attr, is_bias=True, default_initializer=Uniform(-std, std))
        self.bias_hh = self.create_parameter([3 * hidden_size], attr=bias_hh_attr, is_bias=True, default_initializer=Uniform(-std, std))

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def _f(x, hp, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = hp @ wh.T + bh
            ir, iz, in_ = jnp.split(gi, 3, axis=-1)
            hr, hz, hn_ = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            n = jnp.tanh(in_ + r * hn_)
            return (1 - z) * n + z * hp

        h = apply_op(_f, (inputs, states, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh), name="gru_cell")
        return h, h


def _scan_rnn(mode, x, h0, c0, params, time_major):
    """One direction, one layer, compiled with lax.scan.  x: [B,T,I] (or [T,B,I])."""
    wi, wh, bi, bh = params
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)  # -> [T,B,I]

    if mode == "LSTM":
        def step(carry, xt):
            hp, cp = carry
            gates = xt @ wi.T + bi + hp @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            cn = f * cp + i * g
            hn = o * jnp.tanh(cn)
            return (hn, cn), hn

        (hT, cT), ys = jax.lax.scan(step, (h0, c0), x)
        out_states = (hT, cT)
    elif mode == "GRU":
        def step(hp, xt):
            gi = xt @ wi.T + bi
            gh = hp @ wh.T + bh
            ir, iz, in_ = jnp.split(gi, 3, axis=-1)
            hr, hz, hn_ = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            n = jnp.tanh(in_ + r * hn_)
            hn = (1 - z) * n + z * hp
            return hn, hn

        hT, ys = jax.lax.scan(step, h0, x)
        out_states = (hT,)
    else:
        def step(hp, xt):
            hn = jnp.tanh(xt @ wi.T + bi + hp @ wh.T + bh)
            return hn, hn

        hT, ys = jax.lax.scan(step, h0, x)
        out_states = (hT,)
    if not time_major:
        ys = jnp.swapaxes(ys, 0, 1)
    return ys, out_states


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        num_dirs = 2 if self.bidirect else 1
        self.num_directions = num_dirs
        gate_mult = {"LSTM": 4, "GRU": 3, "RNN_TANH": 1, "RNN_RELU": 1}[mode]
        std = 1.0 / hidden_size**0.5
        self._all_weights = []
        for layer in range(num_layers):
            for d in range(num_dirs):
                in_sz = input_size if layer == 0 else hidden_size * num_dirs
                suffix = f"_reverse" if d else ""
                wi = self.create_parameter([gate_mult * hidden_size, in_sz], default_initializer=Uniform(-std, std))
                wh = self.create_parameter([gate_mult * hidden_size, hidden_size], default_initializer=Uniform(-std, std))
                bi = self.create_parameter([gate_mult * hidden_size], is_bias=True, default_initializer=Uniform(-std, std))
                bh = self.create_parameter([gate_mult * hidden_size], is_bias=True, default_initializer=Uniform(-std, std))
                self.add_parameter(f"weight_ih_l{layer}{suffix}", wi)
                self.add_parameter(f"weight_hh_l{layer}{suffix}", wh)
                self.add_parameter(f"bias_ih_l{layer}{suffix}", bi)
                self.add_parameter(f"bias_hh_l{layer}{suffix}", bh)
                self._all_weights.append((wi, wh, bi, bh))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        is_lstm = self.mode == "LSTM"
        B = inputs.shape[1] if self.time_major else inputs.shape[0]
        n_state = self.num_layers * self.num_directions
        if initial_states is None:
            z = creation.zeros([n_state, B, self.hidden_size], "float32")
            initial_states = (z, creation.zeros([n_state, B, self.hidden_size], "float32")) if is_lstm else z

        flat_params = [p for tup in self._all_weights for p in tup]

        def _f(x, h0s, c0s, *params):
            outs = x
            hTs, cTs = [], []
            idx = 0
            mode = self.mode if self.mode in ("LSTM", "GRU") else "RNN"
            for layer in range(self.num_layers):
                dir_outs = []
                for d in range(self.num_directions):
                    p = params[4 * idx: 4 * idx + 4]
                    h0 = h0s[idx]
                    c0 = c0s[idx] if is_lstm else None
                    xin = jnp.flip(outs, axis=0 if self.time_major else 1) if d else outs
                    ys, st = _scan_rnn(mode, xin, h0, c0, p, self.time_major)
                    if d:
                        ys = jnp.flip(ys, axis=0 if self.time_major else 1)
                    dir_outs.append(ys)
                    hTs.append(st[0])
                    if is_lstm:
                        cTs.append(st[1])
                    idx += 1
                outs = jnp.concatenate(dir_outs, axis=-1) if self.num_directions > 1 else dir_outs[0]
            hT = jnp.stack(hTs)
            if is_lstm:
                return outs, hT, jnp.stack(cTs)
            return outs, hT

        if is_lstm:
            h0, c0 = initial_states
            res = apply_op(lambda x, h, c, *ps: _f(x, h, c, *ps), (inputs, h0, c0, *flat_params), name=self.mode)
            out, hT, cT = res
            return out, (hT, cT)
        res = apply_op(lambda x, h, *ps: _f(x, h, None, *ps), (inputs, initial_states, *flat_params), name=self.mode)
        out, hT = res
        return out, hT


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh", **kwargs):
        mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(mode, input_size, hidden_size, num_layers, direction, time_major, dropout)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers, direction, time_major, dropout)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction, time_major, dropout)


class RNN(Layer):
    """Wraps a cell into a scan over time (ref rnn.py RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        # eager python loop (cell may be arbitrary); jit users wrap the whole step
        axis = 0 if self.time_major else 1
        T = inputs.shape[axis]
        steps = range(T - 1, -1, -1) if self.is_reverse else range(T)
        states = initial_states
        outs = []
        from ...tensor import manipulation as M

        for t in steps:
            xt = inputs[(t,) if self.time_major else (slice(None), t)]
            out, states = self.cell(xt, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        outputs = M.stack(outs, axis=axis)
        return outputs, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor import manipulation as M

        fw, sf = self.rnn_fw(inputs, None if initial_states is None else initial_states[0])
        bw, sb = self.rnn_bw(inputs, None if initial_states is None else initial_states[1])
        return M.concat([fw, bw], axis=-1), (sf, sb)


class BeamSearchDecoder:
    """Ref nn/layer/rnn.py BeamSearchDecoder: beam search over an RNN cell.

    The decode loop is host-driven (`dynamic_decode`); each step is jnp math
    through the normal op layer, so it jits under to_static if wrapped.
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None, vocab_size=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn
        if embedding_fn is None and vocab_size is None:
            raise ValueError(
                "BeamSearchDecoder needs embedding_fn (or vocab_size for the "
                "one-hot fallback) — token ids are not valid cell inputs")
        self.vocab_size = vocab_size

    # -- helpers operating on raw jnp values
    def _merge(self, v):      # [B, W, ...] -> [B*W, ...]
        return v.reshape((-1,) + tuple(v.shape[2:]))

    def _split(self, v, B):   # [B*W, ...] -> [B, W, ...]
        return v.reshape((B, self.beam_size) + tuple(v.shape[1:]))

    def initialize(self, initial_cell_states):
        """Tile cell states across beams; first input is start_token."""
        states = jax.tree.map(
            lambda s: jnp.repeat(s[:, None], self.beam_size, 1),
            initial_cell_states)
        B = jax.tree.leaves(initial_cell_states)[0].shape[0]
        ids = jnp.full((B, self.beam_size), self.start_token, jnp.int32)
        # only beam 0 is live initially (others -inf so beams diversify)
        log_probs = jnp.tile(
            jnp.asarray([0.0] + [-1e9] * (self.beam_size - 1), jnp.float32)[None],
            (B, 1))
        finished = jnp.zeros((B, self.beam_size), bool)
        return ids, (states, log_probs, finished)

    def step(self, inputs, beam_state):
        from ...tensor.tensor import Tensor as _T

        states, log_probs, finished = beam_state
        B, W = inputs.shape
        emb = (self.embedding_fn(_T(inputs.reshape(-1)))._value
               if self.embedding_fn is not None
               else jax.nn.one_hot(inputs.reshape(-1), self.vocab_size,
                                   dtype=jnp.float32))
        flat_states = jax.tree.map(self._merge, states)
        out, new_states = self.cell(_T(emb), jax.tree.map(_T, flat_states))
        logits = self.output_fn(out)._value if self.output_fn is not None else out._value
        V = logits.shape[-1]
        step_lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        step_lp = self._split(step_lp, B)                     # [B, W, V]
        # finished beams only extend with end_token at zero cost
        mask = jnp.full((V,), -1e9).at[self.end_token].set(0.0)
        step_lp = jnp.where(finished[..., None], mask[None, None], step_lp)
        total = log_probs[..., None] + step_lp                # [B, W, V]
        flat = total.reshape(B, W * V)
        top_lp, top_idx = jax.lax.top_k(flat, W)
        parent = (top_idx // V).astype(jnp.int32)             # [B, W]
        token = (top_idx % V).astype(jnp.int32)
        new_states = jax.tree.map(
            lambda s: jnp.take_along_axis(
                self._split(s, B), parent.reshape(
                    (B, W) + (1,) * (s.ndim - 1)), 1),
            jax.tree.map(lambda t: t._value, new_states))
        finished = jnp.take_along_axis(finished, parent, 1) | (token == self.end_token)
        return token, parent, (new_states, top_lp, finished)


def dynamic_decode(decoder, inits=None, max_step_num=100, output_time_major=False,
                   impute_finished=False, is_test=False, return_length=False,
                   **kwargs):
    """Ref nn/layer/rnn.py dynamic_decode: run decoder.initialize/step until
    every beam finishes or max_step_num.  Returns (ids [B, T, W], final_states)
    (+ lengths when return_length)."""
    from ...tensor.tensor import Tensor as _T

    ids0, state = decoder.initialize(jax.tree.map(
        lambda t: t._value if isinstance(t, _T) else t, inits))
    tokens, parents = [], []
    inputs = ids0
    for _ in range(int(max_step_num)):
        token, parent, state = decoder.step(inputs, state)
        tokens.append(token)
        parents.append(parent)
        inputs = token
        if bool(state[2].all()):
            break
    import numpy as _np

    idv = jnp.stack(tokens)                                  # [T, B, W]
    pav = jnp.stack(parents)
    from ..functional.common import gather_tree as _gt

    beams = _gt(_T(idv), _T(pav))._value                     # [T, B, W]
    out = beams if output_time_major else jnp.transpose(beams, (1, 0, 2))
    T = beams.shape[0]
    lengths = jnp.minimum(jnp.argmax(
        jnp.concatenate([(beams == decoder.end_token),
                         jnp.ones((1,) + beams.shape[1:], bool)], 0), 0) + 1, T)
    if return_length:
        return _T(out), state, _T(lengths.astype(jnp.int32))
    return _T(out), state
