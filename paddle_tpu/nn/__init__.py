"""paddle.nn parity surface (ref: python/paddle/nn/__init__.py)."""
from .layer.layers import Layer  # noqa: F401
from .layer.container import Sequential, LayerList, ParameterList, LayerDict  # noqa: F401
from .layer.common import (  # noqa: F401
    Identity, Linear, Embedding, Dropout, Dropout2D, Dropout3D, AlphaDropout,
    Flatten, Pad1D, Pad2D, Pad3D, ZeroPad2D, Upsample, UpsamplingBilinear2D,
    UpsamplingNearest2D, PixelShuffle, PixelUnshuffle, Bilinear, CosineSimilarity,
    Unfold, Fold, ChannelShuffle, PairwiseDistance, Softmax2D, SpectralNorm,
)
from .layer.conv import (  # noqa: F401
    Conv1D, Conv2D, Conv3D, Conv1DTranspose, Conv2DTranspose, Conv3DTranspose,
)
from .layer.norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, SyncBatchNorm,
    LayerNorm, RMSNorm, GroupNorm, InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
    LocalResponseNorm,
)
from .layer.pooling import (  # noqa: F401
    MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D, AvgPool2D, AvgPool3D,
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveMaxPool1D, AdaptiveMaxPool2D,
    AdaptiveAvgPool3D, AdaptiveMaxPool3D, MaxUnPool1D, MaxUnPool2D, MaxUnPool3D,
)
from .layer.activation import (  # noqa: F401
    ReLU, ReLU6, GELU, Sigmoid, Tanh, Softmax, LogSoftmax, LogSigmoid, Softplus,
    Softsign, Softshrink, Hardshrink, Hardsigmoid, Hardswish, Hardtanh, LeakyReLU,
    ELU, SELU, CELU, Silu, Swish, Mish, Tanhshrink, ThresholdedReLU, Maxout, GLU,
    RReLU, PReLU,
)
from .layer.loss import (  # noqa: F401
    CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss, BCEWithLogitsLoss,
    KLDivLoss, SmoothL1Loss, MarginRankingLoss, CTCLoss, HingeEmbeddingLoss,
    CosineEmbeddingLoss, TripletMarginLoss, MultiLabelSoftMarginLoss,
    TripletMarginWithDistanceLoss, HSigmoidLoss,
)
from .layer.transformer import (  # noqa: F401
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer,
)
from .layer.rnn import (  # noqa: F401
    RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN, BiRNN, SimpleRNN, LSTM, GRU,
    BeamSearchDecoder, dynamic_decode,
)
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from ..framework.param_attr import ParamAttr  # noqa: F401


class ClipGradByGlobalNorm:
    """Ref: fluid/clip.py GradientClipByGlobalNorm — consumed by Optimizer."""

    def __init__(self, clip_norm=1.0, group_name="default", auto_skip_clip=False):
        self.clip_norm = clip_norm

    def __repr__(self):
        return f"ClipGradByGlobalNorm(clip_norm={self.clip_norm})"


class ClipGradByNorm:
    def __init__(self, clip_norm=1.0):
        self.clip_norm = clip_norm


class ClipGradByValue:
    def __init__(self, max, min=None):
        self.max = max
        self.min = min if min is not None else -max


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    """paddle.nn.utils.clip_grad_norm_ (delegates to the nn.utils impl)."""
    from .utils import clip_grad_norm_ as _impl

    return _impl(parameters, max_norm, norm_type, error_if_nonfinite)


from . import utils  # noqa: F401,E402
from . import quant  # noqa: F401,E402
