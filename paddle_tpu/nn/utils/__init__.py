"""paddle.nn.utils — gradient clipping, param (de)flattening, and the
weight/spectral norm reparametrization hooks.

Ref: python/paddle/nn/utils/{clip_grad_norm_.py, transform_parameters.py,
weight_norm_hook.py:158, spectral_norm_hook.py:130}.

TPU-native: the reparametrized weight is recomputed from (g, v) inside the
forward pre-hook, so it is part of the traced graph — gradients flow to g/v
through jax.vjp exactly like any other op; no custom kernels.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...tensor.tensor import Tensor, Parameter, apply_op

__all__ = ["clip_grad_norm_", "parameters_to_vector", "vector_to_parameters",
           "weight_norm", "remove_weight_norm", "spectral_norm"]


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    """Scale grads in place so the global norm is <= max_norm."""
    params = [p for p in parameters if p._grad is not None]
    if not params:
        return None
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(p._grad.astype(jnp.float32))) for p in params]))
    else:
        total = sum(jnp.sum(jnp.abs(p._grad.astype(jnp.float32)) ** norm_type)
                    for p in params) ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError(
            f"the total norm of gradients is non-finite ({float(total)}); set "
            f"error_if_nonfinite=False to clip anyway")
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in params:
        p._grad = (p._grad.astype(jnp.float32) * scale).astype(p._grad.dtype)
    return Tensor(total)


def parameters_to_vector(parameters, name=None):
    return Tensor(jnp.concatenate([p._value.reshape(-1) for p in parameters]))


def vector_to_parameters(vec, parameters, name=None):
    import numpy as np

    offset = 0
    for p in parameters:
        n = int(np.prod(p._value.shape))
        p.set_value(vec._value[offset:offset + n].reshape(p._value.shape))
        offset += n


# --------------------------------------------------------------- weight norm

def _norm_except(v, dim):
    """L2 norm over every axis except `dim` (dim=None/-1: whole-tensor norm)."""
    if dim is None:
        return jnp.sqrt(jnp.sum(jnp.square(v.astype(jnp.float32))))
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(v.astype(jnp.float32)), axis=axes, keepdims=True))


class _WeightNormHook:
    def __init__(self, name, dim):
        self.name = name
        self.dim = dim

    def compute(self, layer):
        g = getattr(layer, self.name + "_g")
        v = getattr(layer, self.name + "_v")

        def _f(gv, vv):
            n = _norm_except(vv, self.dim)
            return (vv.astype(jnp.float32) / (n + 1e-12) * gv.astype(jnp.float32)).astype(vv.dtype)

        return apply_op(_f, (g, v), name="weight_norm")

    def __call__(self, layer, inputs):
        object.__setattr__(layer, self.name, self.compute(layer))
        return None


def weight_norm(layer, name="weight", dim=0):
    """Reparametrize layer.<name> as g * v / ||v|| (ref weight_norm_hook.py:158).

    Replaces the parameter with <name>_g (the per-slice norms along `dim`)
    and <name>_v (the direction); the effective weight is rebuilt every
    forward inside the trace."""
    if hasattr(layer, "_weight_norm_hooks") and name in layer._weight_norm_hooks:
        raise RuntimeError(f"weight_norm already applied to {name!r}")
    w = layer._parameters.get(name)
    if w is None:
        raise ValueError(f"layer has no parameter {name!r}")
    if dim is not None:
        dim = dim % w._value.ndim  # negative dims: paddle allows -1 for last
    hook = _WeightNormHook(name, dim)
    g0 = _norm_except(w._value, dim)
    layer.add_parameter(name + "_g", Parameter(g0.astype(w._value.dtype)))
    layer.add_parameter(name + "_v", Parameter(w._value))
    del layer._parameters[name]
    handle = layer.register_forward_pre_hook(hook)
    if not hasattr(layer, "_weight_norm_hooks"):
        object.__setattr__(layer, "_weight_norm_hooks", {})
    layer._weight_norm_hooks[name] = (hook, handle)
    object.__setattr__(layer, name, hook.compute(layer))
    return layer


def remove_weight_norm(layer, name="weight"):
    """Fold g*v/||v|| back into a plain parameter (ref weight_norm_hook.py:208)."""
    hooks = getattr(layer, "_weight_norm_hooks", {})
    if name not in hooks:
        raise ValueError(f"weight_norm was not applied to {name!r}")
    hook, handle = hooks.pop(name)
    w = hook.compute(layer)
    handle.remove()
    del layer._parameters[name + "_g"]
    del layer._parameters[name + "_v"]
    if hasattr(layer, name):
        try:
            object.__delattr__(layer, name)
        except AttributeError:
            pass
    layer.add_parameter(name, Parameter(w._value))
    return layer


# -------------------------------------------------------------- spectral norm

class _SpectralNormHook:
    def __init__(self, name, n_power_iterations, eps, dim):
        self.name = name
        self.n_power_iterations = n_power_iterations
        self.eps = eps
        self.dim = dim

    def _mat(self, w):
        if self.dim != 0:
            perm = [self.dim] + [i for i in range(w.ndim) if i != self.dim]
            w = jnp.transpose(w, perm)
        return w.reshape(w.shape[0], -1)

    def compute(self, layer, update_u):
        w = getattr(layer, self.name + "_orig")
        u = getattr(layer, self.name + "_u")

        wv = w._value
        mat = self._mat(wv.astype(jnp.float32))
        uv = u._value
        if update_u:
            for _ in range(self.n_power_iterations):
                v = mat.T @ uv
                v = v / (jnp.linalg.norm(v) + self.eps)
                uv = mat @ v
                uv = uv / (jnp.linalg.norm(uv) + self.eps)
            u.set_value(uv)
        v = mat.T @ uv
        v = v / (jnp.linalg.norm(v) + self.eps)

        def _f(wval):
            m = self._mat(wval.astype(jnp.float32))
            sigma = uv @ (m @ v)
            return (wval.astype(jnp.float32) / sigma).astype(wval.dtype)

        return apply_op(_f, (w,), name="spectral_norm")

    def __call__(self, layer, inputs):
        object.__setattr__(layer, self.name, self.compute(layer, layer.training))
        return None


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12, dim=None):
    """Normalize layer.<name> by its largest singular value, estimated with
    power iteration on a persistent `u` buffer (ref spectral_norm_hook.py:130)."""
    w = layer._parameters.get(name)
    if w is None:
        raise ValueError(f"layer has no parameter {name!r}")
    if dim is None:
        # Linear keeps out_features on axis 1, and transpose convs keep them
        # on axis 1 of their [in, out/groups, *k] weights (ref
        # spectral_norm_hook.py:158); plain convs use axis 0
        dim = 1 if type(layer).__name__ in (
            "Linear", "Conv1DTranspose", "Conv2DTranspose", "Conv3DTranspose",
        ) else 0
    dim = dim % w._value.ndim
    hook = _SpectralNormHook(name, n_power_iterations, eps, dim)
    import numpy as np

    rng = np.random.RandomState(0)
    h = w._value.shape[dim]
    u0 = rng.randn(h).astype(np.float32)
    u0 /= (np.linalg.norm(u0) + eps)
    layer.add_parameter(name + "_orig", Parameter(w._value))
    layer.register_buffer(name + "_u", Tensor(jnp.asarray(u0)))
    del layer._parameters[name]
    layer.register_forward_pre_hook(hook)
    object.__setattr__(layer, name, hook.compute(layer, update_u=False))
    return layer
