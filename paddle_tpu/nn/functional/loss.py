"""Loss functionals (ref: python/paddle/nn/functional/loss.py, phi CrossEntropyKernel).

cross_entropy keeps the reference's semantics: int or soft labels, ignore_index,
weight, reduction, use_softmax toggle (softmax_with_cross_entropy fusion is XLA's job).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor.tensor import Tensor, apply_op, _unwrap


import functools


def _ce_lse_picked(x, safe, axis):
    """f32 logsumexp + picked-logit from possibly-bf16 logits.  The f32
    upcast stays INSIDE producer-fused elementwise/reduction kernels — the
    [N, V] f32 logits array is never materialized in HBM (for a 32k-vocab
    LLaMA step that array is 2.1 GB per pass)."""
    xf = x.astype(jnp.float32)
    m = jnp.max(xf, axis=axis, keepdims=True)
    lse = m + jnp.log(jnp.sum(jnp.exp(xf - m), axis=axis, keepdims=True))
    picked = jnp.take_along_axis(x, jnp.expand_dims(safe, axis),
                                 axis=axis).astype(jnp.float32)
    return lse, picked


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _fused_softmax_ce(x, idx, axis, ignore_index):
    """Per-example hard-label CE with a hand-written backward: the bwd
    emits d_logits = (softmax - onehot) * d_per directly in the logits
    dtype, so neither pass materializes f32 [N, V] (ref phi
    CrossEntropyWithSoftmax fused kernel — same motivation, MXU edition)."""
    safe = jnp.where(idx == ignore_index, 0, idx)
    lse, picked = _ce_lse_picked(x, safe, axis)
    valid = (idx != ignore_index)
    return jnp.squeeze(lse, axis) - jnp.squeeze(picked, axis), valid


def _fused_softmax_ce_fwd(x, idx, axis, ignore_index):
    safe = jnp.where(idx == ignore_index, 0, idx)
    lse, picked = _ce_lse_picked(x, safe, axis)
    valid = (idx != ignore_index)
    per = jnp.squeeze(lse, axis) - jnp.squeeze(picked, axis)
    return (per, valid), (x, jnp.squeeze(lse, axis), safe, valid)


def _fused_softmax_ce_bwd(axis, ignore_index, res, cts):
    x, lse, safe, valid = res
    d_per = cts[0] * valid.astype(cts[0].dtype)
    xf = x.astype(jnp.float32)
    probs = jnp.exp(xf - jnp.expand_dims(lse, axis))
    nclass = x.shape[axis]
    onehot = jax.nn.one_hot(safe, nclass, axis=axis, dtype=jnp.float32)
    dx = (probs - onehot) * jnp.expand_dims(d_per, axis)
    return dx.astype(x.dtype), None


_fused_softmax_ce.defvjp(_fused_softmax_ce_fwd, _fused_softmax_ce_bwd)


def _reduce(v, reduction, weight_sum=None):
    if reduction == "mean":
        if weight_sum is not None:
            return jnp.sum(v) / jnp.maximum(weight_sum, 1e-12)
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0, name=None):
    def _f(logits, lbl, w):
        if (use_softmax and not soft_label and w is None
                and label_smoothing == 0 and jnp.issubdtype(
                    jnp.asarray(lbl).dtype, jnp.integer)):
            # hard-label fast path: fused softmax-CE (f32 math without
            # materializing f32 logits — see _fused_softmax_ce)
            idx = lbl.astype(jnp.int32)
            if idx.ndim == logits.ndim:
                idx = jnp.squeeze(idx, axis=axis)
            per, valid = _fused_softmax_ce(logits, idx, axis, ignore_index)
            per = per * valid.astype(per.dtype)
            if reduction == "mean":
                out = jnp.sum(per) / jnp.maximum(
                    jnp.sum(valid.astype(per.dtype)), 1.0)
            else:
                out = _reduce(per, reduction)
            # internal math is f32; the OUTPUT keeps the reference dtype
            # contract (loss dtype == logits dtype, as log_softmax gave)
            return out.astype(logits.dtype)
        if use_softmax:
            logp = jax.nn.log_softmax(logits, axis=axis)
        else:
            logp = jnp.log(jnp.clip(logits, 1e-15, None))
        nclass = logits.shape[axis]
        if soft_label:
            soft = lbl
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / nclass
            per = -jnp.sum(soft * logp, axis=axis)
            if reduction == "none":
                return per
            return _reduce(per, reduction)
        idx = lbl.astype(jnp.int32)
        if idx.ndim == logp.ndim:  # [N, ..., 1] form
            idx = jnp.squeeze(idx, axis=axis)
        safe_idx = jnp.where(idx == ignore_index, 0, idx)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe_idx, axis), axis=axis)
        per = -jnp.squeeze(picked, axis=axis)
        if label_smoothing > 0:
            smooth = -jnp.mean(logp, axis=axis)
            per = (1 - label_smoothing) * per + label_smoothing * smooth
        valid = (idx != ignore_index).astype(per.dtype)
        per = per * valid
        if w is not None:
            wt = jnp.take(w, safe_idx, axis=0) * valid
            per = per * jnp.take(w, safe_idx, axis=0)
            if reduction == "mean":
                return jnp.sum(per * valid) / jnp.maximum(jnp.sum(wt), 1e-12)
        if reduction == "mean":
            return jnp.sum(per) / jnp.maximum(jnp.sum(valid), 1.0)
        return _reduce(per, reduction)

    return apply_op(_f, (input, label, weight), name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index,
                         reduction="none", axis=axis)
    from .activation import softmax as _softmax

    loss = loss.unsqueeze(axis)
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    def _f(logp, lbl, w):
        idx = lbl.astype(jnp.int32)
        safe = jnp.where(idx == ignore_index, 0, idx)
        per = -jnp.take_along_axis(logp, safe[:, None] if logp.ndim == 2 else jnp.expand_dims(safe, 1), axis=1)
        per = jnp.squeeze(per, axis=1)
        valid = (idx != ignore_index).astype(per.dtype)
        if w is not None:
            wt = jnp.take(w, safe, axis=0)
            per = per * wt
            if reduction == "mean":
                return jnp.sum(per * valid) / jnp.maximum(jnp.sum(wt * valid), 1e-12)
        per = per * valid
        if reduction == "mean":
            return jnp.sum(per) / jnp.maximum(jnp.sum(valid), 1.0)
        return _reduce(per, reduction)

    return apply_op(_f, (input, label, weight), name="nll_loss")


def mse_loss(input, label, reduction="mean", name=None):
    return apply_op(lambda a, b: _reduce(jnp.square(a - b), reduction), (input, label), name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return apply_op(lambda a, b: _reduce(jnp.abs(a - b), reduction), (input, label), name="l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def _f(a, b):
        d = jnp.abs(a - b)
        v = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(v, reduction)

    return apply_op(_f, (input, label), name="smooth_l1_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def _f(p, y, w):
        p = jnp.clip(p, 1e-12, 1 - 1e-12)
        per = -(y * jnp.log(p) + (1 - y) * jnp.log1p(-p))
        if w is not None:
            per = per * w
        return _reduce(per, reduction)

    return apply_op(_f, (input, label, weight), name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def _f(z, y, w, pw):
        # numerically-stable BCE-with-logits
        neg_abs = -jnp.abs(z)
        if pw is not None:
            log_w = (pw - 1) * y + 1
            per = (1 - y) * z + log_w * (jnp.log1p(jnp.exp(neg_abs)) + jnp.maximum(-z, 0.0))
        else:
            per = jnp.maximum(z, 0.0) - z * y + jnp.log1p(jnp.exp(neg_abs))
        if w is not None:
            per = per * w
        return _reduce(per, reduction)

    return apply_op(_f, (logit, label, weight, pos_weight), name="bce_with_logits")


def kl_div(input, label, reduction="mean", name=None):
    def _f(logp, tgt):
        per = tgt * (jnp.log(jnp.clip(tgt, 1e-12, None)) - logp)
        if reduction == "batchmean":
            return jnp.sum(per) / logp.shape[0]
        return _reduce(per, reduction)

    return apply_op(_f, (input, label), name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return apply_op(
        lambda a, b, y: _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction),
        (input, other, label),
        name="margin_ranking_loss",
    )


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean"):
    return apply_op(
        lambda x, y: _reduce(jnp.where(y == 1, x, jnp.maximum(0.0, margin - x)), reduction),
        (input, label),
        name="hinge_embedding_loss",
    )


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean"):
    def _f(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.maximum(jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        per = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(per, reduction)

    return apply_op(_f, (input1, input2, label), name="cosine_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6,
                        swap=False, reduction="mean"):
    def _f(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return apply_op(_f, (input, positive, negative), name="triplet_margin_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction="mean", norm_by_times=False):
    """CTC via the classic dynamic program in log space (lax.scan over time).

    Ref: phi WarpctcKernel — here a pure-XLA scan, no warpctc dependency.
    log_probs: [T, N, C] (paddle layout); labels: [N, L] padded.
    """

    def _f(lp, lbl):
        T, N, C = lp.shape
        lbl = lbl.astype(jnp.int32)
        L = lbl.shape[1]
        S = 2 * L + 1
        # extended label sequence: blank l1 blank l2 ... blank
        ext = jnp.full((N, S), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lbl)
        ilen = jnp.asarray(_unwrap(input_lengths)).astype(jnp.int32)
        llen = jnp.asarray(_unwrap(label_lengths)).astype(jnp.int32)

        neg_inf = -1e30
        alpha0 = jnp.full((N, S), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, jnp.arange(N), ext[:, 0]])
        alpha0 = alpha0.at[:, 1].set(jnp.where(llen > 0, lp[0, jnp.arange(N), ext[:, 1]], neg_inf))

        same = jnp.concatenate([jnp.full((N, 2), True), ext[:, 2:] == ext[:, :-2]], axis=1)

        def logaddexp(a, b):
            m = jnp.maximum(a, b)
            return m + jnp.log1p(jnp.exp(-jnp.abs(a - b)))

        def step(carry, t):
            alpha = carry
            shift1 = jnp.concatenate([jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1)
            shift2 = jnp.concatenate([jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1)
            shift2 = jnp.where(same, neg_inf, shift2)
            a = logaddexp(logaddexp(alpha, shift1), shift2)
            emit = lp[t, jnp.arange(N)[:, None], ext]
            new = a + emit
            new = jnp.where(t < ilen[:, None], new, alpha)
            return new, None

        alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        endS = 2 * llen
        last1 = alpha[jnp.arange(N), endS]
        last2 = jnp.where(llen > 0, alpha[jnp.arange(N), jnp.maximum(endS - 1, 0)], neg_inf)
        ll = logaddexp(last1, last2)
        loss = -ll
        if norm_by_times:
            loss = loss / ilen.astype(loss.dtype)
        return _reduce(loss, reduction)

    return apply_op(_f, (log_probs, labels), name="ctc_loss")


def dice_loss(input, label, epsilon=1e-5):
    def _f(p, y):
        y1 = jax.nn.one_hot(y.squeeze(-1).astype(jnp.int32), p.shape[-1], dtype=p.dtype)
        inter = jnp.sum(p * y1, axis=-1)
        union = jnp.sum(p, axis=-1) + jnp.sum(y1, axis=-1)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))

    return apply_op(_f, (input, label), name="dice_loss")


def square_error_cost(input, label):
    return apply_op(lambda a, b: jnp.square(a - b), (input, label), name="square_error_cost")


def log_loss(input, label, epsilon=1e-4):
    return apply_op(
        lambda p, y: -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon),
        (input, label),
        name="log_loss",
    )


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum"):
    def _f(z, y, nrm):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0.0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        pt = p * y + (1 - p) * (1 - y)
        at = alpha * y + (1 - alpha) * (1 - y)
        per = at * jnp.power(1 - pt, gamma) * ce
        if nrm is not None:
            per = per / nrm
        return _reduce(per, reduction)

    return apply_op(_f, (logit, label, normalizer), name="sigmoid_focal_loss")


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    """Ref nn/functional/loss.py multi_label_soft_margin_loss."""

    def _f(x, y, *w):
        lx = jax.nn.log_sigmoid(x)
        lnx = jax.nn.log_sigmoid(-x)
        loss = -(y * lx + (1.0 - y) * lnx)
        if w:
            loss = loss * w[0]
        return _reduce(loss.mean(axis=-1), reduction)

    args = (input, label) if weight is None else (input, label, weight)
    return apply_op(_f, args, name="multi_label_soft_margin_loss")


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean", name=None):
    """Ref triplet_margin_with_distance_loss (custom metric triplet loss)."""
    if distance_function is None:
        from .common import pairwise_distance

        distance_function = pairwise_distance

    d_pos = distance_function(input, positive)
    d_neg = distance_function(input, negative)
    if swap:
        from ...tensor.math import minimum as _min

        d_neg = _min(d_neg, distance_function(positive, negative))

    def _f(dp, dn):
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return apply_op(_f, (d_pos, d_neg), name="triplet_margin_with_distance_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """Ref npair_loss: softmax CE over anchor-positive similarities + L2."""

    def _f(a, p, y):
        sim = a @ p.T                                   # [B, B]
        yv = y.reshape(-1)
        same = (yv[:, None] == yv[None, :]).astype(sim.dtype)
        tgt = same / jnp.maximum(same.sum(-1, keepdims=True), 1.0)
        logp = jax.nn.log_softmax(sim, axis=-1)
        ce = -(tgt * logp).sum(-1).mean()
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, -1))
                        + jnp.mean(jnp.sum(p * p, -1))) * 0.25
        return ce + reg

    return apply_op(_f, (anchor, positive, labels), name="npair_loss")


def hsigmoid_loss(input, label, num_classes, weight, bias=None, path_table=None,
                  path_code=None, is_sparse=False, name=None):
    """Ref hsigmoid_loss — hierarchical sigmoid over the complete binary tree
    with num_classes-1 internal nodes (heap layout: leaves occupy
    [num_classes-1, 2*num_classes-2])."""
    import numpy as _np

    if path_table is not None or path_code is not None:
        raise NotImplementedError(
            "hsigmoid_loss: custom path_table/path_code trees are not "
            "implemented — only the default complete-binary-tree mode")
    # deepest possible path in a heap of 2*num_classes-1 nodes
    n_codes = int(_np.ceil(_np.log2(max(num_classes, 2)))) + 1

    def _f(x, y, w, *rest):
        b = rest[0] if bias is not None else None
        yv = y.reshape(-1).astype(jnp.int32)
        # walk leaf -> root: parent=(cur-1)//2; code = "is right child";
        # levels past the root are masked out (paths vary for non-pow2)
        codes, nodes, valids = [], [], []
        cur = yv + (num_classes - 1)
        for _ in range(n_codes):
            valid = cur > 0
            parent = jnp.maximum((cur - 1) // 2, 0)
            codes.append((cur == 2 * parent + 2).astype(jnp.float32))
            nodes.append(parent)
            valids.append(valid)
            cur = jnp.where(valid, parent, 0)
        node_idx = jnp.stack(nodes, 1)                    # [B, L]
        code = jnp.stack(codes, 1)
        vmask = jnp.stack(valids, 1).astype(jnp.float32)
        logits = jnp.einsum("blh,bh->bl", w[node_idx], x)
        if b is not None:
            logits = logits + b.reshape(-1)[node_idx]
        # p(path) = prod sigmoid(+/- logit); loss = -log p
        loss = (jax.nn.softplus(logits) - code * logits) * vmask
        return loss.sum(-1, keepdims=True)

    args = [input, label, weight]
    if bias is not None:
        args.append(bias)
    return apply_op(_f, tuple(args), name="hsigmoid_loss")


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean", name=None):
    """Ref margin_cross_entropy (ArcFace/CosFace-style margin softmax).

    cos(m1*theta + m2) - m3 applied to the target logit, then scaled CE.
    Single-group version; model-parallel class sharding composes via the mp
    mesh axis like ParallelCrossEntropy."""

    def _f(lg, y):
        yv = y.reshape(-1).astype(jnp.int32)
        theta = jnp.arccos(jnp.clip(lg, -1.0 + 1e-7, 1.0 - 1e-7))
        tgt = jax.nn.one_hot(yv, lg.shape[-1], dtype=lg.dtype)
        m_theta = margin1 * theta + margin2
        margined = jnp.cos(m_theta) - margin3
        out = jnp.where(tgt > 0, margined, lg) * scale
        logp = jax.nn.log_softmax(out, axis=-1)
        loss = -(tgt * logp).sum(-1)
        loss = _reduce(loss, reduction)
        if return_softmax:
            return loss, jax.nn.softmax(out, -1)
        return loss

    return apply_op(_f, (logits, label), name="margin_cross_entropy")
