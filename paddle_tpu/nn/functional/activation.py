"""Activation functionals (ref: python/paddle/nn/functional/activation.py).

Pure jax.nn/jnp compositions — XLA fuses these into adjacent matmuls on TPU, replacing
the reference's hand-written CUDA activation kernels (phi/kernels/gpu/activation_*).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor.tensor import apply_op


def _mk(name, fn):
    def op(x, *args, **kwargs):
        kwargs.pop("name", None)
        return apply_op(lambda v: fn(v, *args, **kwargs), (x,), name=name)

    op.__name__ = name
    return op


relu = _mk("relu", jax.nn.relu)
relu6 = _mk("relu6", jax.nn.relu6)
sigmoid = _mk("sigmoid", jax.nn.sigmoid)
tanh = _mk("tanh", jnp.tanh)
softplus = _mk("softplus", lambda v, beta=1.0, threshold=20.0: jnp.where(v * beta > threshold, v, jax.nn.softplus(v * beta) / beta))
softsign = _mk("softsign", jax.nn.soft_sign)
silu = _mk("silu", jax.nn.silu)
swish = silu
mish = _mk("mish", lambda v: v * jnp.tanh(jax.nn.softplus(v)))
tanhshrink = _mk("tanhshrink", lambda v: v - jnp.tanh(v))
log_sigmoid = _mk("log_sigmoid", jax.nn.log_sigmoid)


def gelu(x, approximate=False, name=None):
    return apply_op(lambda v: jax.nn.gelu(v, approximate=approximate), (x,), name="gelu")


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply_op(lambda v: jax.nn.leaky_relu(v, negative_slope), (x,), name="leaky_relu")


def elu(x, alpha=1.0, name=None):
    return apply_op(lambda v: jax.nn.elu(v, alpha), (x,), name="elu")


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply_op(lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)), (x,), name="selu")


def celu(x, alpha=1.0, name=None):
    return apply_op(lambda v: jax.nn.celu(v, alpha), (x,), name="celu")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply_op(lambda v: jnp.clip(v, min, max), (x,), name="hardtanh")


def hardshrink(x, threshold=0.5, name=None):
    return apply_op(lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0), (x,), name="hardshrink")


def softshrink(x, threshold=0.5, name=None):
    return apply_op(
        lambda v: jnp.where(v > threshold, v - threshold, jnp.where(v < -threshold, v + threshold, 0.0)),
        (x,),
        name="softshrink",
    )


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply_op(lambda v: jnp.clip(v * slope + offset, 0.0, 1.0), (x,), name="hardsigmoid")


def hardswish(x, name=None):
    return apply_op(lambda v: v * jnp.clip(v + 3.0, 0.0, 6.0) / 6.0, (x,), name="hardswish")


def softmax(x, axis=-1, dtype=None, name=None):
    return apply_op(lambda v: jax.nn.softmax(v, axis=axis), (x,), name="softmax")


def log_softmax(x, axis=-1, dtype=None, name=None):
    return apply_op(lambda v: jax.nn.log_softmax(v, axis=axis), (x,), name="log_softmax")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...framework import random as _random

    def _f(v):
        g = jax.random.gumbel(_random.get_rng_key(), v.shape, v.dtype)
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.put_along_axis(jnp.zeros_like(y), idx, 1.0, axis=axis, inplace=False)
            y = jax.lax.stop_gradient(y_hard - y) + y  # straight-through estimator
        return y

    return apply_op(_f, (x,), name="gumbel_softmax")


def prelu(x, weight, data_format="NCHW", name=None):
    def _f(v, w):
        if w.size == 1:
            return jnp.where(v > 0, v, w.reshape(()) * v)
        shape = [1] * v.ndim
        ch_axis = 1 if data_format == "NCHW" else v.ndim - 1
        shape[ch_axis] = w.size
        return jnp.where(v > 0, v, w.reshape(shape) * v)

    return apply_op(_f, (x, weight), name="prelu")


def rrelu(x, lower=0.125, upper=0.333, training=False, name=None):
    mid = (lower + upper) / 2.0
    return apply_op(lambda v: jnp.where(v >= 0, v, mid * v), (x,), name="rrelu")


def glu(x, axis=-1, name=None):
    return apply_op(lambda v: jax.nn.glu(v, axis=axis), (x,), name="glu")


def maxout(x, groups, axis=1, name=None):
    def _f(v):
        s = list(v.shape)
        c = s[axis]
        s[axis:axis + 1] = [c // groups, groups]
        return jnp.max(v.reshape(s), axis=axis + 1)

    return apply_op(_f, (x,), name="maxout")


def thresholded_relu(x, threshold=1.0, name=None):
    return apply_op(lambda v: jnp.where(v > threshold, v, 0.0), (x,), name="thresholded_relu")


def _inplace(fn):
    def op(x, *a, **k):
        out = fn(x, *a, **k)
        x._assume(out)   # keep the tape node: in-place ops are differentiable
        return x

    return op


relu_ = _inplace(relu)
elu_ = _inplace(elu)
softmax_ = _inplace(softmax)
tanh_ = _inplace(tanh)
