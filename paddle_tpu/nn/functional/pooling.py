"""Pooling functionals (ref: python/paddle/nn/functional/pooling.py, phi Pool2dKernel).

lax.reduce_window lowers to XLA ReduceWindow — fused, MXU-adjacent on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...tensor.tensor import apply_op


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(int(i) for i in v)
    return (int(v),) * n


def _pool_pad(padding, nd):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * nd
    p = list(padding)
    if len(p) == nd:
        return [(int(q), int(q)) for q in p]
    if len(p) == 2 * nd:
        return [(int(p[2 * i]), int(p[2 * i + 1])) for i in range(nd)]
    raise ValueError(f"bad padding {padding}")


def _reduce_pool(v, ksize, strides, pad, nd, op, init, ceil_mode):
    window = (1, 1) + ksize
    strd = (1, 1) + strides
    if isinstance(pad, str):
        padding = pad
    else:
        padding = [(0, 0), (0, 0)] + list(pad)
        if ceil_mode:
            padding = [(lo, hi + s - 1) for (lo, hi), s in zip(padding, strd)]
    return jax.lax.reduce_window(v, init, op, window, strd, padding)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
               data_format="NCHW", name=None):
    ks = _pair(kernel_size)
    st = _pair(stride) if stride is not None else ks
    pad = _pool_pad(padding, 2)

    def _f(v):
        neg = -jnp.inf if jnp.issubdtype(v.dtype, jnp.floating) else jnp.iinfo(v.dtype).min
        if data_format != "NCHW" and not return_mask:
            # native NHWC reduce_window — a transpose round-trip here would cost
            # two full passes over the activation on the TPU fast path
            padding = pad if isinstance(pad, str) else [(0, 0)] + list(pad) + [(0, 0)]
            if not isinstance(padding, str) and ceil_mode:
                padding = [(lo, hi + s - 1) for (lo, hi), s in
                           zip(padding, (1,) + st + (1,))]
            return jax.lax.reduce_window(v, neg, jax.lax.max, (1,) + ks + (1,),
                                         (1,) + st + (1,), padding)
        if data_format != "NCHW":
            v = jnp.transpose(v, (0, 3, 1, 2))
        out = _reduce_pool(v, ks, st, pad, 2, jax.lax.max, neg, ceil_mode)
        if data_format != "NCHW":
            out = jnp.transpose(out, (0, 2, 3, 1))
        if return_mask:
            # argmax within each window -> flattened HxW index (ref MaxPool2dWithIndexKernel)
            n, c, h, w = v.shape
            # shift values to be >= 1 so the zero-filled PAD slots of
            # conv_general_dilated_patches can never win the argmax
            vshift = v - jnp.min(jnp.where(jnp.isfinite(v), v, jnp.inf)) + 1.0
            vshift = jnp.where(jnp.isfinite(v), vshift, 0.0)
            patches = jax.lax.conv_general_dilated_patches(
                vshift, ks, st,
                padding=pad if isinstance(pad, str) else list(pad),
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
            )  # [n, c*kh*kw, oh, ow]
            oh, ow = patches.shape[2], patches.shape[3]
            patches = patches.reshape(n, c, ks[0] * ks[1], oh, ow)
            win = jnp.argmax(patches, axis=2)
            wi, wj = win // ks[1], win % ks[1]
            ph = 0 if isinstance(pad, str) else pad[0][0]
            pw = 0 if isinstance(pad, str) else pad[1][0]
            gi = jnp.arange(oh).reshape(1, 1, -1, 1) * st[0] - ph + wi
            gj = jnp.arange(ow).reshape(1, 1, 1, -1) * st[1] - pw + wj
            mask = (gi * w + gj).astype(jnp.int32)
            if data_format != "NCHW":
                # out was transposed back above; the mask must follow its layout
                mask = jnp.transpose(mask, (0, 2, 3, 1))
            return out, mask
        return out

    return apply_op(_f, (x,), name="max_pool2d")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
               divisor_override=None, data_format="NCHW", name=None):
    ks = _pair(kernel_size)
    st = _pair(stride) if stride is not None else ks
    pad = _pool_pad(padding, 2)

    def _f(v):
        if data_format != "NCHW":
            v = jnp.transpose(v, (0, 3, 1, 2))
        s = _reduce_pool(v, ks, st, pad, 2, jax.lax.add, 0.0 if jnp.issubdtype(v.dtype, jnp.floating) else 0, ceil_mode)
        if divisor_override:
            out = s / divisor_override
        elif exclusive and not isinstance(pad, str):
            ones = jnp.ones_like(v)
            cnt = _reduce_pool(ones, ks, st, pad, 2, jax.lax.add, 0.0, ceil_mode)
            out = s / cnt
        else:
            out = s / (ks[0] * ks[1])
        if data_format != "NCHW":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return apply_op(_f, (x,), name="avg_pool2d")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, name=None):
    ks = _pair(kernel_size, 1)
    st = _pair(stride, 1) if stride is not None else ks
    pad = _pool_pad(padding, 1)

    if return_mask:
        # delegate to the 2-D mask machinery on a height-1 image; the flat
        # (gi*W + gj) index with H=1 IS the 1-D position
        from ...tensor.manipulation import unsqueeze, squeeze

        pad1 = padding[0] if isinstance(padding, (list, tuple)) else int(padding)
        out, mask = max_pool2d(unsqueeze(x, 2), (1, ks[0]), (1, st[0]),
                               padding=(0, pad1),
                               return_mask=True, ceil_mode=ceil_mode)
        return squeeze(out, 2), squeeze(mask, 2)

    def _f(v):
        neg = -jnp.inf
        return _reduce_pool(v, ks, st, pad, 1, jax.lax.max, neg, ceil_mode)

    return apply_op(_f, (x,), name="max_pool1d")


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, name=None):
    ks = _pair(kernel_size, 1)
    st = _pair(stride, 1) if stride is not None else ks
    pad = _pool_pad(padding, 1)

    def _f(v):
        s = _reduce_pool(v, ks, st, pad, 1, jax.lax.add, 0.0, ceil_mode)
        if exclusive and not isinstance(pad, str):
            cnt = _reduce_pool(jnp.ones_like(v), ks, st, pad, 1, jax.lax.add, 0.0, ceil_mode)
            return s / cnt
        return s / ks[0]

    return apply_op(_f, (x,), name="avg_pool1d")


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
               data_format="NCDHW", name=None):
    ks = _pair(kernel_size, 3)
    st = _pair(stride, 3) if stride is not None else ks
    pad = _pool_pad(padding, 3)

    if return_mask and ceil_mode:
        raise NotImplementedError(
            "max_pool3d(return_mask=True, ceil_mode=True) is not supported")

    def _f(v):
        out = _reduce_pool(v, ks, st, pad, 3, jax.lax.max, -jnp.inf, ceil_mode)
        if not return_mask:
            return out
        n, c, d, h, w = v.shape
        vshift = v - jnp.min(jnp.where(jnp.isfinite(v), v, jnp.inf)) + 1.0
        vshift = jnp.where(jnp.isfinite(v), vshift, 0.0)
        patches = jax.lax.conv_general_dilated_patches(
            vshift, ks, st, padding=pad if isinstance(pad, str) else list(pad),
            dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
        od, oh, ow = patches.shape[2:]
        kd, kh, kw = ks
        patches = patches.reshape(n, c, kd * kh * kw, od, oh, ow)
        win = jnp.argmax(patches, axis=2)
        wd = win // (kh * kw)
        wh = (win // kw) % kh
        ww = win % kw
        pd_ = 0 if isinstance(pad, str) else pad[0][0]
        ph = 0 if isinstance(pad, str) else pad[1][0]
        pw = 0 if isinstance(pad, str) else pad[2][0]
        gd = jnp.arange(od).reshape(1, 1, -1, 1, 1) * st[0] - pd_ + wd
        gh = jnp.arange(oh).reshape(1, 1, 1, -1, 1) * st[1] - ph + wh
        gw = jnp.arange(ow).reshape(1, 1, 1, 1, -1) * st[2] - pw + ww
        mask = ((gd * h + gh) * w + gw).astype(jnp.int32)
        return out, mask

    return apply_op(_f, (x,), name="max_pool3d")


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
               divisor_override=None, data_format="NCDHW", name=None):
    ks = _pair(kernel_size, 3)
    st = _pair(stride, 3) if stride is not None else ks
    pad = _pool_pad(padding, 3)

    def _f(v):
        s = _reduce_pool(v, ks, st, pad, 3, jax.lax.add, 0.0, ceil_mode)
        if exclusive and not isinstance(pad, str):
            cnt = _reduce_pool(jnp.ones_like(v), ks, st, pad, 3, jax.lax.add, 0.0, ceil_mode)
            return s / cnt
        return s / (ks[0] * ks[1] * ks[2])

    return apply_op(_f, (x,), name="avg_pool3d")


def _adaptive_bins(in_size, out_size):
    starts = [int(np.floor(i * in_size / out_size)) for i in range(out_size)]
    ends = [int(np.ceil((i + 1) * in_size / out_size)) for i in range(out_size)]
    return starts, ends


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    os = _pair(output_size)

    def _f(v):
        if data_format != "NCHW":
            v = jnp.transpose(v, (0, 3, 1, 2))
        n, c, h, w = v.shape
        if h % os[0] == 0 and w % os[1] == 0:
            out = v.reshape(n, c, os[0], h // os[0], os[1], w // os[1]).mean(axis=(3, 5))
        else:
            hs, he = _adaptive_bins(h, os[0])
            ws, we = _adaptive_bins(w, os[1])
            rows = []
            for i in range(os[0]):
                cols = []
                for j in range(os[1]):
                    cols.append(v[:, :, hs[i]:he[i], ws[j]:we[j]].mean(axis=(2, 3)))
                rows.append(jnp.stack(cols, axis=-1))
            out = jnp.stack(rows, axis=-2)
        if data_format != "NCHW":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return apply_op(_f, (x,), name="adaptive_avg_pool2d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    os = _pair(output_size)

    def _f(v):
        n, c, h, w = v.shape
        if h % os[0] == 0 and w % os[1] == 0:
            return v.reshape(n, c, os[0], h // os[0], os[1], w // os[1]).max(axis=(3, 5))
        hs, he = _adaptive_bins(h, os[0])
        ws, we = _adaptive_bins(w, os[1])
        rows = []
        for i in range(os[0]):
            cols = [v[:, :, hs[i]:he[i], ws[j]:we[j]].max(axis=(2, 3)) for j in range(os[1])]
            rows.append(jnp.stack(cols, axis=-1))
        return jnp.stack(rows, axis=-2)

    return apply_op(_f, (x,), name="adaptive_max_pool2d")


def adaptive_avg_pool1d(x, output_size, name=None):
    os = int(output_size)

    def _f(v):
        n, c, l = v.shape
        if l % os == 0:
            return v.reshape(n, c, os, l // os).mean(axis=3)
        ss, es = _adaptive_bins(l, os)
        return jnp.stack([v[:, :, s:e].mean(axis=2) for s, e in zip(ss, es)], axis=-1)

    return apply_op(_f, (x,), name="adaptive_avg_pool1d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    os = int(output_size)

    def _f(v):
        n, c, l = v.shape
        if l % os == 0:
            return v.reshape(n, c, os, l // os).max(axis=3)
        ss, es = _adaptive_bins(l, os)
        return jnp.stack([v[:, :, s:e].max(axis=2) for s, e in zip(ss, es)], axis=-1)

    return apply_op(_f, (x,), name="adaptive_max_pool1d")


def _triple(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v, v)


def _adaptive_pool3d(v, os3, reduce):
    n, c, d, h, w = v.shape
    if d % os3[0] == 0 and h % os3[1] == 0 and w % os3[2] == 0:
        r = v.reshape(n, c, os3[0], d // os3[0], os3[1], h // os3[1],
                      os3[2], w // os3[2])
        return reduce(r, (3, 5, 7))
    ds, de = _adaptive_bins(d, os3[0])
    hs, he = _adaptive_bins(h, os3[1])
    ws, we = _adaptive_bins(w, os3[2])
    planes = []
    for k in range(os3[0]):
        rows = []
        for i in range(os3[1]):
            cols = [reduce(v[:, :, ds[k]:de[k], hs[i]:he[i], ws[j]:we[j]],
                           (2, 3, 4)) for j in range(os3[2])]
            rows.append(jnp.stack(cols, axis=-1))
        planes.append(jnp.stack(rows, axis=-2))
    return jnp.stack(planes, axis=-3)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    """Ref nn/functional/pooling.py adaptive_avg_pool3d."""
    os3 = _triple(output_size)
    return apply_op(lambda v: _adaptive_pool3d(v, os3, lambda a, ax: a.mean(axis=ax)),
                    (x,), name="adaptive_avg_pool3d")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    os3 = _triple(output_size)
    return apply_op(lambda v: _adaptive_pool3d(v, os3, lambda a, ax: a.max(axis=ax)),
                    (x,), name="adaptive_max_pool3d")


def _unpool(v, mask, spatial_shape):
    """Scatter pooled values back to `spatial_shape` via the flattened-index
    mask max_pool(return_mask=True) produced (ref phi Unpool kernels)."""
    n, c = v.shape[0], v.shape[1]
    size = 1
    for s in spatial_shape:
        size *= s
    flatv = v.reshape(n, c, -1)
    flatm = mask.reshape(n, c, -1).astype(jnp.int32)
    out = jnp.zeros((n, c, size), v.dtype)
    out = jax.vmap(jax.vmap(lambda o, m, val: o.at[m].set(val)))(out, flatm, flatv)
    return out.reshape((n, c) + tuple(spatial_shape))


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCHW", name=None):
    """Ref nn/functional/pooling.py max_unpool2d — inverse of
    max_pool2d(return_mask=True)."""
    ks = _pair(kernel_size)
    st = _pair(stride) if stride is not None else ks

    def _f(v, m):
        n, c, oh, ow = v.shape
        pd = _pair(padding)
        if output_size is not None:
            hw = tuple(output_size[-2:])
        else:
            hw = ((oh - 1) * st[0] + ks[0] - 2 * pd[0],
                  (ow - 1) * st[1] + ks[1] - 2 * pd[1])
        return _unpool(v, m, hw)

    return apply_op(_f, (x, indices), name="max_unpool2d")


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCL", name=None):
    ks = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
    st = (stride if isinstance(stride, int) else
          (stride[0] if stride else ks)) or ks

    pd = padding if isinstance(padding, int) else padding[0]

    def _f(v, m):
        n, c, ol = v.shape
        length = (output_size[-1] if output_size is not None
                  else (ol - 1) * st + ks - 2 * pd)
        return _unpool(v, m, (length,))

    return apply_op(_f, (x, indices), name="max_unpool1d")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCDHW", name=None):
    ks = _triple(kernel_size)
    st = _triple(stride) if stride is not None else ks

    def _f(v, m):
        n, c, od, oh, ow = v.shape
        pd = _triple(padding)
        if output_size is not None:
            dhw = tuple(output_size[-3:])
        else:
            dhw = ((od - 1) * st[0] + ks[0] - 2 * pd[0],
                   (oh - 1) * st[1] + ks[1] - 2 * pd[1],
                   (ow - 1) * st[2] + ks[2] - 2 * pd[2])
        return _unpool(v, m, dhw)

    return apply_op(_f, (x, indices), name="max_unpool3d")
