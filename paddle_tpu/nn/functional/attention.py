"""Attention functionals.

`scaled_dot_product_attention` is the single entry point (ref gap: the snapshot's only
fused attention is `operators/fused/fused_attention_op.cu`, single-device).  The dense
path is a jnp composition; the flash path is a Pallas TPU kernel
(paddle_tpu/ops/flash_attention.py) selected automatically for long sequences on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework import random as _random
from ...tensor.tensor import Tensor, apply_op, _unwrap


def _dense_sdpa(q, k, v, mask, dropout_p, is_causal, scale, training=True):
    # q,k,v: [B, S, H, D] (paddle layout)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    qT = jnp.swapaxes(q, 1, 2)  # [B,H,S,D]
    kT = jnp.swapaxes(k, 1, 2)
    vT = jnp.swapaxes(v, 1, 2)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qT, kT) * s
    if is_causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        causal = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(causal, logits, jnp.finfo(logits.dtype).min)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
        else:
            logits = logits + mask
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_p and training:
        from .common import _dropout_mask_mul

        # key-residual dropout (mask regenerated in bwd — see common.py):
        # the [B,H,S,S] probs mask is the single largest dropout residual
        probs = _dropout_mask_mul(probs, _random.get_rng_key(),
                                  float(dropout_p), True, tuple(probs.shape))
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vT)
    return jnp.swapaxes(out, 1, 2)  # back to [B,S,H,D]


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, scale=None, backend="auto", name=None):
    """query/key/value: [batch, seq, num_heads, head_dim] (paddle layout)."""

    # fused short-sequence path (encoder workloads: BERT/ERNIE S<=512): one
    # Pallas kernel per step with probs + dropout masks held in VMEM — the
    # dense path's [B,H,S,S] logits/probs/mask HBM round-trips disappear
    # (ops/encoder_attention.py; ref fused_attention_op.cu regime)
    if backend == "auto" and attn_mask is None:
        from ...core.device import is_tpu_backend
        from ...ops import encoder_attention as _enc

        qv = _unwrap(query)
        kv = _unwrap(key)
        use_enc = (qv.ndim == 4 and is_tpu_backend()
                   and _enc.supported(qv.shape[0] * qv.shape[2], qv.shape[1],
                                      qv.shape[-1], kv.shape[1]))
        if use_enc:
            rate = float(dropout_p) if (dropout_p and training) else 0.0
            sc = scale

            def _f(q, k, v):
                seed = None
                if rate > 0.0:
                    seed = jax.random.bits(_random.get_rng_key(), (2,),
                                           jnp.uint32).astype(jnp.int32)
                return _enc.encoder_attention(q, k, v, seed=seed, scale=sc,
                                              dropout_rate=rate,
                                              causal=is_causal)

            return apply_op(_f, (query, key, value), name="encoder_attention")

    use_flash = False
    if backend in ("auto", "flash"):
        try:
            qv = _unwrap(query)
            kv = _unwrap(key)
            seq = qv.shape[1]
            seq_k = kv.shape[1]
            hd = qv.shape[-1]
            from ...core.device import is_tpu_backend

            on_tpu = is_tpu_backend()
            no_drop = dropout_p == 0.0 or not training
            if backend == "flash" and not no_drop:
                import warnings

                warnings.warn(
                    "backend='flash' with active attention dropout falls back to the "
                    "dense SDPA path (the Pallas flash kernel has no dropout); full "
                    "[B,H,S,S] attention probs will be materialized")
            from ...ops.flash_attention import supports_seq

            blocks_ok = supports_seq(seq) and supports_seq(seq_k)
            causal_ok = not is_causal or seq <= seq_k
            # blocks_ok gates BOTH paths: an explicit backend='flash' request
            # with an untileable length falls back to dense instead of raising
            # deep inside _auto_block
            use_flash = (backend == "flash" and no_drop and causal_ok
                         and blocks_ok) or (
                on_tpu and seq >= 1024 and blocks_ok and causal_ok
                and hd in (64, 128, 256) and attn_mask is None and no_drop
            )
        except Exception:
            use_flash = False

    if use_flash:
        from ...ops.flash_attention import flash_attention as _flash

        def _f(q, k, v):
            return _flash(q, k, v, causal=is_causal, scale=scale)

        return apply_op(_f, (query, key, value), name="flash_attention")

    def _f(q, k, v, m):
        return _dense_sdpa(q, k, v, m, dropout_p, is_causal, scale, training)

    return apply_op(_f, (query, key, value, attn_mask), name="sdpa")


# paddle.nn.functional.flash_attention module-style API parity
def flash_attention(query, key, value, dropout=0.0, causal=False, return_softmax=False,
                    training=True, name=None):
    out = scaled_dot_product_attention(query, key, value, dropout_p=dropout,
                                       is_causal=causal, training=training)
    if return_softmax:
        return out, None
    return out, None
