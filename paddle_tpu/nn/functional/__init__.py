"""paddle.nn.functional parity surface (ref: python/paddle/nn/functional/__init__.py)."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import (  # noqa: F401
    conv1d,
    conv2d,
    conv3d,
    conv1d_transpose,
    conv2d_transpose,
    conv3d_transpose,
)
from .norm import (  # noqa: F401
    batch_norm,
    layer_norm,
    fused_dropout_add_layer_norm,
    instance_norm,
    group_norm,
    local_response_norm,
    rms_norm,
)
from .pooling import (  # noqa: F401
    max_pool1d,
    max_pool2d,
    max_pool3d,
    avg_pool1d,
    avg_pool2d,
    avg_pool3d,
    adaptive_avg_pool1d,
    adaptive_avg_pool2d,
    adaptive_max_pool1d,
    adaptive_max_pool2d,
    adaptive_avg_pool3d,
    adaptive_max_pool3d,
    max_unpool1d,
    max_unpool2d,
    max_unpool3d,
)
from .loss import *  # noqa: F401,F403
from .attention import scaled_dot_product_attention, flash_attention  # noqa: F401
from ...tensor.manipulation import diag_embed  # noqa: F401
