"""Convolutions (ref: python/paddle/nn/functional/conv.py, phi ConvKernel/cudnn).

On TPU these lower to XLA `convolution` ops that tile directly onto the MXU — the
entire cudnn algo-selection/workspace machinery of the reference
(paddle/phi/kernels/gpudnn/conv_kernel.cu) collapses into XLA's conv emitter.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor.tensor import apply_op


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(int(i) for i in v)
    return (int(v),) * n


def _conv_padding(padding, nd):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * nd
    padding = list(padding)
    if len(padding) == nd:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * nd:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(nd)]
    # paddle also allows [[0,0],[0,0],[h0,h1],[w0,w1]]
    if len(padding) == nd + 2 and isinstance(padding[0], (list, tuple)):
        return [tuple(p) for p in padding[2:]]
    raise ValueError(f"bad padding {padding}")


# When True, channel-first convs are internally rewritten to channel-last
# ("NHWC"/"HWIO") with boundary transposes; when False the NCHW dimension numbers
# are handed to XLA directly (its layout assignment picks physical layouts anyway).
# Benchmarked on v5e (bench.py, r3 RTT-corrected timing): direct NCHW wins
# (2245 vs 2198 img/s on ResNet-50 train; XLA's layout assignment already
# picks physical layouts), so the default is False; kept as a switch for
# future autotuning.
_INTERNAL_CHANNEL_LAST = False


def _conv(x, weight, bias, stride, padding, dilation, groups, data_format, nd, name):
    strides = _pair(stride, nd)
    dilations = _pair(dilation, nd)
    pad = _conv_padding(padding, nd)
    spatial = "DHW"[3 - nd:]
    channel_first = data_format in ("NCHW", "NCL", "NCDHW")
    relayout = channel_first and _INTERNAL_CHANNEL_LAST
    if channel_first and not relayout:
        lhs_spec = "NC" + spatial
        rhs_spec = "OI" + spatial
    else:
        lhs_spec = "N" + spatial + "C"
        rhs_spec = spatial + "IO" if relayout else "OI" + spatial
    dn = (lhs_spec, rhs_spec, lhs_spec)

    def _f(v, w, b):
        # NB: no preferred_element_type here — the MXU accumulates bf16 in f32
        # internally, and an explicit f32 accumulate breaks the conv transpose rule
        # under AD (f32 cotangent vs bf16 weight).  lax.conv requires equal input
        # dtypes; follow the activation dtype when a layer wasn't cast.
        if w.dtype != v.dtype:
            w = w.astype(v.dtype)
        if relayout:
            v = jnp.moveaxis(v, 1, -1)  # NC... -> N...C
            w = jnp.transpose(w, tuple(range(2, 2 + nd)) + (1, 0))  # OI... -> ...IO
        out = jax.lax.conv_general_dilated(
            v, w, window_strides=strides, padding=pad,
            rhs_dilation=dilations, dimension_numbers=dn,
            feature_group_count=groups,
        )
        if b is not None:
            shape = [1] * out.ndim
            shape[lhs_spec.index("C")] = b.shape[0]
            if relayout:
                shape = [1] * (out.ndim - 1) + [b.shape[0]]
            out = out + b.reshape(shape)
        if relayout:
            out = jnp.moveaxis(out, -1, 1)
        return out

    return apply_op(_f, (x, weight, bias), name=name)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 1, "conv1d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 2, "conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 3, "conv3d")


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups,
                    data_format, nd, output_size, name):
    strides = _pair(stride, nd)
    dilations = _pair(dilation, nd)
    opad = _pair(output_padding, nd)
    if isinstance(padding, str):
        raise ValueError("string padding unsupported for conv_transpose")
    pad = _conv_padding(padding, nd)

    if data_format in ("NCHW", "NCL", "NCDHW"):
        lhs_spec = "NC" + "DHW"[3 - nd:]
    else:
        lhs_spec = "N" + "DHW"[3 - nd:] + "C"
    rhs_spec = "IO" + "DHW"[3 - nd:]  # paddle weight layout: [in, out/groups, *k]
    dn = (lhs_spec, rhs_spec, lhs_spec)

    def _f(v, w, b):
        # transpose conv = gradient of conv: use conv_transpose with IO layout
        k = w.shape[2:]
        tpad = [
            (d * (kk - 1) - p[0], d * (kk - 1) - p[1] + op)
            for kk, d, p, op in zip(k, dilations, pad, opad)
        ]
        if groups > 1:
            # split groups manually (lax.conv_transpose lacks feature groups)
            cin = v.shape[lhs_spec.index("C")]
            gs = cin // groups
            outs = []
            for g in range(groups):
                sl = [slice(None)] * v.ndim
                sl[lhs_spec.index("C")] = slice(g * gs, (g + 1) * gs)
                wg = w[g * gs:(g + 1) * gs]
                outs.append(
                    jax.lax.conv_transpose(
                        v[tuple(sl)], wg, strides=strides, padding=tpad,
                        rhs_dilation=dilations, dimension_numbers=dn, transpose_kernel=False,
                    )
                )
            out = jnp.concatenate(outs, axis=lhs_spec.index("C"))
        else:
            out = jax.lax.conv_transpose(
                v, w, strides=strides, padding=tpad,
                rhs_dilation=dilations, dimension_numbers=dn, transpose_kernel=False,
            )
        if b is not None:
            shape = [1] * out.ndim
            shape[lhs_spec.index("C")] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    return apply_op(_f, (x, weight, bias), name=name)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                           groups, data_format, 1, output_size, "conv1d_transpose")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                           groups, data_format, 2, output_size, "conv2d_transpose")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                           groups, data_format, 3, output_size, "conv3d_transpose")
