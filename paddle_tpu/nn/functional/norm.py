"""Normalization functionals (ref: python/paddle/nn/functional/norm.py, phi BatchNormKernel).

Running-stat updates are returned functionally and written back to layer buffers by the
calling Layer — keeping the computation pure so whole steps jit cleanly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor.tensor import Tensor, apply_op, _unwrap


def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False,
               momentum=0.9, epsilon=1e-5, data_format="NCHW", use_global_stats=None, name=None):
    ch_axis = 1 if data_format.startswith("NC") else -1
    use_batch_stats = training and not use_global_stats

    if use_batch_stats:
        # batch stats computed ONCE, in f32 (bf16 mean/var loses precision),
        # shared by the normalization, the backward, and the running-stat
        # update — the reference kernel's saved_mean/saved_variance contract
        # (phi BatchNormKernel).  sum/sum-of-squares form: ONE fused
        # multi-output reduce over the activation instead of mean + var
        # (jnp.var re-reads the input to subtract the mean) — measured
        # +7.7% on the ResNet-50 train step (51.1 -> 47.5 ms, v5e b128);
        # f32 accumulation keeps E[x^2]-E[x]^2 BN-safe, clamped at 0
        def _stats(v):
            ch = ch_axis % v.ndim
            axes = tuple(i for i in range(v.ndim) if i != ch)
            vf = v.astype(jnp.float32)
            s1 = jnp.sum(vf, axis=axes)
            s2 = jnp.sum(vf * vf, axis=axes)
            n = 1
            for i in axes:
                n *= v.shape[i]
            m = s1 / n
            return m, jnp.maximum(s2 / n - m * m, 0.0)

        mean_t, var_t = apply_op(_stats, (x,), name="batch_norm_stats")
    else:
        mean_t, var_t = running_mean, running_var

    def _f(v, m, s, w, b):
        # collapse to a per-channel affine in f32, then one fused
        # multiply-add over the activation in its own dtype
        scale = jax.lax.rsqrt(s.astype(jnp.float32) + epsilon)
        if w is not None:
            scale = scale * w.astype(jnp.float32)
        offset = -m.astype(jnp.float32) * scale
        if b is not None:
            offset = offset + b.astype(jnp.float32)
        shape = [1] * v.ndim
        shape[ch_axis] = v.shape[ch_axis]
        return v * scale.reshape(shape).astype(v.dtype) \
            + offset.reshape(shape).astype(v.dtype)

    out = apply_op(_f, (x, mean_t, var_t, weight, bias), name="batch_norm")

    if use_batch_stats and isinstance(running_mean, Tensor):
        # functional stat update written back to the buffers (ref
        # BatchNormKernel saved stats).  Routed through apply_op so a static
        # Program capture records it — set_value then promotes the write to
        # live program state (MeanOut/VarianceOut analog) instead of baking
        # the build-time placeholder stats.
        v = _unwrap(x)
        ch = ch_axis % v.ndim
        n = 1
        for i in range(v.ndim):
            if i != ch:
                n *= v.shape[i]
        factor = n / max(n - 1, 1)
        new_mean = apply_op(
            lambda rm, m: momentum * rm + (1 - momentum) * m,
            (running_mean, mean_t.detach()), name="bn_moving_mean")
        new_var = apply_op(
            lambda rv, s: momentum * rv + (1 - momentum) * (s * factor),
            (running_var, var_t.detach()), name="bn_moving_var")
        running_mean.set_value(new_mean)
        running_var.set_value(new_var)
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    ns = normalized_shape if isinstance(normalized_shape, (list, tuple)) else [normalized_shape]
    nd = len(ns)

    def _f(v, w, b):
        axes = tuple(range(v.ndim - nd, v.ndim))
        # SHIFTED sum/sum-of-squares stats in ONE fused f32 multi-output
        # reduce (jnp.var re-reads the input to subtract the mean — same
        # single-pass rewrite that bought +7.7% on BN above).  The shift by
        # the row's first element keeps the summands at the scale of the
        # SPREAD, not the mean, so E[d^2]-E[d]^2 cannot cancel
        # catastrophically when |mean| >> std.  f32 stats regardless of
        # activation dtype (bf16 mean/var at h>=768 degrades normalization).
        vf = v.astype(jnp.float32)
        n = 1
        for i in axes:
            n *= v.shape[i]
        first = jax.lax.slice_in_dim(vf, 0, 1, axis=axes[0])
        for ax in axes[1:]:
            first = jax.lax.slice_in_dim(first, 0, 1, axis=ax)
        d = vf - first
        s1 = jnp.sum(d, axis=axes, keepdims=True)
        s2 = jnp.sum(d * d, axis=axes, keepdims=True)
        dmean = s1 / n
        var = jnp.maximum(s2 / n - dmean * dmean, 0.0)
        mean = first + dmean
        out = ((vf - mean) * jax.lax.rsqrt(var + epsilon)).astype(v.dtype)
        if w is not None:
            out = out * w
        if b is not None:
            out = out + b
        return out

    return apply_op(_f, (x, weight, bias), name="layer_norm")


def fused_dropout_add_layer_norm(x, residual, weight, bias, p=0.0, epsilon=1e-5,
                                 training=True, name=None):
    """out = LayerNorm(residual + dropout(x)) — the transformer-encoder glue
    pattern, fused.  Ref: fluid/operators/fused/fused_dropout_helper.h
    (ResidualDropoutBias + LayerNorm epilogue of fused_attention /
    fused_feedforward).  On TPU this lowers to ONE Pallas kernel with on-core
    RNG (paddle_tpu/ops/fused_ln.py); elsewhere it runs the same math as the
    composed ops (key-residual dropout + single-pass f32 LN stats)."""
    from ...framework import random as _random

    rate = float(p) if training else 0.0
    eps = float(epsilon)

    def _f(xb, res, w, b):
        h = xb.shape[-1]
        n = 1
        for d in xb.shape[:-1]:
            n *= d
        from ...core.device import is_tpu_backend

        if is_tpu_backend() and w is not None and b is not None:
            from ...ops import fused_ln as _k

            if _k.supported(n, h):
                if rate > 0.0:
                    key = _random.get_rng_key()
                    seed = jax.random.bits(key, (2,), jnp.uint32).astype(jnp.int32)
                else:
                    # no dropout -> no RNG stream advance (keeps seed-for-seed
                    # parity with the composed/CPU path in eval mode)
                    seed = jnp.zeros((2,), jnp.int32)
                return _k.fused_dropout_add_layer_norm(xb, res, w, b, seed,
                                                       rate, eps)
        # composed path: identical math, jax.random mask
        xv = xb
        if rate > 0.0:
            from .common import _dropout_mask_mul

            xv = _dropout_mask_mul(xv, _random.get_rng_key(), rate, True,
                                   tuple(xv.shape))
        s = res.astype(jnp.float32) + xv.astype(jnp.float32)
        mean = jnp.mean(s, axis=-1, keepdims=True)
        c = s - mean
        var = jnp.mean(c * c, axis=-1, keepdims=True)
        out = (c * jax.lax.rsqrt(var + eps)).astype(xb.dtype)
        if w is not None:
            out = out * w
        if b is not None:
            out = out + b
        return out

    return apply_op(_f, (x, residual, weight, bias), name="fused_dropout_add_ln")


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW", name=None):
    def _f(v, w, b):
        axes = tuple(range(2, v.ndim))
        mean = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.var(v, axis=axes, keepdims=True)
        out = (v - mean) * jax.lax.rsqrt(var + eps)
        if w is not None:
            shape = [1, -1] + [1] * (v.ndim - 2)
            out = out * w.reshape(shape)
        if b is not None:
            shape = [1, -1] + [1] * (v.ndim - 2)
            out = out + b.reshape(shape)
        return out

    return apply_op(_f, (x, weight, bias), name="instance_norm")


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None, data_format="NCHW", name=None):
    def _f(v, w, b):
        n, c = v.shape[0], v.shape[1]
        rest = v.shape[2:]
        g = v.reshape(n, num_groups, c // num_groups, *rest)
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) * jax.lax.rsqrt(var + epsilon)).reshape(v.shape)
        shape = [1, c] + [1] * len(rest)
        if w is not None:
            out = out * w.reshape(shape)
        if b is not None:
            out = out + b.reshape(shape)
        return out

    return apply_op(_f, (x, weight, bias), name="group_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    def _f(v):
        sq = jnp.square(v)
        half = size // 2
        c = v.shape[1]
        padded = jnp.pad(sq, [(0, 0), (half, size - 1 - half)] + [(0, 0)] * (v.ndim - 2))
        acc = jnp.zeros_like(v)
        for i in range(size):
            acc = acc + padded[:, i:i + c]
        return v / jnp.power(k + alpha * acc, beta)

    return apply_op(_f, (x,), name="local_response_norm")


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """Net-new (LLaMA-family); ref gap: Paddle snapshot has no fused RMSNorm."""

    def _f(v, w):
        ms = jnp.mean(jnp.square(v.astype(jnp.float32)), axis=-1, keepdims=True)
        out = (v.astype(jnp.float32) * jax.lax.rsqrt(ms + epsilon)).astype(v.dtype)
        if w is not None:
            out = out * w
        return out

    return apply_op(_f, (x, weight), name="rms_norm")
