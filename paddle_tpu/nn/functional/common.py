"""Common functionals: linear/dropout/pad/embedding/interpolate/one_hot/...

Reference: python/paddle/nn/functional/common.py + input.py.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ...tensor.tensor import Tensor, apply_op, _unwrap
from ...framework import random as _random
from ...core import dtypes as _dt


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b — the MXU workhorse (ref: phi MatmulKernel + EW add fusion)."""

    def _f(v, w, b):
        out = jnp.matmul(v, w)
        if b is not None:
            out = out + b
        return out

    return apply_op(_f, (x, weight, bias), name="linear")


def _keep_mask(key, shape, rate):
    """Bernoulli(1-rate) keep mask from raw uint16 random bits: one
    RngBitGenerator output + one compare, no f32 uniform temp (at the ERNIE
    attention shape that temp alone is 384M per draw).  Granularity of the
    keep probability is 1/65536 — below any observable dropout effect."""
    thresh = np.uint16(min(int(round((1.0 - rate) * 65536.0)), 65535))
    bits = jax.random.bits(key, shape, jnp.uint16)
    return bits < thresh


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _dropout_mask_mul(v, key, rate, upscale, mask_shape):
    keep = _keep_mask(key, mask_shape, rate)
    scale = 1.0 / (1.0 - rate) if upscale else 1.0
    return jnp.where(keep, v * jnp.asarray(scale, v.dtype), jnp.zeros_like(v))


def _dropout_fwd(v, key, rate, upscale, mask_shape):
    # residual = the KEY only: the mask is regenerated in the backward
    # (hardware-RNG bits are cheap; storing the [*, S, S]/[B*S, H] bool
    # residuals for a full encoder step costs ~2.3G HBM and OOMed the dense
    # ERNIE step once rbg made the masks non-rematerializable for XLA)
    return _dropout_mask_mul(v, key, rate, upscale, mask_shape), key


def _dropout_bwd(rate, upscale, mask_shape, key, g):
    keep = _keep_mask(key, mask_shape, rate)
    scale = 1.0 / (1.0 - rate) if upscale else 1.0
    dv = jnp.where(keep, g * jnp.asarray(scale, g.dtype), jnp.zeros_like(g))
    return dv, None


_dropout_mask_mul.defvjp(_dropout_fwd, _dropout_bwd)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training and p > 0.0:
            return apply_op(lambda v: v * (1.0 - float(p)), (x,), name="dropout_infer")
        return apply_op(lambda v: v, (x,), name="dropout_id")
    rate = float(p)
    if rate >= 1.0:  # drop everything (1/(1-rate) scale would div-by-zero)
        return apply_op(lambda v: jnp.zeros_like(v), (x,), name="dropout_all")

    def _f(v):
        shape = list(v.shape)
        if axis is not None:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        return _dropout_mask_mul(v, _random.get_rng_key(), rate,
                                 mode == "upscale_in_train", tuple(shape))

    return apply_op(_f, (x,), name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return apply_op(lambda v: v, (x,), name="alpha_dropout_id")
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def _f(v):
        keep = jax.random.bernoulli(_random.get_rng_key(), 1.0 - p, v.shape)
        a = (1.0 / ((1.0 - p) * (1.0 + p * alpha_p**2)) ** 0.5)
        b = -a * alpha_p * p
        return a * jnp.where(keep, v, alpha_p) + b

    return apply_op(_f, (x,), name="alpha_dropout")


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Ref: phi EmbeddingKernel; gather feeding the MXU-heavy layers above it."""

    def _f(idx, w):
        out = jnp.take(w, idx.astype(jnp.int32), axis=0)
        if padding_idx is not None:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, jnp.zeros_like(out), out)
        return out

    return apply_op(lambda w, idx: _f(idx, w), (weight, _unwrap(x)), name="embedding")


def one_hot(x, num_classes, name=None):
    return apply_op(lambda v: jax.nn.one_hot(v.astype(jnp.int32), num_classes, dtype=_dt.get_default_dtype()), (x,), name="one_hot")


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def _f(l, pd):
        k = l.shape[-1]
        if pd is not None:
            return (1 - epsilon) * l + epsilon * pd
        return (1 - epsilon) * l + epsilon / k

    return apply_op(_f, (label, prior_dist), name="label_smooth")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", pad_from_left_axis=False, name=None):
    """Paddle pad: `pad` is [last-dim lo, hi, 2nd-last lo, hi, ...] for the int-list
    form applied per data_format spatial dims, or full per-axis when len==2*ndim."""

    def _f(v, padlist):
        nd = v.ndim
        if isinstance(padlist, (list, tuple)) and len(padlist) == 2 * nd:
            cfg = [(int(padlist[2 * i]), int(padlist[2 * i + 1])) for i in range(nd)]
        else:
            # spatial form: applies to W (and H, D) depending on rank & format
            p = [int(q) for q in padlist]
            cfg = [(0, 0)] * nd
            if data_format.startswith("NC"):
                spatial = list(range(2, nd))
            else:
                spatial = list(range(1, nd - 1))
            # paddle order: innermost (last spatial) first
            pairs = [(p[i], p[i + 1]) for i in range(0, len(p), 2)]
            for ax, pr in zip(reversed(spatial), pairs):
                cfg[ax] = pr
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(v, cfg, mode="constant", constant_values=value)
        return jnp.pad(v, cfg, mode=jmode)

    padlist = pad if not isinstance(pad, Tensor) else [int(i) for i in np.asarray(pad._value)]
    return apply_op(lambda v: _f(v, padlist), (x,), name="pad")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def _f(v):
        n = jnp.power(jnp.sum(jnp.power(jnp.abs(v), p), axis=axis, keepdims=True), 1.0 / p)
        return v / jnp.maximum(n, epsilon)

    return apply_op(_f, (x,), name="normalize")


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    from ...tensor.linalg import cosine_similarity as _cs

    return _cs(x1, x2, axis, eps)


def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
                align_mode=0, data_format="NCHW", name=None):
    """Ref: phi InterpolateKernel. Uses jax.image.resize for the core method."""

    def _out_size(v):
        if data_format == "NCHW":
            spatial = v.shape[2:]
        else:
            spatial = v.shape[1:-1]
        if size is not None:
            s = size if not isinstance(size, Tensor) else [int(i) for i in np.asarray(size._value)]
            return tuple(int(i) if not isinstance(i, Tensor) else int(i.item()) for i in s)
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * len(spatial)
        return tuple(int(d * f) for d, f in zip(spatial, sf))

    method = {"nearest": "nearest", "bilinear": "bilinear", "bicubic": "bicubic",
              "trilinear": "trilinear", "linear": "linear", "area": "linear"}[mode]

    def _f(v):
        out_sp = _out_size(v)
        if data_format == "NCHW":
            full = v.shape[:2] + out_sp
        else:
            full = (v.shape[0],) + out_sp + (v.shape[-1],)
        if align_corners and method != "nearest" and all(o > 1 for o in out_sp):
            # align_corners resize via explicit gather
            if data_format == "NCHW":
                sp_axes = list(range(2, v.ndim))
            else:
                sp_axes = list(range(1, v.ndim - 1))
            out = v
            for ax, o in zip(sp_axes, out_sp):
                n = out.shape[ax]
                pos = jnp.linspace(0.0, n - 1.0, o)
                lo = jnp.floor(pos).astype(jnp.int32)
                hi = jnp.minimum(lo + 1, n - 1)
                w = (pos - lo).astype(v.dtype)
                a = jnp.take(out, lo, axis=ax)
                b = jnp.take(out, hi, axis=ax)
                shape = [1] * out.ndim
                shape[ax] = o
                w = w.reshape(shape)
                out = a * (1 - w) + b * w
            return out
        if method == "trilinear":
            return jax.image.resize(v, full, method="linear" if v.ndim == 5 else method)
        return jax.image.resize(v, full, method=method)

    return apply_op(_f, (x,), name="interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def _f(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c // (r * r), r, r, h, w)
            v = v.transpose(0, 1, 4, 2, 5, 3)
            return v.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, r, r, c // (r * r))
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(n, h * r, w * r, c // (r * r))

    return apply_op(_f, (x,), name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def _f(v):
        n, c, h, w = v.shape
        v = v.reshape(n, c, h // r, r, w // r, r)
        v = v.transpose(0, 1, 3, 5, 2, 4)
        return v.reshape(n, c * r * r, h // r, w // r)

    return apply_op(_f, (x,), name="pixel_unshuffle")


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (ref: phi UnfoldKernel)."""
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    if len(pd) == 2:
        pd = [pd[0], pd[1], pd[0], pd[1]]
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2

    def _f(v):
        n, c, h, w = v.shape
        v = jnp.pad(v, [(0, 0), (0, 0), (pd[0], pd[2]), (pd[1], pd[3])])
        oh = (v.shape[2] - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (v.shape[3] - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        patches = jax.lax.conv_general_dilated_patches(
            v, filter_shape=ks, window_strides=st, padding="VALID", rhs_dilation=dl,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )  # [n, c*kh*kw, oh, ow]
        return patches.reshape(n, c * ks[0] * ks[1], oh * ow)

    return apply_op(_f, (x,), name="unfold")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    if len(pd) == 2:
        pd = [pd[0], pd[1], pd[0], pd[1]]
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2
    oh, ow = output_sizes

    def _f(v):
        n, ckk, L = v.shape
        c = ckk // (ks[0] * ks[1])
        ph, pw = oh + pd[0] + pd[2], ow + pd[1] + pd[3]
        nh = (ph - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        nw = (pw - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        out = jnp.zeros((n, c, ph, pw), v.dtype)
        v = v.reshape(n, c, ks[0], ks[1], nh, nw)
        for i in range(ks[0]):
            for j in range(ks[1]):
                hs = i * dl[0]
                ws = j * dl[1]
                out = out.at[:, :, hs:hs + nh * st[0]:st[0], ws:ws + nw * st[1]:st[1]].add(v[:, :, i, j])
        return out[:, :, pd[0]:pd[0] + oh, pd[1]:pd[1] + ow]

    return apply_op(_f, (x,), name="fold")


def bilinear(x1, x2, weight, bias=None, name=None):
    def _f(a, b, w, bi):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bi is not None:
            out = out + bi
        return out

    return apply_op(_f, (x1, x2, weight, bias), name="bilinear")


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    def _f(v):
        m = maxlen if maxlen is not None else int(jnp.max(v))
        return (jnp.arange(m)[None, :] < v[..., None]).astype(_dt.convert_dtype(dtype))

    if maxlen is None:
        v = np.asarray(_unwrap(x))
        m = int(v.max())
        return Tensor(jnp.asarray((np.arange(m)[None, :] < v[..., None]).astype(str(_dt.convert_dtype(dtype)))))
    return apply_op(_f, (x,), name="sequence_mask")


def class_center_sample(label, num_classes, num_samples, group=None):
    raise NotImplementedError("class_center_sample pending")


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """Ref nn/functional/vision.py affine_grid: theta [N,2,3] -> grid
    [N,H,W,2] of (x,y) sampling locations in [-1,1]."""

    if len(out_shape) != 4:
        raise NotImplementedError(
            "affine_grid: only 4-D NCHW out_shape (2x3 theta) is supported; "
            "3-D volumetric warps (3x4 theta) are not implemented")

    def _f(th):
        N = th.shape[0]
        H, W = int(out_shape[2]), int(out_shape[3])
        if align_corners:
            xs = jnp.linspace(-1.0, 1.0, W)
            ys = jnp.linspace(-1.0, 1.0, H)
        else:
            xs = (jnp.arange(W) * 2 + 1) / W - 1.0
            ys = (jnp.arange(H) * 2 + 1) / H - 1.0
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1).reshape(-1, 3)   # [H*W, 3]
        out = jnp.einsum("nij,pj->npi", th.astype(jnp.float32), base)
        return out.reshape(N, H, W, 2).astype(th.dtype)

    return apply_op(_f, (theta,), name="affine_grid")


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Ref nn/functional/vision.py grid_sample: sample x [N,C,H,W] at grid
    [N,Hg,Wg,2] of (x,y) in [-1,1].  Differentiable bilinear / nearest."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"unsupported grid_sample mode {mode!r}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(f"unsupported padding_mode {padding_mode!r}")

    def _unnormalize(coord, size):
        if align_corners:
            return (coord + 1.0) / 2.0 * (size - 1)
        return ((coord + 1.0) * size - 1.0) / 2.0

    def _reflect(c, size):
        # align_corners=True reflects about the corner-pixel CENTERS
        # (period 2*(size-1)); False reflects about the pixel EDGES
        # (period 2*size, band [-0.5, size-0.5]) — torch/paddle semantics
        if size == 1:
            return jnp.zeros_like(c)
        if align_corners:
            span = float(size - 1)
            c = jnp.abs(c) % (2.0 * span)
            return jnp.where(c > span, 2.0 * span - c, c)
        span = float(size)
        c = jnp.abs(c + 0.5) % (2.0 * span)
        c = jnp.where(c > span, 2.0 * span - c, c) - 0.5
        return jnp.clip(c, 0.0, size - 1)

    def _f(xv, gv):
        N, C, H, W = xv.shape
        gx = _unnormalize(gv[..., 0].astype(jnp.float32), W)
        gy = _unnormalize(gv[..., 1].astype(jnp.float32), H)
        if padding_mode == "reflection":
            gx = _reflect(gx, W)
            gy = _reflect(gy, H)

        def gather(yy, xx, valid_mask):
            yi = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
            xi = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
            vals = jax.vmap(lambda img, yb, xb: img[:, yb, xb])(xv, yi, xi)
            if padding_mode == "zeros":
                vals = vals * valid_mask[:, None, :, :]
            return vals  # [N, C, Hg, Wg]

        if mode == "nearest":
            yy = jnp.round(gy)
            xx = jnp.round(gx)
            valid = ((yy >= 0) & (yy <= H - 1) & (xx >= 0) & (xx <= W - 1)) \
                .astype(xv.dtype)
            return gather(yy, xx, valid).astype(xv.dtype)

        x0 = jnp.floor(gx)
        y0 = jnp.floor(gy)
        fx = (gx - x0).astype(xv.dtype)
        fy = (gy - y0).astype(xv.dtype)
        out = 0.0
        for dy, wy in ((0.0, 1 - fy), (1.0, fy)):
            for dx, wx in ((0.0, 1 - fx), (1.0, fx)):
                yy = y0 + dy
                xx = x0 + dx
                valid = ((yy >= 0) & (yy <= H - 1) & (xx >= 0)
                         & (xx <= W - 1)).astype(xv.dtype)
                out = out + gather(yy, xx, valid) * (wy * wx)[:, None]
        return out.astype(xv.dtype)

    return apply_op(_f, (x, grid), name="grid_sample")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    """Ref nn/functional/channel_shuffle — interleave channel groups."""

    def _f(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            return v.reshape(n, groups, c // groups, h, w) \
                .swapaxes(1, 2).reshape(n, c, h, w)
        n, h, w, c = v.shape
        return v.reshape(n, h, w, groups, c // groups) \
            .swapaxes(3, 4).reshape(n, h, w, c)

    return apply_op(_f, (x,), name="channel_shuffle")


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    """Ref nn/functional/temporal_shift (TSM): shift a fraction of channels
    one step along the segment (time) axis."""

    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"unsupported data_format {data_format!r}")

    def _f(v):
        if data_format == "NHWC":
            v = jnp.moveaxis(v, -1, 1)
        nt, c, h, w = v.shape
        n = nt // seg_num
        v5 = v.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        back = jnp.concatenate([v5[:, 1:, :fold], jnp.zeros_like(v5[:, :1, :fold])], 1)
        fwd = jnp.concatenate([jnp.zeros_like(v5[:, :1, fold:2 * fold]),
                               v5[:, :-1, fold:2 * fold]], 1)
        keep = v5[:, :, 2 * fold:]
        out = jnp.concatenate([back, fwd, keep], axis=2).reshape(nt, c, h, w)
        return jnp.moveaxis(out, 1, -1) if data_format == "NHWC" else out

    return apply_op(_f, (x,), name="temporal_shift")


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """Ref nn/functional/distance.py pairwise_distance."""

    def _f(a, b):
        d = a - b + epsilon
        return jnp.linalg.norm(d.astype(jnp.float32), ord=p, axis=-1,
                               keepdims=keepdim).astype(a.dtype)

    return apply_op(_f, (x, y), name="pairwise_distance")


def gather_tree(ids, parents, name=None):
    """Ref gather_tree (beam search backtrace): ids/parents [T, B, W] ->
    full beams re-threaded from the last step's parents."""

    def _f(idv, pav):
        T = idv.shape[0]

        def step(carry, t):
            beams = carry  # [B, W] current beam slot per output beam
            out = jnp.take_along_axis(idv[t], beams, axis=-1)
            nxt = jnp.take_along_axis(pav[t], beams, axis=-1)
            return nxt.astype(beams.dtype), out

        init = jnp.broadcast_to(jnp.arange(idv.shape[2], dtype=idv.dtype),
                                idv.shape[1:])
        _, outs = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
        return outs[::-1]

    return apply_op(_f, (ids, parents), name="gather_tree")


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Ref sparse_attention: attention restricted to a CSR block pattern.

    TPU-native: the CSR pattern is densified into a [S, S] mask and the
    attention runs on the MXU (structured-sparse SDPA hardware does not exist
    on TPU; for long sequences prefer flash/ring attention instead)."""
    import numpy as _np

    offs = _np.asarray(_unwrap(sparse_csr_offset))
    cols = _np.asarray(_unwrap(sparse_csr_columns))

    def _f(q, k, v):
        B, H, S, D = q.shape
        # densify per-(batch, head) patterns; a single shared pattern
        # ([S+1]-shaped offsets) broadcasts over every head
        o2 = _np.broadcast_to(offs.reshape((-1, offs.shape[-1]))
                              if offs.ndim > 1 else offs[None], None)             if False else (offs.reshape(-1, offs.shape[-1]))
        c2 = cols.reshape(-1, cols.shape[-1])
        n_pat = o2.shape[0]
        masks = _np.zeros((n_pat, S, S), _np.bool_)
        for i in range(n_pat):
            for r in range(S):
                masks[i, r, c2[i, o2[i, r]:o2[i, r + 1]]] = True
        if n_pat == 1:
            m = jnp.asarray(masks[0])[None, None]
        elif n_pat == B * H:
            m = jnp.asarray(masks).reshape(B, H, S, S)
        else:
            raise ValueError(
                f"sparse_attention: {n_pat} CSR patterns for B*H={B*H} heads")
        s = jnp.einsum("bhsd,bhtd->bhst", q, k) / jnp.sqrt(
            jnp.asarray(D, q.dtype))
        s = jnp.where(m, s, jnp.asarray(-1e30, s.dtype))
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhst,bhtd->bhsd", p, v)

    return apply_op(_f, (query, key, value), name="sparse_attention")
