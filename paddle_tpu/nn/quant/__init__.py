"""paddle.nn.quant — quantization-aware training (ref: python/paddle/nn/quant)."""
from . import functional_layers  # noqa: F401
from .quant_layers import (  # noqa: F401
    FakeQuantAbsMax,
    FakeQuantChannelWiseAbsMax,
    FakeQuantMAOutputScaleLayer,
    FakeQuantMovingAverageAbsMax,
    MAOutputScaleLayer,
    MovingAverageAbsMaxScale,
    QuantizedConv2D,
    QuantizedConv2DTranspose,
    QuantizedLinear,
)
