"""Layer wrappers over tensor functionals, so elementwise ops appear as
graph nodes a quantization pass can hook (ref: nn/quant/functional_layers.py)."""
from __future__ import annotations

from ...tensor import manipulation, math
from ..layer.layers import Layer

__all__ = []


class FloatFunctionalLayer(Layer):
    pass


class add(FloatFunctionalLayer):
    def forward(self, x, y, name=None):
        return math.add(x, y)


class subtract(FloatFunctionalLayer):
    def forward(self, x, y, name=None):
        return math.subtract(x, y)


class multiply(FloatFunctionalLayer):
    def forward(self, x, y, name=None):
        return math.multiply(x, y)


class divide(FloatFunctionalLayer):
    def forward(self, x, y, name=None):
        return math.divide(x, y)


class reshape(FloatFunctionalLayer):
    def forward(self, x, shape, name=None):
        return manipulation.reshape(x, shape)


class transpose(FloatFunctionalLayer):
    def forward(self, x, perm, name=None):
        return manipulation.transpose(x, perm)


class concat(FloatFunctionalLayer):
    def forward(self, x, axis=0, name=None):
        return manipulation.concat(x, axis)


class flatten(FloatFunctionalLayer):
    def forward(self, x, start_axis=0, stop_axis=-1, name=None):
        return manipulation.flatten(x, start_axis, stop_axis)
