"""Quantization-aware-training layers.

Ref API: python/paddle/nn/quant/quant_layers.py (FakeQuantAbsMax:47,
FakeQuantMovingAverageAbsMax:128, FakeQuantChannelWiseAbsMax:226,
MovingAverageAbsMaxScale:310, QuantizedConv2D:398, QuantizedConv2DTranspose:486,
QuantizedLinear:591, MAOutputScaleLayer:662, _get_fake_quant_type:722).

TPU-native design: fake quantization is simulated in the compute dtype with a
straight-through estimator expressed as ``x + stop_gradient(q(x) - x)`` — one
fused XLA expression, no custom kernels; moving-average scale state lives in
layer buffers updated functionally (same pattern as BatchNorm running stats).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor.tensor import Tensor, apply_op
from .. import functional as F
from ..layer.layers import Layer

__all__ = [
    "FakeQuantAbsMax",
    "FakeQuantMovingAverageAbsMax",
    "FakeQuantChannelWiseAbsMax",
    "MovingAverageAbsMaxScale",
    "QuantizedConv2D",
    "QuantizedConv2DTranspose",
    "QuantizedLinear",
    "MAOutputScaleLayer",
    "FakeQuantMAOutputScaleLayer",
]


def _fake_quant(v, scale, bits):
    """Simulated quantize-dequantize with a straight-through gradient."""
    bnt = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(jnp.abs(scale).astype(v.dtype), jnp.asarray(1e-9, v.dtype))
    q = jnp.clip(jnp.round(v / s * bnt), -bnt, bnt) * s / bnt
    return v + jax.lax.stop_gradient(q - v)


class FakeQuantAbsMax(Layer):
    """Dynamic per-tensor abs-max fake quant (scale recomputed every forward)."""

    def __init__(self, name=None, quant_bits=8, dtype="float32", quant_on_weight=False):
        super().__init__()
        self._quant_bits = quant_bits
        # exported so a deploy pass can read the calibrated scale (ref keeps a
        # persistable scale var only for weights)
        if quant_on_weight:
            self.register_buffer("scale", Tensor(jnp.zeros([], jnp.float32)))
        else:
            self.scale = None

    def forward(self, x):
        def _f(v):
            s = jnp.max(jnp.abs(v.astype(jnp.float32)))
            return _fake_quant(v, s, self._quant_bits)

        out = apply_op(_f, (x,), name="fake_quant_abs_max")
        if isinstance(self.scale, Tensor):
            self.scale.set_value(jnp.max(jnp.abs(x._value.astype(jnp.float32))))
        return out


class FakeQuantMovingAverageAbsMax(Layer):
    """Activation fake quant with an EMA of the abs-max as the scale
    (ref quant_layers.py:128: state/accum-corrected moving average)."""

    def __init__(self, name=None, moving_rate=0.9, quant_bits=8, dtype="float32"):
        super().__init__()
        self._moving_rate = moving_rate
        self._quant_bits = quant_bits
        # set by PTQ convert(): a frozen scale never resumes its EMA, even if
        # the model is put back into train() mode for QAT fine-tuning
        self._frozen = False
        self.register_buffer("scale", Tensor(jnp.zeros([], jnp.float32)))
        self.register_buffer("state", Tensor(jnp.zeros([], jnp.float32)))
        self.register_buffer("accum", Tensor(jnp.zeros([], jnp.float32)))

    def forward(self, x):
        if self.training and not self._frozen:
            r = self._moving_rate
            cur = jnp.max(jnp.abs(x._value.astype(jnp.float32)))
            state = self.state._value * r + 1.0
            accum = self.accum._value * r + cur
            scale = accum / state
            self.state.set_value(state)
            self.accum.set_value(accum)
            self.scale.set_value(scale)
        scale = self.scale

        def _f(v, s):
            return _fake_quant(v, s, self._quant_bits)

        return apply_op(_f, (x, scale), name="fake_quant_moving_avg_abs_max")


class FakeQuantChannelWiseAbsMax(Layer):
    """Per-output-channel abs-max fake quant for weights (ref :226)."""

    def __init__(self, name=None, channel_num=None, quant_bits=8, quant_axis=0,
                 dtype="float32", quant_on_weight=True):
        super().__init__()
        self._quant_bits = quant_bits
        self._quant_axis = quant_axis
        if quant_on_weight and channel_num is not None:
            self.register_buffer("scale", Tensor(jnp.zeros([channel_num], jnp.float32)))
        else:
            self.scale = None

    def forward(self, x):
        axis = self._quant_axis

        def _f(v):
            red = tuple(i for i in range(v.ndim) if i != axis)
            s = jnp.max(jnp.abs(v.astype(jnp.float32)), axis=red, keepdims=True)
            return _fake_quant(v, s, self._quant_bits)

        out = apply_op(_f, (x,), name="fake_quant_channel_wise_abs_max")
        if isinstance(self.scale, Tensor):
            red = tuple(i for i in range(x.ndim) if i != axis)
            self.scale.set_value(jnp.max(jnp.abs(x._value.astype(jnp.float32)), axis=red))
        return out


class MovingAverageAbsMaxScale(Layer):
    """Observer: records the EMA abs-max of whatever flows through, without
    altering the value (ref :310 — used to calibrate output scales)."""

    def __init__(self, name=None, moving_rate=0.9, dtype="float32"):
        super().__init__()
        self._moving_rate = moving_rate
        self.register_buffer("scale", Tensor(jnp.zeros([], jnp.float32)))
        self.register_buffer("state", Tensor(jnp.zeros([], jnp.float32)))
        self.register_buffer("accum", Tensor(jnp.zeros([], jnp.float32)))

    def forward(self, x):
        if self.training:
            r = self._moving_rate
            cur = jnp.max(jnp.abs(x._value.astype(jnp.float32)))
            state = self.state._value * r + 1.0
            accum = self.accum._value * r + cur
            self.state.set_value(state)
            self.accum.set_value(accum)
            self.scale.set_value(accum / state)
        return x


def _get_fake_quant_type(quant_type, **kwargs):
    """Factory keyed the same way as ref quant_layers.py:722."""
    call = {
        "abs_max": FakeQuantAbsMax,
        "moving_average_abs_max": FakeQuantMovingAverageAbsMax,
        "channel_wise_abs_max": FakeQuantChannelWiseAbsMax,
    }
    if quant_type not in call:
        raise ValueError(
            f"unsupported quant type {quant_type}; expected one of {sorted(call)}")
    cls = call[quant_type]
    accepted = {
        FakeQuantAbsMax: ("name", "quant_bits", "dtype", "quant_on_weight"),
        FakeQuantMovingAverageAbsMax: ("name", "moving_rate", "quant_bits", "dtype"),
        FakeQuantChannelWiseAbsMax: ("name", "channel_num", "quant_bits",
                                     "quant_axis", "dtype", "quant_on_weight"),
    }[cls]
    return cls(**{k: v for k, v in kwargs.items() if k in accepted})


class _QuantizedLayerBase(Layer):
    def _make_quanters(self, layer, weight_quantize_type, activation_quantize_type,
                       weight_bits, activation_bits, moving_rate, channel_num,
                       weight_quant_axis):
        self._fake_quant_input = _get_fake_quant_type(
            activation_quantize_type, moving_rate=moving_rate,
            quant_bits=activation_bits, quant_on_weight=False)
        self._fake_quant_weight = _get_fake_quant_type(
            weight_quantize_type, moving_rate=moving_rate, quant_bits=weight_bits,
            channel_num=channel_num, quant_axis=weight_quant_axis,
            quant_on_weight=True)


class QuantizedConv2D(_QuantizedLayerBase):
    """Wrap an ``nn.Conv2D``: fake-quant input + weight, then convolve
    (ref quant_layers.py:398)."""

    def __init__(self, layer, weight_bits=8, activation_bits=8, moving_rate=0.9,
                 weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 weight_pre_layer=None, act_pre_layer=None,
                 weight_quant_layer=None, act_quant_layer=None):
        super().__init__()
        self._conv = layer
        if weight_quant_layer is not None or act_quant_layer is not None:
            self._fake_quant_weight = (weight_quant_layer or (lambda: None))()
            self._fake_quant_input = (act_quant_layer or (lambda: None))()
        else:
            self._make_quanters(layer, weight_quantize_type, activation_quantize_type,
                                weight_bits, activation_bits, moving_rate,
                                channel_num=layer.weight.shape[0], weight_quant_axis=0)

    def forward(self, x):
        if self._fake_quant_input is not None:
            x = self._fake_quant_input(x)
        w = self._conv.weight
        if self._fake_quant_weight is not None:
            w = self._fake_quant_weight(w)
        c = self._conv
        return F.conv2d(x, w, bias=c.bias, stride=c._stride, padding=c._padding,
                        dilation=c._dilation, groups=c._groups,
                        data_format=c._data_format)


class QuantizedConv2DTranspose(_QuantizedLayerBase):
    """Ref quant_layers.py:486."""

    def __init__(self, layer, weight_bits=8, activation_bits=8, moving_rate=0.9,
                 weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 weight_pre_layer=None, act_pre_layer=None,
                 weight_quant_layer=None, act_quant_layer=None):
        super().__init__()
        self._conv = layer
        if weight_quant_layer is not None or act_quant_layer is not None:
            self._fake_quant_weight = (weight_quant_layer or (lambda: None))()
            self._fake_quant_input = (act_quant_layer or (lambda: None))()
        else:
            # transpose-conv weight layout is (in, out/groups, kh, kw): per-
            # channel scales go on axis 1
            self._make_quanters(layer, weight_quantize_type, activation_quantize_type,
                                weight_bits, activation_bits, moving_rate,
                                channel_num=layer.weight.shape[1], weight_quant_axis=1)

    def forward(self, x):
        if self._fake_quant_input is not None:
            x = self._fake_quant_input(x)
        w = self._conv.weight
        if self._fake_quant_weight is not None:
            w = self._fake_quant_weight(w)
        c = self._conv
        return F.conv2d_transpose(x, w, bias=c.bias, stride=c._stride,
                                  padding=c._padding, dilation=c._dilation,
                                  groups=c._groups, data_format=c._data_format,
                                  output_padding=getattr(c, "_output_padding", 0))


class QuantizedLinear(_QuantizedLayerBase):
    """Ref quant_layers.py:591."""

    def __init__(self, layer, weight_bits=8, activation_bits=8, moving_rate=0.9,
                 weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 weight_pre_layer=None, act_pre_layer=None,
                 weight_quant_layer=None, act_quant_layer=None):
        super().__init__()
        self._linear = layer
        if weight_quant_layer is not None or act_quant_layer is not None:
            self._fake_quant_weight = (weight_quant_layer or (lambda: None))()
            self._fake_quant_input = (act_quant_layer or (lambda: None))()
        else:
            # linear weight is (in, out): per-channel scales on the out axis
            self._make_quanters(layer, weight_quantize_type, activation_quantize_type,
                                weight_bits, activation_bits, moving_rate,
                                channel_num=layer.weight.shape[1], weight_quant_axis=1)

    def forward(self, x):
        if self._fake_quant_input is not None:
            x = self._fake_quant_input(x)
        w = self._linear.weight
        if self._fake_quant_weight is not None:
            w = self._fake_quant_weight(w)
        return F.linear(x, w, self._linear.bias)


class MAOutputScaleLayer(Layer):
    """Attach a MovingAverageAbsMaxScale observer to a layer's output (ref :662)."""

    def __init__(self, layer=None, moving_rate=0.9, name=None, dtype="float32"):
        super().__init__()
        self._layer = layer
        self._ma_output_scale = MovingAverageAbsMaxScale(name, moving_rate, dtype)

    def forward(self, *inputs, **kwargs):
        out = self._layer(*inputs, **kwargs)
        if isinstance(out, Tensor):
            return self._ma_output_scale(out)
        return out


class FakeQuantMAOutputScaleLayer(Layer):
    """Fake-quant a layer's output with a moving-average scale (ref :689)."""

    def __init__(self, layer, weight_bits=8, activation_bits=8, moving_rate=0.9,
                 name=None, *args, **kwargs):
        super().__init__()
        self._layer = layer
        self._fake_quant_output = _get_fake_quant_type(
            "moving_average_abs_max", moving_rate=moving_rate,
            quant_bits=activation_bits)

    def forward(self, *inputs, **kwargs):
        out = self._layer(*inputs, **kwargs)
        if isinstance(out, Tensor):
            return self._fake_quant_output(out)
        return out
