"""paddle.batch — wrap a sample reader into a batch reader.

Ref: python/paddle/batch.py:18 (batch()).
"""
from __future__ import annotations

__all__ = ["batch"]


def batch(reader, batch_size, drop_last=False):
    """Turn a reader of samples into a reader of lists of ``batch_size`` samples."""
    if batch_size <= 0:
        raise ValueError(f"batch_size should be a positive integer, got {batch_size}")

    def batch_reader():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batch_reader
