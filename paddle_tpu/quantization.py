"""Model-level quantization workflows over nn.quant.

Ref: python/paddle/fluid/contrib/slim/quantization/imperative/qat.py:45
(ImperativeQuantAware.quantize swaps quantizable sublayers for Quantized*
wrappers) and ptq.py (ImperativePTQ: observe activations on sample data,
then freeze scales).

TPU-native: the fake-quant math is the STE expression in nn/quant
(one fused XLA expression per tensor); this module only does the model
surgery and calibration bookkeeping.
"""
from __future__ import annotations

from .nn.layer.layers import Layer
from .nn.quant.quant_layers import (
    MovingAverageAbsMaxScale,
    QuantizedConv2D,
    QuantizedConv2DTranspose,
    QuantizedLinear,
)

__all__ = ["ImperativeQuantAware", "ImperativePTQ", "PTQConfig"]

_WRAPPERS = {
    "Conv2D": QuantizedConv2D,
    "Conv2DTranspose": QuantizedConv2DTranspose,
    "Linear": QuantizedLinear,
}


def _swap_sublayers(model, should_swap, make_wrapper):
    """Replace matching sublayers in place; returns the (mutated) model."""
    for layer in model.sublayers(include_self=True):
        for name, sub in list(layer._sub_layers.items()):
            if sub is None or isinstance(sub, (QuantizedConv2D,
                                               QuantizedConv2DTranspose,
                                               QuantizedLinear)):
                continue
            if should_swap(sub):
                layer._sub_layers[name] = make_wrapper(sub)
    return model


class ImperativeQuantAware:
    """Swap every quantizable sublayer for its fake-quant wrapper (QAT).

    After training, `save_quantized_model` exports via jit.save — the fake
    quant ops are part of the traced graph, so the saved artifact carries
    the calibrated scales.
    """

    def __init__(self, quantizable_layer_type=("Conv2D", "Linear", "Conv2DTranspose"),
                 weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 weight_bits=8, activation_bits=8, moving_rate=0.9,
                 fuse_conv_bn=False, weight_preprocess_layer=None,
                 act_preprocess_layer=None, weight_quantize_layer=None,
                 act_quantize_layer=None, onnx_format=False):
        unknown = [t for t in quantizable_layer_type if t not in _WRAPPERS]
        if unknown:
            raise ValueError(
                f"unsupported quantizable_layer_type {unknown}; "
                f"supported: {sorted(_WRAPPERS)}")
        self._types = tuple(quantizable_layer_type)
        self._kw = dict(weight_quantize_type=weight_quantize_type,
                        activation_quantize_type=activation_quantize_type,
                        weight_bits=weight_bits, activation_bits=activation_bits,
                        moving_rate=moving_rate)

    def quantize(self, model):
        """In-place sublayer swap (ref qat.py quantize)."""
        def should(sub):
            return type(sub).__name__ in self._types

        def wrap(sub):
            return _WRAPPERS[type(sub).__name__](sub, **self._kw)

        return _swap_sublayers(model, should, wrap)

    def save_quantized_model(self, model, path, input_spec=None, **config):
        from . import jit

        jit.save(model, path, input_spec=input_spec, **config)


class PTQConfig:
    """(ref ptq_config.py) — which observers to use for activations/weights."""

    def __init__(self, activation_quantizer="moving_average_abs_max",
                 weight_quantizer="abs_max", moving_rate=0.9,
                 quant_bits=8):
        self.activation_quantizer = activation_quantizer
        self.weight_quantizer = weight_quantizer
        self.moving_rate = moving_rate
        self.quant_bits = quant_bits


class _ObservedLayer(Layer):
    """Wrap a layer with input AND output observers during PTQ calibration
    (ref ptq_hooks.py: in_act_quantizer / out_act_quantizer are sampled
    separately — the frozen input scale must reflect input statistics)."""

    def __init__(self, inner, moving_rate):
        super().__init__()
        self._inner = inner
        self._in_observer = MovingAverageAbsMaxScale(moving_rate=moving_rate)
        self._observer = MovingAverageAbsMaxScale(moving_rate=moving_rate)

    def forward(self, *args, **kwargs):
        from .tensor.tensor import Tensor

        # observe the first Tensor however it was passed (positional or
        # kwarg) — a missed observation would freeze a 0.0 input scale
        observed = False
        new_args = []
        for a in args:
            if not observed and isinstance(a, Tensor):
                a = self._in_observer(a)
                observed = True
            new_args.append(a)
        if not observed:
            for k, v in kwargs.items():
                if isinstance(v, Tensor):
                    kwargs[k] = self._in_observer(v)
                    break
        out = self._inner(*new_args, **kwargs)
        if isinstance(out, Tensor):
            return self._observer(out)
        return out


class ImperativePTQ:
    """Post-training quantization: run sample batches through an observed
    model (`quantize`), then `convert` swaps in fake-quant wrappers whose
    activation scales are FROZEN to the observed values (ref ptq.py)."""

    def __init__(self, quant_config=None):
        self.cfg = quant_config or PTQConfig()

    def quantize(self, model, inplace=True):
        def should(sub):
            return type(sub).__name__ in _WRAPPERS

        def wrap(sub):
            return _ObservedLayer(sub, self.cfg.moving_rate)

        return _swap_sublayers(model, should, wrap)

    def convert(self, model, inplace=True):
        """Replace observers with fixed-scale fake-quant wrappers."""
        for layer in model.sublayers(include_self=True):
            for name, sub in list(layer._sub_layers.items()):
                if isinstance(sub, _ObservedLayer):
                    inner = sub._inner
                    wrapper = _WRAPPERS[type(inner).__name__](
                        inner,
                        weight_quantize_type=self.cfg.weight_quantizer,
                        activation_quantize_type=self.cfg.activation_quantizer,
                        weight_bits=self.cfg.quant_bits,
                        activation_bits=self.cfg.quant_bits,
                        moving_rate=self.cfg.moving_rate)
                    # freeze the INPUT-observed scale into the input quanter
                    # (ref ptq.py uses in_act_quantizer thresholds for input
                    # quantization — output stats are the wrong tensor) and
                    # mark it frozen so a later model.train() (QAT fine-tune
                    # after PTQ) cannot resume the EMA over it
                    fq = wrapper._fake_quant_input
                    if fq is not None and hasattr(fq, "scale"):
                        fq.scale.set_value(sub._in_observer.scale._value)
                        if hasattr(fq, "state"):
                            fq.state.set_value(sub._in_observer.state._value)
                            fq.accum.set_value(sub._in_observer.accum._value)
                        fq._frozen = True
                        fq.eval()
                    layer._sub_layers[name] = wrapper
        return model
