"""paddle.distribution parity (ref: python/paddle/distribution/)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor.tensor import Tensor, apply_op, _unwrap
from ..framework import random as _random


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return list(self._batch_shape)

    @property
    def event_shape(self):
        return list(self._event_shape)

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from ..tensor.math import exp

        return exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = loc if isinstance(loc, Tensor) else Tensor(jnp.asarray(loc, jnp.float32))
        self.scale = scale if isinstance(scale, Tensor) else Tensor(jnp.asarray(scale, jnp.float32))
        super().__init__(tuple(np.broadcast_shapes(self.loc.shape, self.scale.shape)))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + tuple(self._batch_shape)
        eps = jax.random.normal(_random.get_rng_key(), shape, jnp.float32)
        return Tensor(eps * self.scale._value + self.loc._value)

    def log_prob(self, value):
        def _f(v, loc, scale):
            var = scale * scale
            return -((v - loc) ** 2) / (2 * var) - jnp.log(scale) - 0.5 * math.log(2 * math.pi)

        return apply_op(_f, (value, self.loc, self.scale), name="normal_log_prob")

    def entropy(self):
        def _f(scale):
            return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(scale) + jnp.zeros(self._batch_shape)

        return apply_op(_f, (self.scale,), name="normal_entropy")

    def kl_divergence(self, other):
        def _f(l1, s1, l2, s2):
            vr = (s1 / s2) ** 2
            t1 = ((l1 - l2) / s2) ** 2
            return 0.5 * (vr + t1 - 1 - jnp.log(vr))

        return apply_op(_f, (self.loc, self.scale, other.loc, other.scale), name="normal_kl")


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = low if isinstance(low, Tensor) else Tensor(jnp.asarray(low, jnp.float32))
        self.high = high if isinstance(high, Tensor) else Tensor(jnp.asarray(high, jnp.float32))
        super().__init__(tuple(np.broadcast_shapes(self.low.shape, self.high.shape)))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + tuple(self._batch_shape)
        u = jax.random.uniform(_random.get_rng_key(), shape, jnp.float32)
        return Tensor(u * (self.high._value - self.low._value) + self.low._value)

    def log_prob(self, value):
        def _f(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            return jnp.where(inside, -jnp.log(hi - lo), -jnp.inf)

        return apply_op(_f, (value, self.low, self.high), name="uniform_log_prob")

    def entropy(self):
        return apply_op(lambda lo, hi: jnp.log(hi - lo), (self.low, self.high), name="uniform_entropy")


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = logits if isinstance(logits, Tensor) else Tensor(jnp.asarray(logits, jnp.float32))
        super().__init__(tuple(self.logits.shape[:-1]))

    def sample(self, shape=()):
        n = int(np.prod(shape)) if shape else 1
        out = jax.random.categorical(_random.get_rng_key(), self.logits._value,
                                     shape=tuple(shape) + tuple(self._batch_shape))
        return Tensor(out.astype(jnp.int64))

    def log_prob(self, value):
        def _f(logits, v):
            logp = jax.nn.log_softmax(logits, -1)
            return jnp.take_along_axis(logp, v.astype(jnp.int32)[..., None], -1)[..., 0]

        return apply_op(_f, (self.logits, value), name="categorical_log_prob")

    def probs(self, value=None):
        from ..nn.functional import softmax

        p = softmax(self.logits, axis=-1)
        if value is None:
            return p
        from ..tensor.manipulation import take_along_axis

        return take_along_axis(p, value.unsqueeze(-1), -1).squeeze(-1)

    def entropy(self):
        def _f(logits):
            logp = jax.nn.log_softmax(logits, -1)
            return -jnp.sum(jnp.exp(logp) * logp, -1)

        return apply_op(_f, (self.logits,), name="categorical_entropy")


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = probs if isinstance(probs, Tensor) else Tensor(jnp.asarray(probs, jnp.float32))
        super().__init__(tuple(self.probs_.shape))

    def sample(self, shape=()):
        out = jax.random.bernoulli(_random.get_rng_key(), self.probs_._value,
                                   tuple(shape) + tuple(self._batch_shape))
        return Tensor(out.astype(jnp.float32))

    def log_prob(self, value):
        def _f(p, v):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)

        return apply_op(_f, (self.probs_, value), name="bernoulli_log_prob")


def kl_divergence(p, q):
    if isinstance(p, Normal) and isinstance(q, Normal):
        return p.kl_divergence(q)
    if isinstance(p, Categorical) and isinstance(q, Categorical):
        def _f(lp, lq):
            a = jax.nn.log_softmax(lp, -1)
            b = jax.nn.log_softmax(lq, -1)
            return jnp.sum(jnp.exp(a) * (a - b), -1)

        return apply_op(_f, (p.logits, q.logits), name="categorical_kl")
    raise NotImplementedError(f"kl({type(p).__name__},{type(q).__name__})")
