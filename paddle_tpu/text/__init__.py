"""paddle.text datasets (ref: python/paddle/text/datasets/imdb.py, uci_housing.py).

This environment has no network egress, so instead of the reference's
download-on-first-use these loaders take an explicit local `data_file`
(the same artifact the reference downloads) — or `synthetic=True` to opt in
to generated stand-in data for smoke tests.  Passing neither is an error:
a corpus-named dataset must never silently return random numbers.
"""
from __future__ import annotations

import os
import warnings

import numpy as np

from ..io import Dataset


def _require_source(cls_name, data_file, synthetic, artifact):
    if data_file is None and not synthetic:
        raise RuntimeError(
            f"{cls_name}: no data source. Pass data_file=<path to {artifact}> "
            f"(this build cannot download), or synthetic=True to explicitly "
            f"request generated stand-in data for smoke tests.")
    if synthetic and data_file is None:
        warnings.warn(
            f"{cls_name}(synthetic=True): using GENERATED data, not the real "
            f"corpus — metrics are meaningless beyond pipeline smoke tests.",
            stacklevel=3)


class Imdb(Dataset):
    """IMDB sentiment corpus. Real mode reads the extracted aclImdb layout
    (`<root>/<mode>/{pos,neg}/*.txt`, ref imdb.py builds a cutoff-bounded
    word index the same way)."""

    def __init__(self, data_file=None, mode="train", cutoff=150, synthetic=False):
        _require_source("Imdb", data_file, synthetic, "the extracted aclImdb dir")
        if data_file is not None:
            self._load_real(data_file, mode, cutoff)
        else:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            n = 1024
            self.word_idx = {}
            self.docs = [rng.randint(2, 5000, rng.randint(20, 200)).astype(np.int64)
                         for _ in range(n)]
            self.labels = rng.randint(0, 2, n).astype(np.int64)

    def _load_real(self, root, mode, cutoff):
        split = os.path.join(root, mode)
        if not os.path.isdir(split):
            raise FileNotFoundError(
                f"Imdb: expected '{split}' with pos/ and neg/ subdirs "
                f"(the extracted aclImdb archive)")
        texts, labels = [], []
        for lbl, sub in ((0, "neg"), (1, "pos")):
            d = os.path.join(split, sub)
            for name in sorted(os.listdir(d)):
                if name.endswith(".txt"):
                    with open(os.path.join(d, name), encoding="utf8",
                              errors="ignore") as f:
                        texts.append(f.read().lower().split())
                    labels.append(lbl)
        freq: dict = {}
        for t in texts:
            for w in t:
                freq[w] = freq.get(w, 0) + 1
        # ref imdb.py: rank words by frequency, keep the top `cutoff` percentile
        vocab = sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
        self.word_idx = {w: i + 2 for i, (w, c) in enumerate(vocab) if c >= cutoff}
        unk = 1
        self.docs = [np.asarray([self.word_idx.get(w, unk) for w in t], np.int64)
                     for t in texts]
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.labels)


class UCIHousing(Dataset):
    """UCI Boston housing. Real mode reads the classic whitespace-delimited
    `housing.data` (506 rows x 14 cols; ref uci_housing.py normalizes features
    and splits 80/20 train/test)."""

    def __init__(self, data_file=None, mode="train", synthetic=False):
        _require_source("UCIHousing", data_file, synthetic, "housing.data")
        if data_file is not None:
            raw = np.loadtxt(data_file).astype(np.float32)
            if raw.ndim != 2 or raw.shape[1] != 14:
                raise ValueError(
                    f"UCIHousing: expected Nx14 housing.data, got {raw.shape}")
            feats, target = raw[:, :13], raw[:, 13:]
            mn, mx = feats.min(0), feats.max(0)
            feats = (feats - mn) / np.maximum(mx - mn, 1e-6)
            split = int(len(raw) * 0.8)
            if mode == "train":
                self.x, self.y = feats[:split], target[:split]
            else:
                self.x, self.y = feats[split:], target[split:]
        else:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            n = 404 if mode == "train" else 102
            self.x = rng.rand(n, 13).astype(np.float32)
            w = rng.rand(13).astype(np.float32)
            self.y = (self.x @ w + 0.1 * rng.rand(n)).astype(np.float32)[:, None]

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.y)
from .viterbi import viterbi_decode, ViterbiDecoder  # noqa: F401


class Imikolov(Dataset):
    """PTB-style n-gram LM dataset (ref text/datasets/imikolov.py: yields
    n-gram tuples, data_type 'NGRAM' or 'SEQ')."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, synthetic=False):
        _require_source("Imikolov", data_file, synthetic, "the simple-examples tarball")
        self.window = int(window_size)
        self.data_type = data_type
        if data_file is not None:
            with open(data_file, encoding="utf8") as f:
                sents = [ln.split() for ln in f if ln.strip()]
            from collections import Counter
            freq = Counter(w for s in sents for w in s)
            vocab = [w for w, c in sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
                     if c >= min_word_freq]
            self.word_idx = {w: i + 3 for i, w in enumerate(vocab)}
            corpus = [[self.word_idx.get(w, 0) for w in s] for s in sents]
        else:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            self.word_idx = {str(i): i for i in range(2048)}
            corpus = [list((rng.zipf(1.3, rng.randint(5, 30)) % 2046).astype(np.int64) + 2)
                      for _ in range(512 if mode == "train" else 64)]
        self.samples = []
        for s in corpus:
            s = [1] + list(s) + [2]
            if self.data_type.upper() == "SEQ":
                self.samples.append(np.asarray(s, np.int64))
            else:
                n = self.window
                for i in range(n, len(s) + 1):
                    self.samples.append(np.asarray(s[i - n:i], np.int64))

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class Movielens(Dataset):
    """MovieLens-1M rating triples (ref text/datasets/movielens.py: yields
    (user features, movie features, rating))."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1, rand_seed=0,
                 synthetic=False):
        _require_source("Movielens", data_file, synthetic, "ml-1m ratings.dat")
        rng = np.random.RandomState(rand_seed)
        if data_file is not None:
            rows = []
            with open(data_file, encoding="latin1") as f:
                for ln in f:
                    parts = ln.strip().split("::")
                    if len(parts) >= 3:
                        rows.append((int(parts[0]), int(parts[1]), float(parts[2])))
            rows = np.asarray(rows, np.float32)
        else:
            n = 2048
            rows = np.stack([rng.randint(1, 6041, n), rng.randint(1, 3953, n),
                             rng.randint(1, 6, n)], 1).astype(np.float32)
        mask = rng.rand(len(rows)) < test_ratio
        rows = rows[mask] if mode == "test" else rows[~mask]
        self.user = rows[:, 0].astype(np.int64)
        self.movie = rows[:, 1].astype(np.int64)
        self.rating = rows[:, 2:3]

    def __getitem__(self, idx):
        return self.user[idx], self.movie[idx], self.rating[idx]

    def __len__(self):
        return len(self.rating)


class Conll05st(Dataset):
    """CoNLL-2005 SRL dataset (ref text/datasets/conll05.py: yields word ids,
    predicate/context features, and BIO label ids)."""

    def __init__(self, data_file=None, mode="train", synthetic=False):
        _require_source("Conll05st", data_file, synthetic, "the conll05st test.wsj files")
        if data_file is not None:
            self._load_real(data_file, mode)
            return
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n = 256
        self.sents = [rng.randint(2, 5000, rng.randint(5, 40)).astype(np.int64)
                      for _ in range(n)]
        self.labels = [rng.randint(0, 67, len(s)).astype(np.int64) for s in self.sents]

    def _load_real(self, root, mode="train"):
        """Parse the conll05st propbank column files: `*.words` (one token
        per line, blank line between sentences) + `*.props` (column 0 the
        predicate lemma or '-', one bracketed-span column per predicate:
        '(A0*', '*', '*)' ...).  Yields one (word_ids, BIO label_ids) item
        per (sentence, predicate) pair — the reference conll05.py reader's
        shape (ref text/datasets/conll05.py).  File pairs whose stem
        contains `mode` are preferred (train.words vs test.wsj.words);
        with no mode match, ONE pair must exist (ambiguity raises)."""
        stems: dict = {}
        for name in sorted(os.listdir(root)):
            for ext in (".words", ".props"):
                if name.endswith(ext):
                    stems.setdefault(name[: -len(ext)], {})[ext] = \
                        os.path.join(root, name)
        pairs = {s: f for s, f in stems.items()
                 if ".words" in f and ".props" in f}
        if not pairs:
            raise FileNotFoundError(
                f"Conll05st: expected a *.words + *.props pair in '{root}'")
        matching = {s: f for s, f in pairs.items() if mode in s}
        if matching:
            pairs = matching
        elif len(pairs) > 1:
            raise ValueError(
                f"Conll05st: multiple corpus pairs {sorted(pairs)} and none "
                f"matches mode={mode!r}; point data_file at one split")
        stem = sorted(pairs)[0]
        words_f, props_f = pairs[stem][".words"], pairs[stem][".props"]

        def read_blocks(path):
            blocks, cur = [], []
            with open(path, encoding="utf8") as f:
                for ln in f:
                    ln = ln.rstrip("\n")
                    if not ln.strip():
                        if cur:
                            blocks.append(cur)
                            cur = []
                    else:
                        cur.append(ln.split())
                if cur:
                    blocks.append(cur)
            return blocks

        word_blocks = read_blocks(words_f)
        prop_blocks = read_blocks(props_f)
        if len(word_blocks) != len(prop_blocks):
            raise ValueError(
                f"Conll05st: {len(word_blocks)} sentences in words vs "
                f"{len(prop_blocks)} in props")
        freq: dict = {}
        for blk in word_blocks:
            for row in blk:
                freq[row[0].lower()] = freq.get(row[0].lower(), 0) + 1
        vocab = sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
        self.word_idx = {w: i + 2 for i, (w, _) in enumerate(vocab)}
        self.label_idx = {"O": 0}
        self.sents, self.labels = [], []
        for wblk, pblk in zip(word_blocks, prop_blocks):
            toks = [row[0].lower() for row in wblk]
            ids = np.asarray([self.word_idx.get(w, 1) for w in toks], np.int64)
            n_preds = max(len(row) for row in pblk) - 1
            for k in range(n_preds):
                bio, open_tag = [], None
                for row in pblk:
                    span = row[k + 1] if len(row) > k + 1 else "*"
                    tag = "O"
                    if span.startswith("("):
                        open_tag = span[1:].split("*")[0].rstrip(")")
                        tag = "B-" + open_tag
                    elif open_tag is not None:
                        tag = "I-" + open_tag
                    if span.endswith(")"):
                        open_tag = None
                    bio.append(self.label_idx.setdefault(
                        tag, len(self.label_idx)))
                self.sents.append(ids)
                self.labels.append(np.asarray(bio, np.int64))

    def __getitem__(self, idx):
        return self.sents[idx], self.labels[idx]

    def __len__(self):
        return len(self.sents)


class _WMTBase(Dataset):
    def __init__(self, cls_name, artifact, data_file, mode, src_dict_size,
                 trg_dict_size, synthetic):
        _require_source(cls_name, data_file, synthetic, artifact)
        self.src_dict_size = src_dict_size
        self.trg_dict_size = trg_dict_size
        if data_file is not None:
            pairs = []
            with open(data_file, encoding="utf8") as f:
                for ln in f:
                    parts = ln.rstrip("\n").split("\t")
                    if len(parts) == 2:
                        src = [hash(w) % (src_dict_size - 3) + 3 for w in parts[0].split()]
                        trg = [hash(w) % (trg_dict_size - 3) + 3 for w in parts[1].split()]
                        pairs.append((src, trg))
            self.pairs = pairs
        else:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            self.pairs = [
                (list(rng.randint(3, src_dict_size, rng.randint(4, 30)).astype(np.int64)),
                 list(rng.randint(3, trg_dict_size, rng.randint(4, 30)).astype(np.int64)))
                for _ in range(512 if mode == "train" else 64)]

    def __getitem__(self, idx):
        src, trg = self.pairs[idx]
        # (source ids, target ids shifted in, target ids shifted out) — the
        # seq2seq training triple the reference yields
        s = np.asarray(src, np.int64)
        t = np.asarray([1] + list(trg), np.int64)
        lbl = np.asarray(list(trg) + [2], np.int64)
        return s, t, lbl

    def __len__(self):
        return len(self.pairs)


class WMT14(_WMTBase):
    """WMT'14 en-fr translation pairs (ref text/datasets/wmt14.py)."""

    def __init__(self, data_file=None, mode="train", dict_size=30000, synthetic=False):
        super().__init__("WMT14", "a tab-separated en\\tfr file", data_file, mode,
                         dict_size, dict_size, synthetic)


class WMT16(_WMTBase):
    """WMT'16 en-de translation pairs (ref text/datasets/wmt16.py)."""

    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en", synthetic=False):
        super().__init__("WMT16", "a tab-separated en\\tde file", data_file, mode,
                         src_dict_size, trg_dict_size, synthetic)
