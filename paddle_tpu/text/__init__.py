"""paddle.text datasets (ref: python/paddle/text/) — synthetic-capable corpora."""
from __future__ import annotations

import numpy as np

from ..io import Dataset


class Imdb(Dataset):
    def __init__(self, data_file=None, mode="train", cutoff=150):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n = 1024
        self.docs = [rng.randint(2, 5000, rng.randint(20, 200)).astype(np.int64) for _ in range(n)]
        self.labels = rng.randint(0, 2, n).astype(np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.labels)


class UCIHousing(Dataset):
    def __init__(self, data_file=None, mode="train"):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n = 404 if mode == "train" else 102
        self.x = rng.rand(n, 13).astype(np.float32)
        w = rng.rand(13).astype(np.float32)
        self.y = (self.x @ w + 0.1 * rng.rand(n)).astype(np.float32)[:, None]

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.y)
