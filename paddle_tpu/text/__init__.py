"""paddle.text datasets (ref: python/paddle/text/datasets/imdb.py, uci_housing.py).

This environment has no network egress, so instead of the reference's
download-on-first-use these loaders take an explicit local `data_file`
(the same artifact the reference downloads) — or `synthetic=True` to opt in
to generated stand-in data for smoke tests.  Passing neither is an error:
a corpus-named dataset must never silently return random numbers.
"""
from __future__ import annotations

import os
import warnings

import numpy as np

from ..io import Dataset


def _require_source(cls_name, data_file, synthetic, artifact):
    if data_file is None and not synthetic:
        raise RuntimeError(
            f"{cls_name}: no data source. Pass data_file=<path to {artifact}> "
            f"(this build cannot download), or synthetic=True to explicitly "
            f"request generated stand-in data for smoke tests.")
    if synthetic and data_file is None:
        warnings.warn(
            f"{cls_name}(synthetic=True): using GENERATED data, not the real "
            f"corpus — metrics are meaningless beyond pipeline smoke tests.",
            stacklevel=3)


class Imdb(Dataset):
    """IMDB sentiment corpus. Real mode reads the extracted aclImdb layout
    (`<root>/<mode>/{pos,neg}/*.txt`, ref imdb.py builds a cutoff-bounded
    word index the same way)."""

    def __init__(self, data_file=None, mode="train", cutoff=150, synthetic=False):
        _require_source("Imdb", data_file, synthetic, "the extracted aclImdb dir")
        if data_file is not None:
            self._load_real(data_file, mode, cutoff)
        else:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            n = 1024
            self.word_idx = {}
            self.docs = [rng.randint(2, 5000, rng.randint(20, 200)).astype(np.int64)
                         for _ in range(n)]
            self.labels = rng.randint(0, 2, n).astype(np.int64)

    def _load_real(self, root, mode, cutoff):
        split = os.path.join(root, mode)
        if not os.path.isdir(split):
            raise FileNotFoundError(
                f"Imdb: expected '{split}' with pos/ and neg/ subdirs "
                f"(the extracted aclImdb archive)")
        texts, labels = [], []
        for lbl, sub in ((0, "neg"), (1, "pos")):
            d = os.path.join(split, sub)
            for name in sorted(os.listdir(d)):
                if name.endswith(".txt"):
                    with open(os.path.join(d, name), encoding="utf8",
                              errors="ignore") as f:
                        texts.append(f.read().lower().split())
                    labels.append(lbl)
        freq: dict = {}
        for t in texts:
            for w in t:
                freq[w] = freq.get(w, 0) + 1
        # ref imdb.py: rank words by frequency, keep the top `cutoff` percentile
        vocab = sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
        self.word_idx = {w: i + 2 for i, (w, c) in enumerate(vocab) if c >= cutoff}
        unk = 1
        self.docs = [np.asarray([self.word_idx.get(w, unk) for w in t], np.int64)
                     for t in texts]
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.labels)


class UCIHousing(Dataset):
    """UCI Boston housing. Real mode reads the classic whitespace-delimited
    `housing.data` (506 rows x 14 cols; ref uci_housing.py normalizes features
    and splits 80/20 train/test)."""

    def __init__(self, data_file=None, mode="train", synthetic=False):
        _require_source("UCIHousing", data_file, synthetic, "housing.data")
        if data_file is not None:
            raw = np.loadtxt(data_file).astype(np.float32)
            if raw.ndim != 2 or raw.shape[1] != 14:
                raise ValueError(
                    f"UCIHousing: expected Nx14 housing.data, got {raw.shape}")
            feats, target = raw[:, :13], raw[:, 13:]
            mn, mx = feats.min(0), feats.max(0)
            feats = (feats - mn) / np.maximum(mx - mn, 1e-6)
            split = int(len(raw) * 0.8)
            if mode == "train":
                self.x, self.y = feats[:split], target[:split]
            else:
                self.x, self.y = feats[split:], target[split:]
        else:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            n = 404 if mode == "train" else 102
            self.x = rng.rand(n, 13).astype(np.float32)
            w = rng.rand(13).astype(np.float32)
            self.y = (self.x @ w + 0.1 * rng.rand(n)).astype(np.float32)[:, None]

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.y)
from .viterbi import viterbi_decode, ViterbiDecoder  # noqa: F401
