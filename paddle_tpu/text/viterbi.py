"""Viterbi decoding for linear-chain CRFs.

Ref: python/paddle/text/viterbi_decode.py:24 (viterbi_decode op + ViterbiDecoder
layer; kernel at paddle/phi/kernels/cpu/viterbi_decode_kernel.cc).

TPU-native: one lax.scan forward pass carrying (alpha, final_alpha) and
emitting backpointers, one reverse scan for the path — static shapes, no
host loop; padding steps (t >= length) carry identity backpointers so the
backtrack needs no special casing.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..nn.layer.layers import Layer
from ..tensor.tensor import Tensor

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def _viterbi(pot, trans, lengths, with_bos_eos):
    B, L, T = pot.shape
    pot = pot.astype(jnp.float32)
    trans = trans.astype(jnp.float32)
    lengths = lengths.astype(jnp.int32)

    alpha0 = pot[:, 0]
    if with_bos_eos:
        # last row of transitions = scores out of the start tag
        alpha0 = alpha0 + trans[-1][None, :]
    # sequences shorter than 1 don't occur; final_alpha snapshots alpha at t==len-1
    final0 = alpha0

    idx_bp = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))

    def fwd(carry, t):
        (alpha, final) = carry
        # scores[b, i, j] = alpha[b, i] + trans[i, j]
        scores = alpha[:, :, None] + trans[None, :, :]
        best = jnp.max(scores, axis=1) + pot[:, t]
        bp = jnp.argmax(scores, axis=1).astype(jnp.int32)
        active = (t < lengths)[:, None]
        alpha = jnp.where(active, best, alpha)
        bp = jnp.where(active, bp, idx_bp)
        final = jnp.where((t == lengths - 1)[:, None], alpha, final)
        return (alpha, final), bp

    (alpha, final), bps = jax.lax.scan(fwd, (alpha0, final0), jnp.arange(1, L))

    if with_bos_eos:
        # second-to-last column = scores into the stop tag
        final = final + trans[:, -2][None, :]

    scores = jnp.max(final, axis=-1)
    last_tag = jnp.argmax(final, axis=-1).astype(jnp.int32)

    # backtrack: bps[s] holds the argmax of the transition t=s -> t=s+1
    def bwd(tag, bp_t):
        prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
        return prev, prev

    _, rev_tags = jax.lax.scan(bwd, last_tag, bps, reverse=True)
    path = jnp.concatenate([rev_tags, last_tag[None, :]], axis=0).T  # [B, L]
    # zero out padding region (t >= length), matching fixed-shape output
    tpos = jnp.arange(L, dtype=jnp.int32)[None, :]
    path = jnp.where(tpos < lengths[:, None], path, 0)
    return scores, path.astype(jnp.int64)


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Highest-scoring tag sequence under emission `potentials` [B, L, T] and
    `transition_params` [T, T] (ref viterbi_decode.py:24).

    Returns (scores [B] float32, path [B, L] int64); positions past each
    sequence's `lengths` are 0 in the path.
    """
    pot = potentials._value if isinstance(potentials, Tensor) else jnp.asarray(potentials)
    trans = (transition_params._value if isinstance(transition_params, Tensor)
             else jnp.asarray(transition_params))
    lens = lengths._value if isinstance(lengths, Tensor) else jnp.asarray(lengths)
    scores, path = _viterbi(pot, trans, lens, bool(include_bos_eos_tag))
    s = Tensor(scores)
    p = Tensor(path)
    s.stop_gradient = True
    p.stop_gradient = True
    return s, p


class ViterbiDecoder(Layer):
    """Layer wrapper (ref viterbi_decode.py:92)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
