"""Fleet scraper: Prometheus text-format parser + multi-target HTTP poller.

PR 5 put the registry on the network (`/metrics`); this module is the first
CONSUMER of that exposition — the sense half of the alerting plane's
sense -> decide -> act loop (ISSUE 7).  Two layers:

- ``parse_prometheus(text)`` — the exact inverse of
  ``metrics.render_prometheus()``: HELP/TYPE comments, label escaping
  (``\\`` / ``\"`` / ``\\n``), and histogram ``_bucket``/``_sum``/``_count``
  sample families reassembled into one histogram family.  The round-trip
  property ``parse_prometheus(render_prometheus()) == snapshot()`` holds for
  the full README catalogue (tests/test_alerting.py).
- ``Scraper`` — polls N ``/metrics`` targets concurrently with a PER-TARGET
  deadline on a monotonic clock, bounded retry, and staleness tracking.
  One slow or dead target can never block the others: each target is
  fetched on its own thread with a socket timeout derived from its own
  remaining deadline, and ``poll()`` joins against the same deadline.
  Self-telemetry: ``scrape_target_up{target}``,
  ``scrape_duration_seconds{target}``, ``scrape_staleness_seconds{target}``,
  ``scrape_errors_total{target}`` — the scraper watches the fleet and the
  alert engine watches the scraper with the same machinery.

Scraped samples land in a :class:`SampleSet` — a flat, label-addressable
view (every sample gains a ``target`` label, the Prometheus ``instance``
convention) that `observability.alerts` evaluates rules against.  A
``SampleSet`` can also be built from the local registry
(:meth:`SampleSet.from_registry`), so the alert engine runs identically
in-process and against a scraped fleet.

No jax / numpy imports (same contract as ``observability.metrics``).
"""
from __future__ import annotations

import http.client
import math
import threading
import time
import urllib.parse

from . import metrics as _metrics

__all__ = [
    "parse_prometheus", "SampleSet", "Scraper", "ScrapeTarget",
    "ScrapeResult", "flatten_families",
]

_M_UP = _metrics.gauge(
    "scrape_target_up",
    "1 when the last scrape of the target succeeded, 0 otherwise",
    labelnames=("target",))
_M_DURATION = _metrics.histogram(
    "scrape_duration_seconds",
    "Wall time of one target scrape (including retries)",
    labelnames=("target",))
_M_STALENESS = _metrics.gauge(
    "scrape_staleness_seconds",
    "Seconds since the last successful scrape of the target",
    labelnames=("target",))
_M_ERRORS = _metrics.counter(
    "scrape_errors_total",
    "Failed scrape attempts per target (each retry counts)",
    labelnames=("target",))


# ----------------------------------------------------------------- parsing
def _unescape_label(s: str) -> str:
    """Inverse of ``metrics._escape_label``: the only three escapes the
    exposition format defines inside label values."""
    out, i, n = [], 0, len(s)
    while i < n:
        c = s[i]
        if c == "\\" and i + 1 < n:
            nxt = s[i + 1]
            if nxt == "\\":
                out.append("\\")
            elif nxt == '"':
                out.append('"')
            elif nxt == "n":
                out.append("\n")
            else:  # unknown escape: literal backslash (Prometheus behavior)
                out.append("\\")
                out.append(nxt)
            i += 2
            continue
        out.append(c)
        i += 1
    return "".join(out)


def _unescape_help(s: str) -> str:
    """Inverse of ``metrics._escape_help`` (only ``\\`` and ``\\n``)."""
    out, i, n = [], 0, len(s)
    while i < n:
        c = s[i]
        if c == "\\" and i + 1 < n:
            nxt = s[i + 1]
            if nxt == "\\":
                out.append("\\")
                i += 2
                continue
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
        out.append(c)
        i += 1
    return "".join(out)


def _parse_labels(body: str, line: str) -> dict:
    """Parse ``k="v",...`` between braces, honoring escaped quotes."""
    labels = {}
    i, n = 0, len(body)
    while i < n:
        eq = body.index("=", i)
        key = body[i:eq].strip().lstrip(",").strip()
        if body[eq + 1] != '"':
            raise ValueError(f"unquoted label value in: {line}")
        j = eq + 2
        while j < n:
            if body[j] == "\\":
                j += 2
                continue
            if body[j] == '"':
                break
            j += 1
        else:
            raise ValueError(f"unterminated label value in: {line}")
        labels[key] = _unescape_label(body[eq + 2:j])
        i = j + 1
        while i < n and body[i] in ", ":
            i += 1
    return labels


def _parse_value(s: str) -> float:
    if s == "+Inf":
        return math.inf
    if s == "-Inf":
        return -math.inf
    return float(s)


def _scan_label_block(s: str, start: int) -> int:
    """Index of the ``}`` closing the label block opened at ``start``
    (quote- and escape-aware, so a ``}`` inside a label value never
    truncates the block).  Raises on an unterminated block."""
    j, n = start, len(s)
    in_quotes = False
    while j < n:
        c = s[j]
        if in_quotes:
            if c == "\\":
                j += 2
                continue
            if c == '"':
                in_quotes = False
        elif c == '"':
            in_quotes = True
        elif c == "}":
            return j
        j += 1
    raise ValueError(f"unterminated label block: {s}")


def _parse_exemplar(part: str, line: str):
    """``{labels} value [timestamp]`` after the exemplar marker ``# `` —
    the OpenMetrics exemplar a bucket sample may carry."""
    part = part.strip()
    if not part.startswith("{"):
        return None
    j = _scan_label_block(part, 1)
    labels = _parse_labels(part[1:j], line)
    rest = part[j + 1:].split()
    if not rest:
        return None
    return {"labels": labels, "value": _parse_value(rest[0])}


def _split_sample(line: str):
    """``name{labels} value [timestamp] [# {exemplar-labels} value]`` ->
    (name, labels, value, exemplar).  The brace scan is quote- and
    escape-aware, so a ``}`` inside a label value never truncates the
    label block."""
    brace = line.find("{")
    space = line.find(" ")
    if brace != -1 and (space == -1 or brace < space):
        name = line[:brace]
        j = _scan_label_block(line, brace + 1)
        labels = _parse_labels(line[brace + 1:j], line)
        rest = line[j + 1:].strip()
    else:
        name, _, rest = line.partition(" ")
        labels = {}
        rest = rest.strip()
    # exemplar annotation: the value/timestamp part never contains "#"
    # (labels were already consumed above), so the first " # " is the
    # OpenMetrics exemplar marker
    exemplar = None
    if " # " in rest:
        rest, _, ex_part = rest.partition(" # ")
        exemplar = _parse_exemplar(ex_part, line)
    parts = rest.split()
    if not parts:
        raise ValueError(f"sample line has no value: {line}")
    return name, labels, _parse_value(parts[0]), exemplar


def parse_prometheus(text: str) -> dict:
    """Parse exposition text into the ``MetricRegistry.snapshot()`` shape:
    ``{name: {"kind", "help", "series": [...]}}``.

    Histogram families are reassembled: a ``# TYPE f histogram`` groups the
    subsequent ``f_bucket{le=}``/``f_sum``/``f_count`` samples by their
    non-``le`` labels into ``{"labels", "sum", "count", "buckets"}`` series
    entries whose bucket keys keep the exposition's ``le`` strings
    (``"0.001"``, ``"+Inf"``) — exactly what ``snapshot()`` emits, so
    ``parse_prometheus(render_prometheus())`` round-trips sample-for-sample.
    Families never declared by a TYPE line parse as kind ``"untyped"``.
    """
    families: dict = {}
    hist_names = set()

    def family(name, kind=None, help_=None):
        fam = families.get(name)
        if fam is None:
            fam = families[name] = {"kind": "untyped", "help": "",
                                    "series": []}
        if kind is not None:
            fam["kind"] = kind
        if help_ is not None:
            fam["help"] = help_
        return fam

    def hist_series(fam, labels):
        for s in fam["series"]:
            if s["labels"] == labels:
                return s
        s = {"labels": labels, "sum": 0.0, "count": 0, "buckets": {}}
        fam["series"].append(s)
        return s

    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            family(name, help_=_unescape_help(help_text))
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            kind = kind.strip()
            family(name, kind=kind)
            if kind == "histogram":
                hist_names.add(name)
            continue
        if line.startswith("#"):
            continue  # other comments are legal exposition noise
        name, labels, value, exemplar = _split_sample(line)
        base = None
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in hist_names:
                base = name[:-len(suffix)]
                break
        if base is not None:
            fam = family(base)
            if name.endswith("_bucket"):
                le = labels.pop("le", "+Inf")
                s = hist_series(fam, labels)
                s["buckets"][le] = int(value)
                if exemplar is not None:
                    # same shape snapshot() emits, so the round-trip
                    # parse(render()) == snapshot() covers exemplars too
                    s.setdefault("exemplars", {})[le] = exemplar
            elif name.endswith("_sum"):
                hist_series(fam, labels)["sum"] = value
            else:
                hist_series(fam, labels)["count"] = int(value)
        else:
            family(name)["series"].append({"labels": labels, "value": value})
    return families


def _merge_labels(labels: dict, extra: dict) -> dict:
    """Overlay ``extra`` onto ``labels``; a colliding pre-existing label is
    preserved as ``exported_<name>`` (the Prometheus honor_labels=false
    convention) — a target that scrapes OTHER targets must not have its
    view of them collapsed into its own ``target`` identity."""
    out = dict(labels)
    for k, v in extra.items():
        if k in out and out[k] != v:
            out[f"exported_{k}"] = out.pop(k)
        out[k] = v
    return out


def flatten_families(families: dict, extra_labels=None):
    """Yield flat ``(name, labels, value)`` samples from a parsed (or
    ``snapshot()``) family dict.  Histogram families flatten back into
    ``_bucket``/``_sum``/``_count`` samples so rule selectors address them
    the way a Prometheus expression would."""
    extra = dict(extra_labels or {})
    for name, fam in families.items():
        for s in fam["series"]:
            labels = _merge_labels(s["labels"], extra)
            if "buckets" in s:
                for le, c in s["buckets"].items():
                    yield (f"{name}_bucket", {**labels, "le": str(le)},
                           float(c))
                yield f"{name}_sum", labels, float(s["sum"])
                yield f"{name}_count", labels, float(s["count"])
            else:
                yield name, labels, float(s["value"])


# --------------------------------------------------------------- sample set
class SampleSet:
    """Flat, label-addressable view of scraped/local samples.

    The alert engine's only input: ``match(name, selector)`` returns every
    sample of a family whose labels are a superset of ``selector`` — the
    subset-match semantics of a Prometheus instant selector.
    """

    def __init__(self):
        self._by_name: dict[str, list] = {}
        # family name -> [trace_id] harvested from histogram exemplars —
        # the metrics -> traces correlation alert notifications ship
        self._exemplars: dict[str, list] = {}

    def add(self, name, labels, value):
        self._by_name.setdefault(str(name), []).append(
            (dict(labels or {}), float(value)))
        return self

    def add_families(self, families, extra_labels=None):
        """Merge a parsed/snapshot family dict (histograms flattened).
        Histogram exemplar ``trace_id``s are harvested into a per-family
        side table (:meth:`exemplar_trace_ids`)."""
        for name, labels, value in flatten_families(families, extra_labels):
            self.add(name, labels, value)
        for name, fam in families.items():
            for s in fam.get("series", ()):
                for ex in (s.get("exemplars") or {}).values():
                    tid = (ex.get("labels") or {}).get("trace_id")
                    if tid:
                        ids = self._exemplars.setdefault(str(name), [])
                        if tid not in ids:
                            ids.append(tid)
        return self

    @classmethod
    def from_registry(cls, registry=None):
        """The local-process view: evaluate alert rules without a network
        hop (``run_with_recovery(alert_policy=)`` uses this)."""
        reg = registry if registry is not None else _metrics.REGISTRY
        return cls().add_families(reg.snapshot())

    def names(self):
        return set(self._by_name)

    def match(self, name, selector=None):
        """Samples of ``name`` whose labels contain every (k, v) of
        ``selector``: ``[(labels, value)]``.  Prometheus convention: a
        selector value of ``""`` matches samples where the label is ABSENT
        (e.g. ``{"exported_target": ""}`` excludes another scraper's
        re-exported series)."""
        out = []
        sel = {str(k): str(v) for k, v in (selector or {}).items()}
        for labels, value in self._by_name.get(str(name), ()):
            if all(labels.get(k, "") == v for k, v in sel.items()):
                out.append((labels, value))
        return out

    def value(self, name, selector=None, default=None):
        """Value of the single matching sample (raises on ambiguity)."""
        hits = self.match(name, selector)
        if not hits:
            return default
        if len(hits) > 1:
            raise ValueError(
                f"{name}{selector or {}} matches {len(hits)} samples; "
                f"narrow the selector or use match()")
        return hits[0][1]

    def exemplar_trace_ids(self, prefix):
        """Exemplar ``trace_id``s of every histogram family named exactly
        ``prefix`` or starting with it — ``"llm_ttft"`` finds the
        ``llm_ttft_seconds`` exemplars, so a burn-rate alert on the SLO
        series can name the traces that burned it."""
        out = []
        p = str(prefix)
        for fam, ids in self._exemplars.items():
            if fam == p or fam.startswith(p):
                for tid in ids:
                    if tid not in out:
                        out.append(tid)
        return out

    def __len__(self):
        return sum(len(v) for v in self._by_name.values())


# ------------------------------------------------------------------ scraper
class ScrapeTarget:
    """One scrape endpoint.  ``url`` may be ``host:port`` or a full
    ``http://host:port[/metrics]`` URL; ``name`` defaults to ``host:port``
    and becomes the sample's ``target`` label.  ``probe_health=True`` GETs
    ``/healthz`` before ``/metrics`` so the target's component healthchecks
    re-evaluate and their ``healthcheck_status_value`` gauges are fresh in
    the same scrape (the probe's status code is informational; a 503 target
    still serves its metrics)."""

    def __init__(self, url, name=None, probe_health=False):
        u = str(url)
        if "//" not in u:
            u = "http://" + u
        parsed = urllib.parse.urlsplit(u)
        if not parsed.hostname or not parsed.port:
            raise ValueError(f"scrape target needs host:port, got {url!r}")
        self.host = parsed.hostname
        self.port = int(parsed.port)
        self.path = parsed.path if parsed.path not in ("", "/") \
            else "/metrics"
        self.name = str(name) if name else f"{self.host}:{self.port}"
        self.probe_health = bool(probe_health)

    def __repr__(self):
        return f"ScrapeTarget({self.name!r})"


class ScrapeResult:
    """Outcome of one target scrape."""

    __slots__ = ("target", "ok", "families", "error", "duration_s",
                 "attempts", "health_status")

    def __init__(self, target, ok, families=None, error=None,
                 duration_s=0.0, attempts=0, health_status=None):
        self.target = target
        self.ok = ok
        self.families = families if families is not None else {}
        self.error = error
        self.duration_s = duration_s
        self.attempts = attempts
        self.health_status = health_status

    def to_dict(self):
        return {"target": self.target.name, "ok": self.ok,
                "error": self.error, "duration_s": round(self.duration_s, 6),
                "attempts": self.attempts,
                "families": len(self.families),
                "health_status": self.health_status}


class Scraper:
    """Poll N targets; never let one bad target starve the rest.

    Per-target budget: ``timeout_s`` on a monotonic clock covers ALL
    attempts (``retries + 1``) of that target including backoff sleeps; the
    socket timeout of each attempt is the target's remaining budget.
    ``poll()`` runs every target on its own (daemon) thread and joins
    against the same budget — a target that somehow outlives its deadline
    is reported down for this poll and its straggler thread is abandoned,
    not waited on.
    """

    def __init__(self, targets, timeout_s=5.0, retries=1,
                 retry_backoff_s=0.05, clock=time.monotonic, sleep=None):
        self.targets = [t if isinstance(t, ScrapeTarget) else ScrapeTarget(t)
                        for t in targets]
        names = [t.name for t in self.targets]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate target names: {sorted(names)}")
        self.timeout_s = float(timeout_s)
        self.retries = max(0, int(retries))
        self.retry_backoff_s = float(retry_backoff_s)
        self._clock = clock
        self._sleep = sleep if sleep is not None else time.sleep
        self._last_ok: dict[str, float] = {}   # target name -> mono stamp
        self._started = self._clock()

    def add_target(self, target):
        """Add one target to the live rotation.  The target list is
        swapped atomically (rebuilt, never mutated in place), so a
        concurrent ``poll()`` keeps iterating its own snapshot."""
        t = target if isinstance(target, ScrapeTarget) else ScrapeTarget(
            target)
        if any(x.name == t.name for x in self.targets):
            raise ValueError(f"duplicate target name {t.name!r}")
        self.targets = self.targets + [t]
        # a just-added target has never answered: date its staleness from
        # now, not from scraper construction
        self._last_ok.setdefault(t.name, self._clock())
        return t

    def remove_target(self, name):
        """Drop a target by name (atomic list swap; unknown names are a
        no-op so remove is idempotent under supervisor churn)."""
        name = str(name)
        self.targets = [t for t in self.targets if t.name != name]
        self._last_ok.pop(name, None)

    # ------------------------------------------------------------ one target
    def _fetch(self, target, path, deadline):
        remaining = deadline - self._clock()
        if remaining <= 0:
            raise TimeoutError(f"scrape budget exhausted for {target.name}")
        conn = http.client.HTTPConnection(target.host, target.port,
                                          timeout=remaining)
        try:
            # negotiate OpenMetrics (with 0.0.4 fallback): the exporter
            # attaches histogram exemplar annotations — the metrics ->
            # /tracez correlation — only to the OpenMetrics variant
            conn.request("GET", path, headers={
                "Accept": "application/openmetrics-text; version=1.0.0, "
                          "text/plain; version=0.0.4"})
            resp = conn.getresponse()
            return resp.status, resp.read().decode("utf-8", "replace")
        finally:
            conn.close()

    def scrape_one(self, target, defer_publish=False) -> ScrapeResult:
        """Scrape one target within its own deadline; updates the
        self-telemetry series unless ``defer_publish`` — ``poll()`` defers
        and publishes under its own lock, so a straggler thread it has
        abandoned can never publish up=1 after the poll already reported
        the target down."""
        t0 = self._clock()
        deadline = t0 + self.timeout_s
        error, attempts, health_status = None, 0, None
        families = None
        while attempts <= self.retries:
            attempts += 1
            try:
                if target.probe_health:
                    health_status, _ = self._fetch(
                        target, "/healthz", deadline)
                status, body = self._fetch(target, target.path, deadline)
                if status != 200:
                    raise OSError(f"HTTP {status} from {target.name}")
                families = parse_prometheus(body)
                error = None
                break
            except Exception as e:
                error = repr(e)
                _M_ERRORS.labels(target=target.name).inc()
                remaining = deadline - self._clock()
                if attempts <= self.retries and remaining > 0:
                    self._sleep(min(self.retry_backoff_s, remaining))
                if remaining <= 0:
                    break
        dur = self._clock() - t0
        ok = families is not None
        result = ScrapeResult(target, ok, families, error=error,
                              duration_s=dur, attempts=attempts,
                              health_status=health_status)
        if not defer_publish:
            self._publish(result)
        return result

    def _publish(self, result):
        """Land one result on the self-telemetry series + staleness clock."""
        name = result.target.name
        now = self._clock()
        if result.ok:
            self._last_ok[name] = now
        _M_UP.labels(target=name).set(1.0 if result.ok else 0.0)
        _M_DURATION.labels(target=name).observe(result.duration_s)
        _M_STALENESS.labels(target=name).set(self.staleness(name, now=now))

    def staleness(self, target_name, now=None) -> float:
        """Seconds since the last successful scrape (since construction when
        the target has never answered)."""
        now = self._clock() if now is None else now
        return now - self._last_ok.get(target_name, self._started)

    # ------------------------------------------------------------- the fleet
    def poll(self):
        """Scrape every target concurrently.  Returns ``(SampleSet,
        [ScrapeResult])``: scraped samples carry a ``target`` label, and the
        scraper's own up/staleness series are ALSO present as samples, so
        absence/staleness rules evaluate against the same view."""
        results: dict[str, ScrapeResult] = {}
        abandoned: set[str] = set()
        lock = threading.Lock()
        targets = self.targets  # snapshot: membership swaps mid-poll are
        #                         someone else's poll

        def worker(t):
            r = self.scrape_one(t, defer_publish=True)
            with lock:  # publish and abandon are mutually exclusive
                if t.name not in abandoned:
                    self._publish(r)
                results[t.name] = r

        threads = [threading.Thread(target=worker, args=(t,), daemon=True,
                                    name=f"scrape-{t.name}")
                   for t in targets]
        deadline = self._clock() + self.timeout_s + 0.25
        for th in threads:
            th.start()
        for th in threads:
            th.join(max(0.0, deadline - self._clock()))
        now = self._clock()
        samples = SampleSet()
        out = []
        for t in targets:
            with lock:
                r = results.get(t.name)
                if r is None:
                    # straggler blew even the joined deadline: abandoning
                    # it under the publish lock guarantees its late
                    # completion can never land up=1 over this verdict
                    abandoned.add(t.name)
            if r is None:
                r = ScrapeResult(t, False, error="scrape thread overran "
                                 "its deadline", duration_s=self.timeout_s)
                _M_UP.labels(target=t.name).set(0.0)
                _M_ERRORS.labels(target=t.name).inc()
                _M_DURATION.labels(target=t.name).observe(r.duration_s)
                # keep the staleness gauge advancing: a perpetually-
                # wedged target must look STALE to a meta-scraper, not
                # frozen at its last healthy reading
                _M_STALENESS.labels(target=t.name).set(
                    self.staleness(t.name, now=now))
            if r.ok:
                samples.add_families(r.families, {"target": t.name})
            samples.add("scrape_target_up", {"target": t.name},
                        1.0 if r.ok else 0.0)
            samples.add("scrape_staleness_seconds", {"target": t.name},
                        self.staleness(t.name, now=now))
            samples.add("scrape_duration_seconds", {"target": t.name},
                        r.duration_s)
            out.append(r)
        return samples, out
