"""HTTP telemetry endpoints: ``/metrics``, ``/healthz``, ``/varz``.

The PR-2 registry renders Prometheus text but every series still died
inside the process; this module is the missing network edge — a
stdlib-only (http.server) daemon-threaded exporter any layer can opt into:

- ``/metrics`` — Prometheus text exposition (``render_prometheus()``) with
  the canonical ``text/plain; version=0.0.4`` content type;
- ``/healthz`` — liveness plus registered component healthchecks (store
  connected, pump thread alive, last-step age ...): HTTP 200 when every
  check passes, 503 with a JSON body naming the failures otherwise — the
  k8s/load-balancer probe contract;
- ``/varz`` — the full registry snapshot as JSON (the debug endpoint);
- ``/alertz`` — the attached alert engine's rule/instance state as JSON
  (``attach_alerts``); each GET re-evaluates the engine against the local
  registry first (scrape-driven evaluation: the scraper IS the tick), so
  the payload is always current;
- ``/tracez`` — the tail-sampled request-trace store
  (``observability.tracing``): bare GET lists trace summaries + sampler
  stats, ``?trace_id=<id>`` fetches one full span tree as JSON, and
  ``?trace_id=<id>&format=chrome`` exports it as a chrome://tracing
  document — the histogram exemplars on `/metrics` resolve here.

Lifecycle: ``TelemetryServer(port=0)`` binds an ephemeral port,
``start()`` serves from a daemon thread (a forgotten exporter can never
hang interpreter exit — the tier-1 guarantee), ``stop()`` shuts the
socket down and joins the thread.  ``LLMEngine(metrics_port=...)``,
``run_with_recovery(telemetry_port=...)`` and the launcher's
``--metrics_port`` own one each; libraries embed via
``register_healthcheck``.

No jax / numpy imports (same contract as ``observability.metrics``).
"""
from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import metrics as _metrics

__all__ = ["TelemetryServer", "start_exporter", "PROMETHEUS_CONTENT_TYPE",
           "OPENMETRICS_CONTENT_TYPE"]

#: The content type Prometheus scrapers negotiate for the text format.
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: Served when the scraper's Accept header asks for OpenMetrics — the
#: only variant that carries histogram exemplar annotations (the classic
#: 0.0.4 text format has no exemplar syntax, and a stock Prometheus
#: parser would reject a 0.0.4 payload containing them).
OPENMETRICS_CONTENT_TYPE = \
    "application/openmetrics-text; version=1.0.0; charset=utf-8"

_M_SCRAPES = _metrics.counter(
    "exporter_scrapes_total",
    "HTTP requests served by the telemetry exporter",
    labelnames=("endpoint",))
_M_HTTP_ERRORS = _metrics.counter(
    "exporter_http_errors_total",
    "Exporter requests that failed (bad path or handler exception)")
_M_HEALTH = _metrics.gauge(
    "healthcheck_status_value",
    "Latest result of each registered healthcheck (1 healthy, 0 failing)",
    labelnames=("check",))


class TelemetryServer:
    """One process-local scrape endpoint over a metrics registry."""

    def __init__(self, port=0, host="127.0.0.1", registry=None,
                 recorder=None, alerts=None, traces=None):
        self.host = host
        self._requested_port = int(port)
        self.registry = registry if registry is not None \
            else _metrics.REGISTRY
        self.recorder = recorder  # optional FlightRecorder for /varz
        self.traces = traces  # Tracer or TraceStore for /tracez
                              # (None = the process-global tracer)
        self._httpd = None
        self._thread = None
        self._checks = {}  # name -> callable() -> truthy | (ok, detail)
        self._checks_lock = threading.Lock()
        # app endpoints (the router rides the telemetry server instead of
        # owning a second HTTP stack): path -> fn(query) -> JSON doc for
        # GETs, path -> fn(query, body_bytes) -> (code, doc) for POSTs
        self._json_endpoints = {}
        self._post_endpoints = {}
        self._collectors = []  # (fn, varz_key) pre-scrape refresh hooks
        self.alerts = None  # AlertEngine served on /alertz
        self._alerts_eval = True
        if alerts is not None:
            self.attach_alerts(alerts)

    def attach_alerts(self, engine, eval_on_request=True):
        """Serve ``engine`` (an ``alerts.AlertEngine``) on ``/alertz``.
        With ``eval_on_request`` every GET first evaluates the engine
        against this server's registry — each scrape is an engine tick, so
        an otherwise-idle process still advances its alert state machine."""
        self.alerts = engine
        self._alerts_eval = bool(eval_on_request)
        return self

    def register_json_endpoint(self, path, fn):
        """Serve ``fn(query_string) -> JSON-serializable doc`` on GET
        ``path`` (e.g. the router's ``/routerz``).  ``fn`` may instead
        return ``(status_code, doc)``."""
        self._json_endpoints[str(path).rstrip("/")] = fn
        return self

    def register_collect(self, fn, varz_key=None):
        """Run ``fn()`` at the top of every `/metrics` and `/varz`
        request — the pull-model refresh hook for gauges that mirror
        external state (the engine registers
        ``profiling.poll_device_memory`` here so ``hbm_*`` is current at
        scrape time, not at the last engine tick).  With ``varz_key``
        the return value is additionally embedded in the `/varz`
        document under that key.  A raising collector is skipped, never
        a 500: stale gauges beat a dead scrape."""
        self._collectors.append((fn, str(varz_key) if varz_key else None))
        return self

    def _collect(self, varz=None):
        for fn, key in self._collectors:
            try:
                out = fn()
            except Exception:
                continue
            if varz is not None and key is not None:
                varz[key] = out

    def register_post_endpoint(self, path, fn):
        """Serve ``fn(query_string, body_bytes) -> (status_code, doc)`` on
        POST ``path`` — the data-plane hook (``/admitz``, ``/cancelz``)
        that lets a replica share one port with its telemetry."""
        self._post_endpoints[str(path).rstrip("/")] = fn
        return self

    # ----------------------------------------------------------- lifecycle
    @property
    def port(self):
        return self._httpd.server_address[1] if self._httpd else None

    def pin(self):
        """Freeze the currently-bound port as the requested port, so a
        ``stop()``/``start()`` cycle (a fleet-controller restart) rebinds
        the SAME address and the replica's URL stays stable."""
        if self._httpd is not None:
            self._requested_port = self._httpd.server_address[1]
        return self

    @property
    def url(self):
        return f"http://{self.host}:{self.port}" if self._httpd else None

    def running(self):
        return self._thread is not None and self._thread.is_alive()

    def start(self):
        """Bind and serve from a daemon thread.  Idempotent."""
        if self.running():
            return self
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def do_GET(self):  # noqa: N802 (http.server API)
                server._handle(self)

            def do_POST(self):  # noqa: N802 (http.server API)
                server._handle_post(self)

            def log_message(self, *args):
                pass  # scrapes must not spam the training job's stdout

        self._httpd = ThreadingHTTPServer(
            (self.host, self._requested_port), Handler)
        self._httpd.daemon_threads = True  # scrape handlers never pin exit
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
            name="paddle-tpu-telemetry", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout=5.0):
        """Shut down the listener and join the serving thread — the clean
        shutdown that keeps tier-1 from hanging on a live socket."""
        httpd, thread = self._httpd, self._thread
        self._httpd = None
        self._thread = None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            thread.join(timeout)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # --------------------------------------------------------- healthchecks
    def register_healthcheck(self, name, fn):
        """Register ``fn`` under ``name``.  ``fn()`` returns truthy
        (healthy), falsy (failing), or an ``(ok, detail)`` pair; a raise
        counts as failing with the exception as detail."""
        with self._checks_lock:
            self._checks[str(name)] = fn
        return self

    def unregister_healthcheck(self, name):
        with self._checks_lock:
            self._checks.pop(str(name), None)

    def health(self):
        """Run every registered check: ``(all_ok, {name: {ok, detail}})``.
        Publishes each result on ``healthcheck_status_value{check=}``."""
        with self._checks_lock:
            checks = dict(self._checks)
        results, all_ok = {}, True
        for name, fn in checks.items():
            try:
                out = fn()
                ok, detail = (bool(out[0]), str(out[1])) \
                    if isinstance(out, tuple) else (bool(out), "")
            except Exception as e:
                ok, detail = False, repr(e)
            results[name] = {"ok": ok, "detail": detail}
            _M_HEALTH.labels(check=name).set(1.0 if ok else 0.0)
            all_ok = all_ok and ok
        return all_ok, results

    def _trace_source(self):
        """``(stats_source, store)`` — ``traces`` may be a ``Tracer``
        (preferred: its stats include the started counter) or a bare
        ``TraceStore``."""
        src = self.traces
        if src is None:
            from . import tracing as _tracing  # lazy: avoids import cycle

            src = _tracing.TRACER
        return src, getattr(src, "store", src)

    # ------------------------------------------------------------ handlers
    def _handle(self, req):
        path, _, query = req.path.partition("?")
        path = path.rstrip("/") or "/"
        try:
            if path == "/metrics":
                _M_SCRAPES.labels(endpoint="metrics").inc()
                self._collect()
                # content negotiation: exemplars ride ONLY on the
                # OpenMetrics variant — a 0.0.4 scraper gets clean
                # classic text it can always parse
                accept = req.headers.get("Accept") or ""
                om = "application/openmetrics-text" in accept
                text = self.registry.render_prometheus(exemplars=om)
                if om:
                    text += "# EOF\n"
                self._reply(req, 200,
                            OPENMETRICS_CONTENT_TYPE if om
                            else PROMETHEUS_CONTENT_TYPE, text.encode())
            elif path == "/healthz":
                _M_SCRAPES.labels(endpoint="healthz").inc()
                ok, results = self.health()
                body = json.dumps(
                    {"status": "ok" if ok else "unhealthy",
                     "checks": results}, sort_keys=True).encode()
                self._reply(req, 200 if ok else 503,
                            "application/json", body)
            elif path == "/varz":
                _M_SCRAPES.labels(endpoint="varz").inc()
                varz = {"metrics": None}
                self._collect(varz)
                varz["metrics"] = self.registry.snapshot()
                if self.recorder is not None:
                    varz["flight_recorder"] = {
                        "events": len(self.recorder),
                        "capacity": self.recorder.capacity,
                    }
                # tracer sampling health rides on /varz so fleetwatch can
                # see starved/overflowing trace stores without /tracez
                varz["tracing"] = self._trace_source()[0].stats()
                body = json.dumps(varz, default=repr).encode()
                self._reply(req, 200, "application/json", body)
            elif path == "/tracez":
                _M_SCRAPES.labels(endpoint="tracez").inc()
                self._handle_tracez(req, query)
            elif path == "/alertz":
                _M_SCRAPES.labels(endpoint="alertz").inc()
                if self.alerts is None:
                    doc = {"enabled": False, "alerts": []}
                else:
                    if self._alerts_eval:
                        from .scrape import SampleSet
                        self.alerts.evaluate(
                            SampleSet.from_registry(self.registry))
                    doc = {"enabled": True, **self.alerts.state(),
                           "firing": self.alerts.firing()}
                body = json.dumps(doc, default=repr).encode()
                self._reply(req, 200, "application/json", body)
            elif path in self._json_endpoints:
                _M_SCRAPES.labels(endpoint=path.lstrip("/")).inc()
                out = self._json_endpoints[path](query)
                code, doc = out if isinstance(out, tuple) else (200, out)
                self._reply(req, code, "application/json",
                            json.dumps(doc, default=repr).encode())
            else:
                _M_HTTP_ERRORS.inc()
                self._reply(req, 404, "text/plain; charset=utf-8",
                            b"not found: try /metrics /healthz /varz "
                            b"/alertz /tracez\n")
        except BrokenPipeError:
            pass  # scraper hung up mid-reply; nothing to clean up
        except Exception:
            _M_HTTP_ERRORS.inc()
            try:
                self._reply(req, 500, "text/plain; charset=utf-8",
                            b"internal error\n")
            except Exception:
                pass  # socket already gone

    def _handle_post(self, req):
        path, _, query = req.path.partition("?")
        path = path.rstrip("/") or "/"
        try:
            fn = self._post_endpoints.get(path)
            if fn is None:
                _M_HTTP_ERRORS.inc()
                self._reply(req, 404, "text/plain; charset=utf-8",
                            b"not found\n")
                return
            _M_SCRAPES.labels(endpoint=path.lstrip("/")).inc()
            length = int(req.headers.get("Content-Length") or 0)
            body = req.rfile.read(length) if length > 0 else b""
            code, doc = fn(query, body)
            self._reply(req, code, "application/json",
                        json.dumps(doc, default=repr).encode())
        except BrokenPipeError:
            pass
        except Exception:
            _M_HTTP_ERRORS.inc()
            try:
                self._reply(req, 500, "text/plain; charset=utf-8",
                            b"internal error\n")
            except Exception:
                pass

    def _handle_tracez(self, req, query):
        """`/tracez` contract: list (``?limit=N``), fetch
        (``?trace_id=<id>``), export (``&format=chrome``)."""
        src, store = self._trace_source()
        q = urllib.parse.parse_qs(query)
        tid = (q.get("trace_id") or q.get("id") or [None])[0]
        if tid is None:
            try:
                limit = int((q.get("limit") or [100])[0])
            except ValueError:
                limit = 100
            doc = {"stats": src.stats(), "traces": store.list(limit=limit)}
            self._reply(req, 200, "application/json",
                        json.dumps(doc, default=repr).encode())
            return
        trace = store.get_trace(tid)
        if trace is None:
            self._reply(req, 404, "application/json", json.dumps(
                {"error": f"unknown trace_id {tid!r} (expired from the "
                          f"bounded store, or never sampled)"}).encode())
            return
        fmt = (q.get("format") or ["json"])[0]
        doc = trace.to_chrome_trace() if fmt == "chrome" \
            else trace.to_dict()
        self._reply(req, 200, "application/json",
                    json.dumps(doc, default=repr).encode())

    @staticmethod
    def _reply(req, code, ctype, body):
        req.send_response(code)
        req.send_header("Content-Type", ctype)
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)


def start_exporter(port=0, host="127.0.0.1", registry=None, recorder=None):
    """Convenience: build + start a :class:`TelemetryServer`."""
    return TelemetryServer(port=port, host=host, registry=registry,
                           recorder=recorder).start()
